"""PRNG case matrix (reference model: heat/core/tests/test_random.py —
the reference proves its Threefry counter sequence gives identical global
streams for any rank count, correct moments, and stateful get/set
semantics; this is the same contract over jax's partitionable Threefry
plus the round-4 cached-sampler layer).
"""

import numpy as np

import heat_tpu as ht
from .base import TestCase


class TestStreamContracts(TestCase):
    def test_seed_reproducibility_per_sampler(self):
        for fn, args, kw in [
            (ht.random.rand, (9, 5), {}),
            (ht.random.randn, (9, 5), {}),
            (ht.random.randint, (0, 100), {"size": (9, 5)}),
            (ht.random.randperm, (37,), {}),
        ]:
            with self.subTest(fn=fn.__name__):
                ht.random.seed(999)
                a = fn(*args, **kw).numpy()
                ht.random.seed(999)
                b = fn(*args, **kw).numpy()
                np.testing.assert_array_equal(a, b)

    def test_split_invariance_matrix(self):
        # the core RNG contract: same seed -> same GLOBAL numbers for any
        # split (the reference's any-rank-count invariant)
        for splits in [(None, 0), (None, 1), (0, 1)]:
            with self.subTest(splits=splits):
                ht.random.seed(1234)
                a = ht.random.rand(13, 7, split=splits[0]).numpy()
                ht.random.seed(1234)
                b = ht.random.rand(13, 7, split=splits[1]).numpy()
                np.testing.assert_array_equal(a, b)

    def test_counter_advances_between_calls(self):
        ht.random.seed(7)
        a = ht.random.rand(50).numpy()
        b = ht.random.rand(50).numpy()
        self.assertFalse(np.array_equal(a, b))

    def test_get_set_state_roundtrip(self):
        ht.random.seed(42)
        ht.random.rand(10)
        state = ht.random.get_state()
        self.assertEqual(state[0], "Threefry")
        a = ht.random.rand(20).numpy()
        ht.random.set_state(state)
        b = ht.random.rand(20).numpy()
        np.testing.assert_array_equal(a, b)

    def test_set_state_validates(self):
        with self.assertRaises(ValueError):
            ht.random.set_state(("Mersenne", 0, 0))
        with self.assertRaises(ValueError):
            ht.random.set_state("not-a-tuple")


class TestSamplerDomains(TestCase):
    def test_rand_in_unit_interval(self):
        for dtype in (ht.float32, ht.float64, ht.bfloat16):
            with self.subTest(dtype=dtype):
                x = ht.random.rand(1000, dtype=dtype, split=0).numpy().astype(np.float64)
                self.assertGreaterEqual(x.min(), 0.0)
                self.assertLess(x.max(), 1.0)

    def test_randn_moments(self):
        x = ht.random.randn(200_000, split=0).numpy()
        self.assertLess(abs(x.mean()), 0.02)
        self.assertLess(abs(x.std() - 1.0), 0.02)

    def test_normal_loc_scale(self):
        x = ht.random.normal(3.0, 0.5, (100_000,), split=0).numpy()
        self.assertLess(abs(x.mean() - 3.0), 0.02)
        self.assertLess(abs(x.std() - 0.5), 0.02)

    def test_randint_bounds_matrix(self):
        for low, high in [(0, 2), (-5, 5), (100, 101), (0, 256)]:
            with self.subTest(low=low, high=high):
                x = ht.random.randint(low, high, size=(5000,), split=0).numpy()
                self.assertGreaterEqual(int(x.min()), low)
                self.assertLess(int(x.max()), high)
        # one-arg form: [0, high)
        x = ht.random.randint(7, size=(1000,)).numpy()
        self.assertGreaterEqual(int(x.min()), 0)
        self.assertLess(int(x.max()), 7)

    def test_randint_covers_small_range(self):
        x = ht.random.randint(0, 4, size=(4000,), split=0).numpy()
        self.assertEqual(set(np.unique(x).tolist()), {0, 1, 2, 3})

    def test_randint_dtype(self):
        self.assertEqual(
            ht.random.randint(0, 10, size=(5,), dtype=ht.int64).dtype, ht.int64
        )

    def test_scalar_shapes(self):
        s = ht.random.rand()
        self.assertEqual(tuple(s.shape), ())
        s2 = ht.random.randn()
        self.assertEqual(tuple(s2.shape), ())


class TestPermutations(TestCase):
    def test_randperm_is_permutation_sizes(self):
        for n in (1, 2, 13, 100, 1000):
            with self.subTest(n=n):
                p = ht.random.randperm(n).numpy()
                self.assertEqual(sorted(p.tolist()), list(range(n)))

    def test_sharded_randperm_is_permutation(self):
        p = ht.random.randperm(257, split=0)
        self.assertEqual(p.split, 0)
        self.assertEqual(sorted(p.numpy().tolist()), list(range(257)))

    def test_sharded_randperm_not_identity(self):
        p = ht.random.randperm(1000, split=0).numpy()
        self.assertGreater((p != np.arange(1000)).sum(), 900)

    def test_permutation_of_array_shuffles_rows(self):
        host = np.arange(40, dtype=np.float32).reshape(20, 2)
        x = ht.array(host, split=0)
        shuffled = ht.random.permutation(x)
        got = shuffled.numpy()
        self.assertEqual(got.shape, (20, 2))
        # rows preserved as units
        np.testing.assert_array_equal(
            np.sort(got[:, 0]), host[:, 0]
        )
        np.testing.assert_array_equal(got[:, 1] - got[:, 0], np.ones(20))

    def test_permutation_int_arg(self):
        p = ht.random.permutation(29)
        self.assertEqual(sorted(p.numpy().tolist()), list(range(29)))

    def test_shuffle_rows_shared_permutation(self):
        host_a = np.arange(60, dtype=np.float32).reshape(30, 2)
        host_b = np.arange(30, dtype=np.float32)[:, None]
        a = ht.array(host_a, split=0)
        b = ht.array(host_b, split=0)
        sa, sb = ht.random.shuffle_rows([a, b])
        ga, gb = sa.numpy(), sb.numpy()
        # the SAME permutation applied to both arrays
        np.testing.assert_array_equal(ga[:, 0] / 2.0, gb[:, 0])
        np.testing.assert_array_equal(np.sort(gb[:, 0]), host_b[:, 0])


class TestChunkedBigSampler(TestCase):
    def test_chunked_path_determinism_and_shape(self):
        # force the chunked generator (sub-f32 dtype + size over threshold is
        # impractical in a unit test; instead exercise the wrapper directly)
        from heat_tpu.core.random import _chunk_sampler, _base_uniform
        import jax
        import jax.numpy as jnp

        # patch the threshold locally by calling the builder with a shape
        # whose f32 intermediate exceeds a tiny budget
        import heat_tpu.core.random as rnd

        old = rnd._CHUNK_F32_BYTES
        rnd._CHUNK_F32_BYTES = 1024
        try:
            chunked = _chunk_sampler(_base_uniform, (300, 4), jnp.bfloat16)
            self.assertIsNotNone(chunked)
            key = jax.random.PRNGKey(0)
            a = np.asarray(chunked(key, (300, 4), jnp.bfloat16).astype(jnp.float32))
            b = np.asarray(chunked(key, (300, 4), jnp.bfloat16).astype(jnp.float32))
            np.testing.assert_array_equal(a, b)
            self.assertEqual(a.shape, (300, 4))
            self.assertGreaterEqual(a.min(), 0.0)
            self.assertLess(a.max(), 1.0)
            # all rows populated (no zero block left from the fori_loop)
            self.assertTrue((a.max(axis=1) > 0).all())
        finally:
            rnd._CHUNK_F32_BYTES = old


class TestSamplerCache(TestCase):
    def test_jit_cache_reuses_programs(self):
        # the round-4 fix: repeated calls must HIT the sampler cache (a
        # fresh jit per call recompiled every ht.random.* — 0.8 s/call on
        # a tunnel, the round-3 "lanczos" cost)
        from heat_tpu.core.random import _sampler_jit

        before = _sampler_jit.cache_info()
        ht.random.rand(64, 3, split=0)
        ht.random.rand(64, 3, split=0)
        ht.random.rand(64, 3, split=0)
        after = _sampler_jit.cache_info()
        self.assertGreaterEqual(after.hits - before.hits, 2)

    def test_factory_cache_reuses_programs(self):
        from heat_tpu.core.factories import _factory_jit

        before = _factory_jit.cache_info()
        ht.zeros((32, 4), split=0)
        ht.zeros((32, 4), split=0)
        ht.full((32, 4), 7.0, split=0)
        ht.full((32, 4), 9.0, split=0)  # different value, SAME program
        after = _factory_jit.cache_info()
        self.assertGreaterEqual(after.hits - before.hits, 2)

    def test_full_value_rides_as_operand(self):
        np.testing.assert_array_equal(
            ht.full((5,), 3, dtype=ht.int32).numpy(), np.full(5, 3, np.int32)
        )
        np.testing.assert_array_equal(
            ht.full((5,), True, dtype=ht.bool).numpy(), np.full(5, True)
        )
        np.testing.assert_allclose(
            ht.full((5,), 2.5, dtype=ht.bfloat16).numpy().astype(np.float32),
            np.full(5, 2.5, np.float32),
        )
