"""Odd-shape / unbalanced manipulations sweep vs the NumPy oracle.

The reference's deepest test file is test_manipulations.py (3,635 LoC,
heat/core/tests/) whose convention is: loop every op over split=None/0/1
and odd shapes so chunk remainders and empty shards are exercised
(SURVEY.md §4).  This is the table-driven version: one oracle runner, many
ops, shapes chosen so every split has uneven chunks on the 8-device mesh
(13, 7, 5, 3 are all non-multiples of 8).
"""

import numpy as np

import heat_tpu as ht
from .base import TestCase

A2 = np.arange(13 * 7, dtype=np.float32).reshape(13, 7)
B2 = (np.arange(13 * 7, dtype=np.float32) * 0.5).reshape(13, 7)
A3 = np.arange(5 * 3 * 4, dtype=np.float32).reshape(5, 3, 4)
V1 = np.arange(11, dtype=np.float32)

# (label, ht_fn(x...), np_fn(x...), [np input arrays])
CASES = [
    ("concat0", lambda x, y: ht.concatenate([x, y], axis=0), lambda x, y: np.concatenate([x, y], 0), [A2, B2]),
    ("concat1", lambda x, y: ht.concatenate([x, y], axis=1), lambda x, y: np.concatenate([x, y], 1), [A2, B2]),
    ("pad", lambda x: ht.pad(x, ((1, 2), (0, 3))), lambda x: np.pad(x, ((1, 2), (0, 3))), [A2]),
    ("roll", lambda x: ht.roll(x, 3, axis=0), lambda x: np.roll(x, 3, 0), [A2]),
    ("roll_flat", lambda x: ht.roll(x, -2), lambda x: np.roll(x, -2), [V1]),
    ("repeat", lambda x: ht.repeat(x, 3, axis=0), lambda x: np.repeat(x, 3, 0), [A2]),
    ("reshape", lambda x: ht.reshape(x, (7, 13)), lambda x: x.reshape(7, 13), [A2]),
    ("flatten", lambda x: ht.flatten(x), lambda x: x.reshape(-1), [A3]),
    ("flip0", lambda x: ht.flip(x, 0), lambda x: np.flip(x, 0), [A2]),
    ("fliplr", lambda x: ht.fliplr(x), np.fliplr, [A2]),
    ("flipud", lambda x: ht.flipud(x), np.flipud, [A2]),
    ("moveaxis", lambda x: ht.moveaxis(x, 0, 2), lambda x: np.moveaxis(x, 0, 2), [A3]),
    ("swapaxes", lambda x: ht.swapaxes(x, 0, 1), lambda x: np.swapaxes(x, 0, 1), [A2]),
    ("rot90", lambda x: ht.rot90(x), np.rot90, [A2]),
    ("squeeze", lambda x: ht.squeeze(ht.expand_dims(x, 1), 1), lambda x: x, [A2]),
    ("expand_dims", lambda x: ht.expand_dims(x, 0), lambda x: x[None], [A2]),
    ("stack", lambda x, y: ht.stack([x, y], axis=1), lambda x, y: np.stack([x, y], 1), [A2, B2]),
    ("hstack", lambda x, y: ht.hstack([x, y]), lambda x, y: np.hstack([x, y]), [A2, B2]),
    ("vstack", lambda x, y: ht.vstack([x, y]), lambda x, y: np.vstack([x, y]), [A2, B2]),
    ("column_stack", lambda x, y: ht.column_stack([x, y]), lambda x, y: np.column_stack([x, y]), [V1, V1 * 2]),
    ("tile", lambda x: ht.tile(x, (2, 1)), lambda x: np.tile(x, (2, 1)), [A2]),
    ("diag_vec", lambda x: ht.diag(x), np.diag, [V1]),
    ("diagonal", lambda x: ht.diagonal(x), lambda x: np.diagonal(x), [A2]),
    ("ravel", lambda x: ht.ravel(x), np.ravel, [A3]),
]


class TestManipulationsOddShapes(TestCase):
    def test_sweep_all_splits(self):
        for label, ht_fn, np_fn, inputs in CASES:
            expected = np_fn(*inputs)
            for split in [None] + list(range(inputs[0].ndim)):
                args = [ht.array(a, split=split if split is not None and split < a.ndim else None) for a in inputs]
                try:
                    got = ht_fn(*args)
                    self.assert_array_equal(got, expected)
                except AssertionError as exc:
                    raise AssertionError(f"{label} split={split}: {exc}")

    def test_split_list_ops(self):
        for split in [None, 0, 1]:
            x = ht.array(A2, split=split)
            for parts, axis in ((len(np.array_split(A2, 3, 0)), 0),):
                got = ht.vsplit(x, [4, 9])
                exp = np.vsplit(A2, [4, 9])
                self.assertEqual(len(got), len(exp))
                for g, e in zip(got, exp):
                    self.assert_array_equal(g, e)
            got = ht.hsplit(x, [2, 5])
            for g, e in zip(got, np.hsplit(A2, [2, 5])):
                self.assert_array_equal(g, e)

    def test_dsplit(self):
        for split in [None, 0, 2]:
            x = ht.array(A3, split=split)
            got = ht.dsplit(x, 2)
            for g, e in zip(got, np.dsplit(A3, 2)):
                self.assert_array_equal(g, e)

    def test_topk_split_and_unsplit(self):
        rng = np.random.default_rng(0)
        D = rng.standard_normal((13, 7)).astype(np.float32)
        for split in [None, 0, 1]:
            x = ht.array(D, split=split)
            v, i = ht.topk(x, 3, dim=1)
            exp = np.sort(D, axis=1)[:, ::-1][:, :3]
            np.testing.assert_allclose(v.numpy(), exp, rtol=1e-6)
            np.testing.assert_array_equal(
                np.take_along_axis(D, i.numpy(), 1), v.numpy()
            )

    def test_resplit_roundtrip_odd(self):
        x = ht.array(A2, split=0)
        y = ht.resplit(x, 1)
        self.assertEqual(y.split, 1)
        z = ht.resplit(y, None)
        self.assertIsNone(z.split)
        w = ht.resplit(z, 0)
        self.assert_array_equal(w, A2)

    def test_unbalanced_input_via_slicing(self):
        # the reference creates unbalanced arrays by slicing; our GSPMD
        # layout rebalances — the logical content must be unaffected
        x = ht.array(np.arange(29, dtype=np.float32), split=0)
        y = x[3:20]
        self.assertEqual(y.shape, (17,))
        got = ht.concatenate([y, y], axis=0)
        exp = np.concatenate([np.arange(3, 20)] * 2).astype(np.float32)
        self.assert_array_equal(got, exp)

    def test_empty_shard_ops(self):
        # 3 rows over 8 devices: five shards empty
        x = ht.array(np.arange(9, dtype=np.float32).reshape(3, 3), split=0)
        self.assert_array_equal(ht.concatenate([x, x], axis=0),
                                np.concatenate([np.arange(9).reshape(3, 3)] * 2))
        v, _ = ht.sort(x, axis=0)
        self.assert_array_equal(v, np.sort(np.arange(9, dtype=np.float32).reshape(3, 3), 0))
