"""Test bootstrap: force an 8-device virtual CPU mesh.

The reference runs its whole suite under ``mpirun -n 3/4 pytest``
(.github/workflows/ci.yaml:55-56). The TPU-native equivalent (SURVEY.md §4)
is a forced multi-device CPU backend: every test sees a real 8-way mesh and
real XLA collectives, no mocks.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
