"""Test bootstrap: force an 8-device virtual CPU mesh.

The reference runs its whole suite under ``mpirun -n 3/4 pytest``
(.github/workflows/ci.yaml:55-56). The TPU-native equivalent (SURVEY.md §4)
is a forced multi-device CPU backend: every test sees a real 8-way mesh and
real XLA collectives, no mocks.
"""

import faulthandler
import os

# A native crash (XLA abort, runtime segfault) must leave a traceback, not
# a truncated "Fatal Python error" with no frames (round-4 VERDICT weak #5:
# one full-suite death was unattributable because nothing captured the
# faulting stack).  pytest's own faulthandler plugin covers test bodies;
# enabling it here covers collection and interpreter teardown too.
faulthandler.enable()

# mesh size override (scripts/ci.sh runs a 4-device leg, the reference's
# `-n 3` AND `-n 4` convention); default stays the 8-way mesh.  Validate
# here: an unparsable value would otherwise surface as an opaque XLA
# flag-parse abort at jax init, far from the actual mistake.
try:
    _N_DEVICES = int(os.environ.get("HEAT_TEST_DEVICES", "8"))
    if _N_DEVICES < 1:
        raise ValueError
except ValueError:
    raise SystemExit(
        f"HEAT_TEST_DEVICES must be a positive integer, got "
        f"{os.environ.get('HEAT_TEST_DEVICES')!r}"
    )
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_N_DEVICES}"
)
os.environ["JAX_PLATFORMS"] = "cpu"

# suite determinism: the self-tuning plane (core/autotune.py, default on)
# measures wall clocks and flips dispatch on whatever this box's scheduler
# happened to time — counter-law tests need today's static env-knob
# dispatch bit-for-bit.  Autotune's own tests opt back in explicitly
# (autotune.set_enabled(True)); an operator exporting HEAT_TPU_AUTOTUNE
# still wins over this default.
os.environ.setdefault("HEAT_TPU_AUTOTUNE", "off")

import jax

jax.config.update("jax_platforms", "cpu")
