"""Tutorials must run top to bottom (round 5; VERDICT r4 #8).

Extracts every ```python block from docs/tutorial_30_minutes.md,
docs/tutorial_clustering.md, and docs/tutorial_training.md and executes them in order in one shared
namespace per document — the markdown IS the test vector, so a doc edit
that breaks a snippet fails CI, and a new user can paste any prefix of a
tutorial and have it work.
"""

import os
import re

from .base import TestCase

DOCS = os.path.join(os.path.dirname(__file__), "..", "docs")


def python_blocks(path):
    text = open(path, encoding="utf-8").read()
    return re.findall(r"```python\n(.*?)```", text, re.S)


class TestTutorials(TestCase):
    def _run_doc(self, name):
        blocks = python_blocks(os.path.join(DOCS, name))
        self.assertGreater(len(blocks), 3, f"{name} lost its code blocks")
        ns = {}
        for i, block in enumerate(blocks):
            try:
                exec(compile(block, f"{name}[block {i}]", "exec"), ns)
            except Exception as e:
                self.fail(f"{name} block {i} failed: {e}\n---\n{block}")

    def test_tutorial_30_minutes(self):
        self._run_doc("tutorial_30_minutes.md")

    def test_tutorial_clustering(self):
        self._run_doc("tutorial_clustering.md")

    def test_tutorial_training(self):
        self._run_doc("tutorial_training.md")

    def test_quick_start_go_sparse(self):
        """quick_start.md section 17 ("Go sparse") executes top to
        bottom — the residency-ratio and zero-densification claims in
        the doc are live assertions, not prose."""
        from heat_tpu.core import telemetry

        text = open(os.path.join(DOCS, "quick_start.md"), encoding="utf-8").read()
        m = re.search(r"## 17\. Go sparse\n(.*?)\n## 18\.", text, re.S)
        self.assertIsNotNone(m, "quick_start.md lost its 'Go sparse' section")
        blocks = re.findall(r"```python\n(.*?)```", m.group(1), re.S)
        self.assertGreaterEqual(len(blocks), 4, "Go sparse lost its code blocks")
        prev_level = telemetry.set_level("off")
        try:
            ns = {}
            for i, block in enumerate(blocks):
                try:
                    exec(compile(block, f"quick_start.md[sparse block {i}]", "exec"), ns)
                except Exception as e:
                    self.fail(f"Go sparse block {i} failed: {e}\n---\n{block}")
        finally:
            telemetry.set_level(prev_level)
            telemetry.clear_events()

    def test_quick_start_stream(self):
        """quick_start.md section 18 ("Stream what doesn't fit in HBM")
        executes top to bottom — the centroid-parity and
        peak-under-budget claims in the doc are live assertions, not
        prose."""
        from heat_tpu.core import memtrack, telemetry

        text = open(os.path.join(DOCS, "quick_start.md"), encoding="utf-8").read()
        m = re.search(
            r"## 18\. Stream what doesn't fit in HBM\n(.*?)\n## 19\.",
            text, re.S,
        )
        self.assertIsNotNone(m, "quick_start.md lost its streaming section")
        blocks = re.findall(r"```python\n(.*?)```", m.group(1), re.S)
        self.assertGreaterEqual(len(blocks), 2, "streaming section lost its code blocks")
        prev_level = telemetry.set_level("off")
        try:
            ns = {}
            for i, block in enumerate(blocks):
                try:
                    exec(compile(block, f"quick_start.md[stream block {i}]", "exec"), ns)
                except Exception as e:
                    self.fail(f"Stream block {i} failed: {e}\n---\n{block}")
        finally:
            telemetry.set_level(prev_level)
            telemetry.clear_events()
            memtrack.reset()
