"""Monitoring harness (SURVEY.md §5 — perun-equivalent in-tree)."""

import io
import json

import heat_tpu as ht
from heat_tpu.utils import monitor

from .base import TestCase


class TestMonitor(TestCase):
    def setUp(self):
        monitor.reset()

    def test_decorator_records_wall_time(self):
        @monitor.monitor(emit=False)
        def work():
            return (ht.random.randn(64, 64, split=0) @ ht.random.randn(64, 64)).larray

        work()
        work()
        entries = monitor.measurements()
        self.assertEqual(len(entries), 2)
        self.assertEqual(entries[0]["name"], "work")
        self.assertGreater(entries[0]["wall_s"], 0.0)

    def test_report_json_lines(self):
        @monitor.monitor(name="labelled", emit=False)
        def work():
            return None

        work()
        buf = io.StringIO()
        monitor.report(file=buf)
        lines = [json.loads(line) for line in buf.getvalue().splitlines()]
        self.assertEqual(lines[0]["name"], "labelled")

    def test_reset(self):
        @monitor.monitor(emit=False)
        def work():
            return None

        work()
        monitor.reset()
        self.assertEqual(monitor.measurements(), [])
