"""Linear algebra tests (reference models: heat/core/linalg/tests/
test_basics.py — full matmul split matrix — and test_qr.py)."""

import numpy as np

import heat_tpu as ht
from .base import TestCase


class TestMatmul(TestCase):
    def test_matmul_split_matrix(self):
        """The reference tests every (a.split, b.split) case of its dispatch
        table (test_basics.py, 2155 LoC); here the table is GSPMD but the
        contract is identical."""
        rng = np.random.default_rng(101)
        da = rng.random((17, 13)).astype(np.float32)
        db = rng.random((13, 11)).astype(np.float32)
        expected = da @ db
        for sa in (None, 0, 1):
            for sb in (None, 0, 1):
                a, b = ht.array(da, split=sa), ht.array(db, split=sb)
                r = ht.matmul(a, b)
                self.assert_array_equal(r, expected, rtol=1e-4)
        self.assertEqual(ht.matmul(ht.array(da, split=0), ht.array(db)).split, 0)
        self.assertEqual(ht.matmul(ht.array(da), ht.array(db, split=1)).split, 1)

    def test_matmul_operator(self):
        rng = np.random.default_rng(103)
        da = rng.random((8, 6)).astype(np.float32)
        db = rng.random((6, 4)).astype(np.float32)
        r = ht.array(da, split=0) @ ht.array(db, split=0)
        self.assert_array_equal(r, da @ db, rtol=1e-4)

    def test_dot_vdot_outer(self):
        rng = np.random.default_rng(107)
        va = rng.random(50).astype(np.float32)
        vb = rng.random(50).astype(np.float32)
        a, b = ht.array(va, split=0), ht.array(vb, split=0)
        self.assertAlmostEqual(float(ht.dot(a, b)), float(va @ vb), places=3)
        self.assertAlmostEqual(float(ht.vdot(a, b)), float(np.vdot(va, vb)), places=3)
        self.assert_array_equal(ht.outer(a, b), np.outer(va, vb), rtol=1e-5)

    def test_transpose_tril_triu(self):
        data = np.random.default_rng(109).random((6, 4)).astype(np.float32)
        for split in (None, 0, 1):
            x = ht.array(data, split=split)
            t = x.T
            self.assert_array_equal(t, data.T)
            if split is not None:
                self.assertEqual(t.split, 1 - split)
            self.assert_array_equal(ht.tril(x), np.tril(data))
            self.assert_array_equal(ht.triu(x, 1), np.triu(data, 1))

    def test_norm_trace(self):
        data = np.random.default_rng(113).random((5, 5)).astype(np.float32)
        x = ht.array(data, split=0)
        self.assertAlmostEqual(float(ht.norm(x)), float(np.linalg.norm(data)), places=4)
        self.assertAlmostEqual(float(ht.trace(x)), float(np.trace(data)), places=4)
        v = ht.array(data[0], split=0)
        self.assertAlmostEqual(
            float(ht.vector_norm(v)), float(np.linalg.norm(data[0])), places=4
        )

    def test_det_inv(self):
        data = np.random.default_rng(127).random((4, 4)).astype(np.float64) + 2 * np.eye(4)
        x = ht.array(data, split=0)
        self.assertAlmostEqual(float(ht.linalg.det(x)), float(np.linalg.det(data)), places=4)
        self.assert_array_equal(ht.linalg.inv(x), np.linalg.inv(data), rtol=1e-4, atol=1e-6)


class TestQR(TestCase):
    def test_tsqr_tall_skinny(self):
        """split=0 tall-skinny path — the TSQR tree (reference: qr.py split=0
        tiled path)."""
        rng = np.random.default_rng(131)
        data = rng.random((64, 6)).astype(np.float64)
        x = ht.array(data, split=0)
        q, r = ht.linalg.qr(x)
        self.assertEqual(q.split, 0)
        qn, rn = q.numpy(), r.numpy()
        # reconstruction
        np.testing.assert_allclose(qn @ rn, data, rtol=1e-8, atol=1e-8)
        # orthonormality
        np.testing.assert_allclose(qn.T @ qn, np.eye(6), atol=1e-8)
        # R upper-triangular with non-negative diagonal
        np.testing.assert_allclose(rn, np.triu(rn), atol=1e-10)
        self.assertTrue((np.diag(rn) >= 0).all())

    def test_qr_replicated_and_split1(self):
        rng = np.random.default_rng(137)
        data = rng.random((20, 12)).astype(np.float64)
        for split in (None, 1):
            x = ht.array(data, split=split)
            q, r = ht.linalg.qr(x)
            np.testing.assert_allclose(q.numpy() @ r.numpy(), data, rtol=1e-8, atol=1e-8)

    def test_cholesky_qr2_tall_path(self):
        """Replicated tall-skinny inputs take the CholeskyQR2 MXU path; it
        must deliver working-precision orthogonality."""
        rng = np.random.default_rng(141)
        data = rng.standard_normal((512, 16)).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(data))
        qn, rn = q.numpy(), r.numpy()
        np.testing.assert_allclose(qn @ rn, data, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(qn.T @ qn, np.eye(16), atol=1e-4)
        np.testing.assert_allclose(rn, np.triu(rn), atol=1e-5)
        self.assertTrue((np.diag(rn) > 0).all())

    def test_qr_ill_conditioned_falls_back(self):
        """cond(A)² overflows the float32 Gram matrix; qr must detect the
        failed Cholesky and still return an accurate factorization."""
        rng = np.random.default_rng(143)
        u, _ = np.linalg.qr(rng.standard_normal((256, 8)))
        v, _ = np.linalg.qr(rng.standard_normal((8, 8)))
        s = np.logspace(0, -7, 8)  # cond 1e7
        data = (u * s) @ v.T
        q, r = ht.linalg.qr(ht.array(data.astype(np.float32)))
        qn, rn = q.numpy(), r.numpy()
        np.testing.assert_allclose(qn @ rn, data, rtol=1e-3, atol=1e-6)
        np.testing.assert_allclose(qn.T @ qn, np.eye(8), atol=1e-3)

    def test_qr_matches_across_splits(self):
        """Same factorization regardless of distribution (sign-normalized)."""
        rng = np.random.default_rng(139)
        data = rng.random((48, 4)).astype(np.float64)
        q0, r0 = ht.linalg.qr(ht.array(data, split=0))
        q1, r1 = ht.linalg.qr(ht.array(data))
        np.testing.assert_allclose(r0.numpy(), r1.numpy(), rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(q0.numpy(), q1.numpy(), rtol=1e-6, atol=1e-8)


class TestSVD(TestCase):
    def test_tall_skinny_svd(self):
        rng = np.random.default_rng(149)
        data = rng.random((64, 5)).astype(np.float64)
        x = ht.array(data, split=0)
        u, s, v = ht.linalg.svd(x)
        np.testing.assert_allclose(
            u.numpy() @ np.diag(s.numpy()) @ v.numpy().T, data, rtol=1e-8, atol=1e-8
        )
        np.testing.assert_allclose(s.numpy(), np.linalg.svd(data, compute_uv=False), rtol=1e-8)


class TestSolvers(TestCase):
    def test_cg(self):
        rng = np.random.default_rng(151)
        n = 24
        M = rng.random((n, n))
        A = M @ M.T + n * np.eye(n)
        b = rng.random(n)
        x = ht.linalg.cg(
            ht.array(A, split=0), ht.array(b, split=0), ht.zeros((n,), dtype=ht.float64, split=0)
        )
        np.testing.assert_allclose(x.numpy(), np.linalg.solve(A, b), rtol=1e-5, atol=1e-6)

    def test_lanczos(self):
        rng = np.random.default_rng(157)
        n = 16
        M = rng.random((n, n))
        A = (M + M.T) / 2
        V, T = ht.linalg.lanczos(ht.array(A, split=0), m=n)
        Vn, Tn = V.numpy(), T.numpy()
        # V orthonormal, T tridiagonal, V T V^T ≈ A
        np.testing.assert_allclose(Vn.T @ Vn, np.eye(n), atol=1e-6)
        np.testing.assert_allclose(Vn @ Tn @ Vn.T, A, rtol=1e-4, atol=1e-5)


class TestDistributedDetInv(TestCase):
    """Round 3 (VERDICT missing #2): det/inv by fused on-device
    partial-pivoting elimination — the split matrix stays split; the
    reference's row elimination with per-pivot host sync + Bcast
    (heat/core/linalg/basics.py:160-312) becomes one fori_loop program."""

    def _mats(self, n, seed):
        rng = np.random.default_rng(seed)
        return rng.standard_normal((n, n)).astype(np.float32)

    def test_det_matches_numpy_all_splits(self):
        for n in (1, 2, 5, 17, 33):
            A = self._mats(n, n)
            want = np.linalg.det(A)
            for split in (None, 0, 1):
                got = float(ht.linalg.det(ht.array(A, split=split)))
                np.testing.assert_allclose(
                    got, want, rtol=2e-3, err_msg=f"n={n} split={split}"
                )

    def test_det_sign_from_permutation(self):
        # permutation matrices: det exactly +-1, pure pivoting exercise
        rng = np.random.default_rng(0)
        for trial in range(4):
            n = 12
            P = np.eye(n, dtype=np.float32)[rng.permutation(n)]
            want = np.linalg.det(P)
            got = float(ht.linalg.det(ht.array(P, split=0)))
            self.assertAlmostEqual(got, want, places=5)

    def test_det_singular_is_zero(self):
        A = self._mats(8, 3)
        A[:, 3] = A[:, 1] * 2.0  # rank-deficient
        got = float(ht.linalg.det(ht.array(A, split=0)))
        self.assertAlmostEqual(got, 0.0, places=2)

    def test_det_needs_pivoting(self):
        # zero leading pivot: unpivoted elimination would divide by zero
        A = np.array([[0.0, 1.0], [1.0, 0.0]], np.float32)
        got = float(ht.linalg.det(ht.array(A, split=0)))
        self.assertAlmostEqual(got, -1.0, places=5)

    def test_inv_matches_numpy_all_splits(self):
        for n in (2, 9, 31):
            A = self._mats(n, 10 + n) + np.eye(n, dtype=np.float32) * 3
            want = np.linalg.inv(A)
            for split in (None, 0, 1):
                x = ht.array(A, split=split)
                got = ht.linalg.inv(x)
                self.assertEqual(got.split, split)
                np.testing.assert_allclose(
                    got.numpy(), want, rtol=5e-3, atol=5e-4,
                    err_msg=f"n={n} split={split}",
                )
                # functional check: A @ inv(A) == I
                np.testing.assert_allclose(
                    A @ got.numpy(), np.eye(n), atol=5e-3
                )

    def test_inv_needs_pivoting(self):
        A = np.array([[0.0, 2.0], [1.0, 0.0]], np.float32)
        got = ht.linalg.inv(ht.array(A, split=0)).numpy()
        np.testing.assert_allclose(got, np.linalg.inv(A), atol=1e-5)

    def test_batched_stack_local_path(self):
        rng = np.random.default_rng(7)
        A = rng.standard_normal((3, 5, 5)).astype(np.float32)
        got = ht.linalg.det(ht.array(A))
        np.testing.assert_allclose(
            got.numpy(), np.linalg.det(A), rtol=1e-3
        )

    def test_split_matrix_stays_split_in_program(self):
        """The compiled elimination must not all-gather the matrix: the
        jaxpr works on the global sharded array (GSPMD decides per-op),
        and the OUTPUT of inv keeps the input's split."""
        A = self._mats(32, 5) + np.eye(32, dtype=np.float32) * 2
        x = ht.array(A, split=0)
        out = ht.linalg.inv(x)
        self.assertEqual(out.split, 0)
        shard_rows = {s.data.shape[0] for s in out.parray.addressable_shards}
        self.assertEqual(shard_rows, {32 // self.comm.size})


class TestQROptions(TestCase):
    """check="defer" and precision="mixed" on the CholeskyQR2 path
    (qr.py: breakdown contract + mixed-precision pass-1)."""

    def test_defer_matches_eager_when_well_conditioned(self):
        a = ht.random.random((64, 8), split=None)
        eager = ht.linalg.qr(a)
        defer = ht.linalg.qr(a, check="defer")
        np.testing.assert_allclose(
            np.asarray(defer.R.larray), np.asarray(eager.R.larray), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(defer.Q.larray), np.asarray(eager.Q.larray), rtol=1e-5
        )

    def test_defer_nan_latches_on_breakdown(self):
        # rank-deficient input: Gram is singular, Cholesky fails, and the
        # deferred path must surface NaN (never finite garbage)
        col = np.arange(40, dtype=np.float32)
        a = ht.array(np.stack([col, 2 * col, 3 * col], axis=1))
        defer = ht.linalg.qr(a, check="defer")
        self.assertFalse(bool(np.isfinite(np.asarray(defer.R.larray)).all()))
        # eager path detects it and falls back to Householder: finite R
        eager = ht.linalg.qr(a)
        self.assertTrue(bool(np.isfinite(np.asarray(eager.R.larray)).all()))

    def test_invalid_check_raises(self):
        a = ht.random.random((16, 4))
        with self.assertRaises(ValueError):
            ht.linalg.qr(a, check="lazy")
        with self.assertRaises(ValueError):
            ht.linalg.qr(a, precision="float16")

    def test_mixed_precision_orthogonality(self):
        # mixed keeps orthogonality at f32 level; reconstruction at bf16
        # working precision (the documented trade, qr.py docstring)
        rng = np.random.default_rng(3)
        host = rng.standard_normal((4096, 64)).astype(np.float32)
        a = ht.array(host)
        q, r = ht.linalg.qr(a, precision="mixed")
        qn = np.asarray(q.larray)
        rn = np.asarray(r.larray)
        orth = np.linalg.norm(np.eye(64) - qn.T @ qn)
        self.assertLess(orth, 1e-3)
        recon = np.linalg.norm(host - qn @ rn) / np.linalg.norm(host)
        self.assertLess(recon, 2e-2)
        # R upper-triangular with nonnegative diagonal
        self.assertTrue(np.allclose(rn, np.triu(rn)))
        self.assertTrue((np.diag(rn) >= 0).all())
