"""Self-tuning runtime (ISSUE 11): explore/exploit matmul dispatch,
HBM-seeded budgets, and the persisted warm-start cache.

The suite runs with ``HEAT_TPU_AUTOTUNE=off`` (conftest default — counter
laws elsewhere need today's static dispatch bit-for-bit); each test here
opts back in through the API (``autotune.set_enabled(True)``) and
restores env control on the way out.  Doctrine stays "no mocks": the
explore tests run the real ring and GSPMD programs under measurement on
the real mesh, the seeding tests drive the real ``memory_stats()``
consumer through ``FaultInjector.low_hbm`` / ``memtrack.stats_override``,
and the persistence tests round-trip real JSON files."""

import json
import os
import tempfile
import unittest

import numpy as np

import jax

import heat_tpu as ht
from heat_tpu.core import autotune, fusion, memtrack, telemetry
from heat_tpu.parallel import overlap, transport
from heat_tpu.utils import fault

from .base import TestCase

_MULTI = len(jax.local_devices()) > 1

# clears the ring threshold at S>=2: ag bps = ceil(512/S)/S... for S=8,
# kb=64 → 64*1024*4 B/step × 7 steps ≈ 1.8 MiB ≥ 1 MiB
_BIG = ((256, 512), (512, 1024))
# stays under it: bps = 32*384*4 × 7 ≈ 336 KiB
_SMALL = ((512, 256), (256, 384))


class _Tuned:
    """Scoped tuning plane: enabled via API, events level, clean
    table/counters/recorder on both sides.  The round-19 per-link wire
    arms are forced OFF here: this file pins the MATMUL site's counter
    arithmetic (explores == k, table_size == 1, ...), and a winning ring
    arm would otherwise open its own wire entries per transfer geometry
    — whose laws test_wire.py pins separately."""

    def __init__(self, level="events"):
        self.level = level

    def __enter__(self):
        from heat_tpu.core import wire

        self.prev_level = telemetry.set_level(self.level)
        self.prev_on = autotune.set_enabled(True)
        self.prev_wire = wire.set_mode("off")
        telemetry.reset_all()
        telemetry.clear_events()
        autotune.reset()
        return self

    def __exit__(self, *exc):
        from heat_tpu.core import wire

        wire.set_mode(self.prev_wire)
        autotune.set_enabled(self.prev_on)
        autotune.reset()
        telemetry.reset_all()
        telemetry.clear_events()
        telemetry.set_level(self.prev_level)
        return False


def _mm_pair(shape_a=_SMALL[0], shape_b=_SMALL[1], split=0):
    rng = np.random.default_rng(7)
    a = ht.array(rng.random(shape_a).astype(np.float32), split=split)
    b = ht.array(rng.random(shape_b).astype(np.float32), split=split)
    return a, b


def _decision_events():
    return [e for e in telemetry.events() if e["kind"] == "autotune_decision"]


class TestEnvBytes(TestCase):
    """Satellite: ONE parser for byte-sized env knobs; malformed values
    raise (transport's behavior) instead of silently defaulting
    (overlap's old bug)."""

    def test_default_and_valid(self):
        self.assertEqual(autotune.env_bytes("X_B", 123, {}), 123)
        self.assertEqual(autotune.env_bytes("X_B", 123, {"X_B": ""}), 123)
        self.assertEqual(autotune.env_bytes("X_B", 123, {"X_B": " 456 "}), 456)

    def test_malformed_raises_with_name(self):
        for bad in ("lots", "-4", "0", "1.5"):
            with self.assertRaises(ValueError) as ctx:
                autotune.env_bytes("X_B", 123, {"X_B": bad})
            self.assertIn("X_B must be a positive integer (bytes)", str(ctx.exception))

    def test_transport_knob_unchanged(self):
        # the pre-existing contract (test_guard.py) now served by the
        # shared parser
        self.assertEqual(
            transport._env_tile_bytes({"HEAT_TPU_TILE_BYTES": "1048576"}),
            1 << 20,
        )
        self.assertEqual(transport._env_tile_bytes({}), 8 << 20)

    def test_ring_min_bytes_now_raises(self):
        # the satellite fix: a typo'd threshold must surface, not silently
        # run the 1 MiB default
        os.environ["HEAT_TPU_MATMUL_RING_MIN_BYTES"] = "garbage"
        try:
            with self.assertRaises(ValueError) as ctx:
                overlap._ring_min_bytes()
            self.assertIn(
                "HEAT_TPU_MATMUL_RING_MIN_BYTES must be a positive integer "
                "(bytes)", str(ctx.exception),
            )
        finally:
            del os.environ["HEAT_TPU_MATMUL_RING_MIN_BYTES"]
        self.assertEqual(overlap._ring_min_bytes(), 1 << 20)


class TestSuggestBudget(TestCase):
    """Satellite: the one free-HBM budget formula behind transport retry,
    kmeans packing, and plan-time seeding."""

    def test_formula(self):
        free = 8 << 20
        # clamp to request / fraction of free / floor
        self.assertEqual(
            memtrack.suggest_budget(1 << 20, fraction=0.25, free=free), 1 << 20
        )
        self.assertEqual(
            memtrack.suggest_budget(4 << 20, fraction=0.25, free=free), 2 << 20
        )
        self.assertEqual(
            memtrack.suggest_budget(4 << 20, fraction=0.25, floor=3 << 20, free=free),
            3 << 20,
        )
        # headroom reserved before the fraction
        self.assertEqual(
            memtrack.suggest_budget(
                4 << 20, fraction=1.0, headroom=6 << 20, free=free
            ),
            2 << 20,
        )

    def test_matches_informed_retry_formula(self):
        # exactly transport's informed first-retry sizing (ISSUE 10)
        free, halved = 2 << 20, transport.TILE_BYTES >> 1
        want = max(
            transport.TILE_FLOOR_BYTES,
            min(halved, int(free * transport._FREE_TILE_FRACTION)),
        )
        self.assertEqual(
            memtrack.suggest_budget(
                halved, fraction=transport._FREE_TILE_FRACTION,
                floor=transport.TILE_FLOOR_BYTES, free=free,
            ),
            want,
        )

    def test_statsless_is_none(self):
        # CPU reports no memory_stats: no fake budget, callers keep their
        # static defaults
        if memtrack.min_free_bytes() is None:
            self.assertIsNone(memtrack.suggest_budget(1 << 20))

    def test_override_supplies_free(self):
        with memtrack.stats_override([
            {"device": "fake0", "bytes_limit": 100, "bytes_in_use": 60}
        ]):
            self.assertEqual(
                memtrack.suggest_budget(1000, fraction=0.5), 20
            )

    def test_kmeans_pack_budget_routes_through_helper(self):
        import jax.numpy as jnp

        from heat_tpu.cluster import kmeans as km

        arr = jnp.asarray(
            np.random.default_rng(0).random((256, 64)), dtype=jnp.bfloat16
        )
        # tight free HBM (< 1 GiB headroom): the lane-pack must decline
        with memtrack.stats_override([
            {"device": "fake0", "bytes_limit": 1 << 30, "bytes_in_use": (1 << 30) - (64 << 20)}
        ]):
            self.assertIsNone(km._pack_lanes(arr))
        # plentiful: it packs
        with memtrack.stats_override([
            {"device": "fake0", "bytes_limit": 8 << 30, "bytes_in_use": 1 << 20}
        ]):
            packed = km._pack_lanes(arr)
        self.assertIsNotNone(packed)
        self.assertEqual(packed[3:], (64, 2))


class TestExploreExploit(TestCase):
    """Tentpole site 1: both arms measured for the first K calls, winner
    sticky by steady-state min_s, lazy chains consume (never explore)."""

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_explore_then_sticky(self):
        with _Tuned():
            a, b = _mm_pair()
            k = autotune.explore_k()
            with fusion.fuse(False):
                for _ in range(k + 2):
                    out = ht.matmul(a, b)
                    _ = out.larray
            st = autotune.stats()
            self.assertEqual(st["explores"], k)
            self.assertEqual(st["cache_hits"], 2)
            self.assertEqual(st["decisions"], k + 2)
            self.assertEqual(st["table_size"], 1)
            self.assertEqual(st["resolved"], 1)
            # both arms really measured
            (key, entry), = autotune.table().items()
            self.assertGreaterEqual(len(entry["arms"]["ring"]), k)
            self.assertGreaterEqual(len(entry["arms"]["gspmd"]), k)
            self.assertIn(entry["winner"], autotune.ARMS)
            self.assertEqual(entry["best_s"], min(entry["arms"][entry["winner"]]))
            # the flight recorder saw the explores and the sticky phase
            sources = [e["source"] for e in _decision_events()]
            self.assertEqual(sources.count("explored"), k + 1)  # +1 resolution
            self.assertEqual(sources.count("cached"), 2)
            # numerics: explore returns the ring arm's result
            self.assert_array_equal(
                out, np.asarray(a.larray) @ np.asarray(b.larray), rtol=1e-4
            )

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_chain_consumes_winner_never_explores(self):
        with _Tuned():
            a, b = _mm_pair()
            # lazy chains before any winner: static prior stands, recorded
            out = ht.matmul(a, b) + 1.0
            _ = out.larray
            st = autotune.stats()
            self.assertEqual(st["explores"], 0)
            self.assertEqual(st["priors"], 1)
            # resolve a winner eagerly on the same GEMM geometry
            with fusion.fuse(False):
                for _ in range(autotune.explore_k()):
                    _ = ht.matmul(a, b).larray
            self.assertEqual(autotune.stats()["resolved"], 1)
            # the chain now lowers with the cached winner — and because the
            # autotune generation salts the fusion cache key, it REBUILDS
            # rather than reusing the prior-mode executable
            out2 = ht.matmul(a, b) + 1.0
            _ = out2.larray
            last = overlap.stats()["last"]
            self.assertEqual(last["reason"], "autotune:cached")
            chain_evs = [
                e for e in _decision_events() if e.get("site") == "chain"
            ]
            self.assertEqual(chain_evs[-1]["source"], "cached")
            self.assert_array_equal(
                out2, np.asarray(a.larray) @ np.asarray(b.larray) + 1.0,
                rtol=1e-4,
            )

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_off_restores_static_dispatch(self):
        # HEAT_TPU_AUTOTUNE=off (the conftest suite default): dispatch is
        # exactly the byte-threshold census law — no explores, no table,
        # no autotune events
        prev = telemetry.set_level("events")
        telemetry.reset_all()
        telemetry.clear_events()
        autotune.reset()
        try:
            self.assertFalse(autotune.enabled())
            big = _mm_pair(*_BIG)
            small = _mm_pair(*_SMALL)
            with fusion.fuse(False):
                for _ in range(2):
                    _ = ht.matmul(*big).larray
                    _ = ht.matmul(*small).larray
            sched = overlap.stats()["by_schedule"]
            self.assertEqual(sched["ring_ag"], 2)   # big: above threshold
            self.assertEqual(sched["gspmd"], 2)     # small: below threshold
            self.assertEqual(overlap.stats()["last"]["reason"], "below-threshold")
            st = autotune.stats()
            for c in ("decisions", "explores", "cache_hits", "priors"):
                self.assertEqual(st[c], 0, c)
            self.assertEqual(st["table_size"], 0)
            self.assertEqual(_decision_events(), [])
        finally:
            autotune.reset()
            telemetry.reset_all()
            telemetry.clear_events()
            telemetry.set_level(prev)

    def test_degradation_reexplores(self):
        # synthetic clock: a sticky winner that turns 2x slower on two
        # consecutive sampled calls goes back to explore
        with _Tuned():
            key = ("fp_degrade", "test:kind")
            for _ in range(autotune.explore_k()):
                d = autotune.decide(key, "ring")
                self.assertTrue(d.explore)
                autotune.observe(key, "ring", 0.001)
                autotune.observe(key, "gspmd", 0.002)
            self.assertEqual(autotune.winner(key), "ring")
            gen = autotune.salt()[2]
            autotune.observe(key, "ring", 0.0011)   # fine: strikes stay 0
            autotune.observe(key, "ring", 0.0030)   # strike 1
            autotune.observe(key, "ring", 0.0012)   # recovery clears it
            autotune.observe(key, "ring", 0.0030)   # strike 1
            self.assertIsNotNone(autotune.winner(key))
            autotune.observe(key, "ring", 0.0031)   # strike 2 → re-explore
            self.assertIsNone(autotune.winner(key))
            self.assertEqual(autotune.stats()["re_explores"], 1)
            self.assertGreater(autotune.salt()[2], gen)
            self.assertTrue(
                any(e["kind"] == "autotune_reexplore" for e in telemetry.events())
            )


class TestPersistence(TestCase):
    """Tentpole site 3: versioned atomic save/load; corrupt or stale
    files fall back to a cold start with a recorded event.  Table-level
    laws run at EVERY mesh size (ci.sh replays this file at 8/4/1)."""

    def _resolve(self, key, winner="ring"):
        slow = {"ring": 0.002, "gspmd": 0.001}
        slow[winner] = 0.0005
        for _ in range(autotune.explore_k()):
            autotune.decide(key, "ring")
            for arm in autotune.ARMS:
                autotune.observe(key, arm, slow[arm])

    def test_save_load_roundtrip(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "tune.json")
            k1 = ("fp_one", autotune.device_kind())
            k2 = ("fp_two", autotune.device_kind())
            self._resolve(k1, "ring")
            self._resolve(k2, "gspmd")
            n = autotune.save(path)
            self.assertEqual(n, 2)
            doc = json.load(open(path))
            self.assertEqual(doc["version"], autotune.CACHE_VERSION)
            self.assertEqual(doc["library"], ht.__version__)
            autotune.reset()
            self.assertEqual(autotune.stats()["table_size"], 0)
            self.assertEqual(autotune.load(path), 2)
            st = autotune.stats()
            self.assertEqual(st["cache_loads"], 2)
            self.assertEqual(st["fallbacks"], 0)
            self.assertEqual(autotune.winner(k1), "ring")
            self.assertEqual(autotune.winner(k2), "gspmd")
            # loaded entries serve decisions without exploring
            d = autotune.decide(k1, "gspmd")
            self.assertEqual((d.arm, d.source, d.explore), ("ring", "cached", False))
            row = [r for r in autotune.report()["rows"] if r["fingerprint"] == "fp_one"][0]
            self.assertEqual(row["source"], "cached")

    def test_corrupt_and_stale_ignored_with_fallback_event(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            cases = {
                "not_json.json": "{nope",
                "not_object.json": json.dumps([1, 2]),
                "stale_version.json": json.dumps(
                    {"version": 999, "library": ht.__version__, "entries": []}
                ),
                "other_library.json": json.dumps(
                    {"version": autotune.CACHE_VERSION, "library": "9.9.9",
                     "entries": []}
                ),
                "bad_arm.json": json.dumps(
                    {"version": autotune.CACHE_VERSION,
                     "library": ht.__version__,
                     "entries": [{"fingerprint": "f", "device_kind": "d",
                                  "winner": "quantum"}]}
                ),
            }
            for i, (name, content) in enumerate(cases.items(), 1):
                path = os.path.join(td, name)
                with open(path, "w") as f:
                    f.write(content)
                self.assertEqual(autotune.load(path), 0, name)
                self.assertEqual(autotune.stats()["fallbacks"], i, name)
                self.assertEqual(autotune.stats()["table_size"], 0, name)
            evs = [e for e in telemetry.events() if e["kind"] == "fallback"
                   and e.get("site") == "autotune.load"]
            self.assertEqual(len(evs), len(cases))
            self.assertTrue(all(e["error"] for e in evs))

    def test_save_is_atomic(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "tune.json")
            self._resolve(("fp_a", "dk"))
            autotune.save(path)
            self.assertEqual(os.listdir(td), ["tune.json"])  # no tmp litter

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_warm_start_zero_explores(self):
        # the acceptance law, in-process: a table resolved by process 1
        # lets the same workload replay with ZERO explore calls (the
        # two-OS-process version runs in ci.sh stage 15)
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "tune.json")
            a, b = _mm_pair()
            with _Tuned():
                with fusion.fuse(False):
                    for _ in range(autotune.explore_k() + 1):
                        _ = ht.matmul(a, b).larray
                self.assertGreater(autotune.stats()["explores"], 0)
                autotune.save(path)
            with _Tuned():
                autotune.load(path)
                with fusion.fuse(False):
                    for _ in range(3):
                        _ = ht.matmul(a, b).larray
                st = autotune.stats()
                self.assertEqual(st["explores"], 0)
                self.assertEqual(st["cache_hits"], 3)
                self.assertTrue(
                    all(e["source"] == "cached" for e in _decision_events())
                )


class TestHBMSeeding(TestCase):
    """Tentpole site 2: budgets seeded from measured free HBM at plan
    time — before the first RESOURCE_EXHAUSTED, not after it."""

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_low_hbm_seeds_transport_tile_budget(self):
        with _Tuned():
            free = 2 << 20
            inj = fault.FaultInjector(seed=0).low_hbm(free)
            with fault.injected(inj):
                x = ht.arange(16 * 64, dtype=ht.float32, split=0).reshape((16, 64))
                x.resplit_(1)
            st = transport.stats()
            want = max(
                transport.TILE_FLOOR_BYTES,
                min(transport.TILE_BYTES,
                    int(free * transport._FREE_TILE_FRACTION)),
            )
            self.assertEqual(st["last_tile_bytes"], want)
            self.assertEqual(st["oom_retries"], 0)  # seeded, not recovered
            self.assertGreaterEqual(autotune.stats()["budget_seeds"], 1)
            evs = [e for e in telemetry.events() if e["kind"] == "autotune_budget"]
            self.assertTrue(evs)
            self.assertEqual(evs[0]["budget"], want)

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_off_keeps_static_tile_budget(self):
        # same injected pressure, tuning plane off: today's static budget
        inj = fault.FaultInjector(seed=0).low_hbm(2 << 20)
        transport.reset_stats()
        try:
            with fault.injected(inj):
                x = ht.arange(16 * 64, dtype=ht.float32, split=0).reshape((16, 64))
                x.resplit_(1)
            self.assertEqual(
                transport.stats()["last_tile_bytes"], transport.TILE_BYTES
            )
        finally:
            transport.reset_stats()

    @unittest.skipUnless(_MULTI, "needs a multi-device mesh")
    def test_ring_staging_declined_under_pressure(self):
        with _Tuned():
            a, b = _mm_pair(*_BIG)
            inj = fault.FaultInjector(seed=0).low_hbm(64 << 10)
            with fault.injected(inj):
                with fusion.fuse(False):
                    out = ht.matmul(a, b)
            # ring refused up front; the GSPMD fallback still computes
            self.assertEqual(overlap.stats()["last"]["reason"], "hbm-budget")
            self.assertGreaterEqual(autotune.stats()["staging_declines"], 1)
            self.assertEqual(autotune.stats()["explores"], 0)
            self.assert_array_equal(
                out, np.asarray(a.larray) @ np.asarray(b.larray), rtol=1e-4
            )


class TestOpsSurface(TestCase):
    """Satellite: Prometheus gauges + the report table."""

    def test_prometheus_gauges(self):
        with _Tuned():
            self._seed_one()
            text = telemetry.export_prometheus()
            for fam in (
                "heat_tpu_autotune_table_size",
                "heat_tpu_autotune_explores",
                "heat_tpu_autotune_cache_hits",
                "heat_tpu_autotune_cache_loads",
            ):
                self.assertIn(fam, text)
            line = [l for l in text.splitlines()
                    if l.startswith("heat_tpu_autotune_table_size")][0]
            self.assertEqual(line.split()[-1], "1")

    def _seed_one(self):
        key = ("fp_prom", "test:kind")
        for _ in range(autotune.explore_k()):
            autotune.decide(key, "ring")
            autotune.observe(key, "ring", 0.001)
            autotune.observe(key, "gspmd", 0.002)

    def test_report_shape(self):
        with _Tuned():
            self._seed_one()
            rep = telemetry.autotune_report()
            self.assertTrue(rep["enabled"])
            self.assertEqual(len(rep["rows"]), 1)
            row = rep["rows"][0]
            self.assertEqual(row["winner"], "ring")
            self.assertEqual(row["source"], "explored")
            self.assertEqual(row["ring_min_s"], 0.001)
            self.assertEqual(row["gspmd_min_s"], 0.002)
            self.assertEqual(rep["stats"]["resolved"], 1)

    def test_explore_k_env(self):
        self.assertEqual(autotune.explore_k(), 3)
        os.environ["HEAT_TPU_AUTOTUNE_EXPLORE"] = "5"
        try:
            self.assertEqual(autotune.explore_k(), 5)
            os.environ["HEAT_TPU_AUTOTUNE_EXPLORE"] = "zero"
            with self.assertRaises(ValueError):
                autotune.explore_k()
        finally:
            del os.environ["HEAT_TPU_AUTOTUNE_EXPLORE"]


class TestMerge(TestCase):
    """`autotune.merge` (ISSUE 14 satellite): fleet caches fold into one
    warm-start file, newest-best per (fingerprint, device kind, arms),
    refusing whole files that `load` would refuse."""

    @staticmethod
    def _doc(entries, library=None):
        return {
            "version": autotune.CACHE_VERSION,
            "library": ht.__version__ if library is None else library,
            "entries": entries,
        }

    @staticmethod
    def _entry(fp, winner, best, arms=None):
        arms = arms or {"ring": [best or 0.01], "gspmd": [0.05]}
        return {"fingerprint": fp, "device_kind": "cpu", "winner": winner,
                "best_s": best, "desc": "d", "arms": arms}

    def test_newest_best_selection(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            p1, p2, out = (os.path.join(td, n) for n in ("a.json", "b.json", "m.json"))
            # p1: slower resolved winner for fp_x + an unresolved fp_y
            json.dump(self._doc([
                self._entry("fp_x", "ring", 0.02),
                self._entry("fp_y", None, None, {"classic": [0.5], "kernel": []}),
            ]), open(p1, "w"))
            # p2 (newer): faster winner for fp_x, resolved fp_y
            json.dump(self._doc([
                self._entry("fp_x", "gspmd", 0.01,
                            {"ring": [0.03], "gspmd": [0.01]}),
                self._entry("fp_y", "kernel", 0.1,
                            {"classic": [0.5], "kernel": [0.1]}),
            ]), open(p2, "w"))
            self.assertEqual(autotune.merge([p1, p2], out), out)
            doc = json.load(open(out))
            self.assertEqual(doc["version"], autotune.CACHE_VERSION)
            self.assertEqual(doc["library"], ht.__version__)
            got = {e["fingerprint"]: e for e in doc["entries"]}
            self.assertEqual(len(got), 2)
            # lower best_s wins regardless of order...
            self.assertEqual(got["fp_x"]["winner"], "gspmd")
            self.assertEqual(got["fp_x"]["best_s"], 0.01)
            # ...and resolved beats unresolved
            self.assertEqual(got["fp_y"]["winner"], "kernel")
            # the merged file round-trips through load
            autotune.reset()
            self.assertEqual(autotune.load(out), 2)
            self.assertEqual(autotune.winner(("fp_x", "cpu")), "gspmd")

    def test_ties_go_to_the_later_path(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            p1, p2, out = (os.path.join(td, n) for n in ("a.json", "b.json", "m.json"))
            json.dump(self._doc([self._entry("fp", "ring", 0.01)]), open(p1, "w"))
            newer = self._entry("fp", "ring", 0.01)
            newer["desc"] = "newest"
            json.dump(self._doc([newer]), open(p2, "w"))
            autotune.merge([p1, p2], out)
            (entry,) = json.load(open(out))["entries"]
            self.assertEqual(entry["desc"], "newest")

    def test_cross_library_rows_refused_whole_file(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            good = os.path.join(td, "good.json")
            alien = os.path.join(td, "alien.json")
            broken = os.path.join(td, "broken.json")
            out = os.path.join(td, "m.json")
            json.dump(self._doc([self._entry("fp_ok", "ring", 0.01)]), open(good, "w"))
            json.dump(self._doc([self._entry("fp_alien", "ring", 0.001)],
                                library="9.9.9"), open(alien, "w"))
            with open(broken, "w") as f:
                f.write("{nope")
            autotune.merge([alien, good, broken], out)
            doc = json.load(open(out))
            self.assertEqual([e["fingerprint"] for e in doc["entries"]], ["fp_ok"])
            self.assertEqual(autotune.stats()["fallbacks"], 2)
            evs = [e for e in telemetry.events()
                   if e["kind"] == "fallback" and e.get("site") == "autotune.merge"]
            self.assertEqual(len(evs), 2)

    def test_cli_entry_point(self):
        with _Tuned(), tempfile.TemporaryDirectory() as td:
            p1 = os.path.join(td, "a.json")
            out = os.path.join(td, "m.json")
            json.dump(self._doc([self._entry("fp", "ring", 0.01)]), open(p1, "w"))
            rc = autotune._main(["--merge", p1, p1, "--out", out])
            self.assertEqual(rc, 0)
            self.assertEqual(len(json.load(open(out))["entries"]), 1)

    def test_wire_arm_entries_merge_and_round_trip(self):
        # ISSUE 16: the wire arms are first-class merge citizens — fleet
        # caches carrying ("wire_f32","wire_int8","wire_fp8") rows fold
        # newest-best and serve back through the --merge CLI + load
        def _wire_entry(fp, winner, best, f32=0.02):
            return self._entry(
                fp, winner, best,
                {"wire_f32": [f32], "wire_int8": [best or 0.01],
                 "wire_fp8": []},
            )

        with _Tuned(), tempfile.TemporaryDirectory() as td:
            p1, p2, out = (
                os.path.join(td, n) for n in ("a.json", "b.json", "m.json")
            )
            json.dump(self._doc([
                _wire_entry("fp_w", "wire_int8", 0.02),
                self._entry("fp_mm", "ring", 0.03),
            ]), open(p1, "w"))
            # newer + faster: the int8 wire win survives the fold
            json.dump(self._doc([
                _wire_entry("fp_w", "wire_int8", 0.005),
            ]), open(p2, "w"))
            rc = autotune._main(["--merge", p1, p2, "--out", out])
            self.assertEqual(rc, 0)
            doc = json.load(open(out))
            got = {e["fingerprint"]: e for e in doc["entries"]}
            self.assertEqual(set(got), {"fp_w", "fp_mm"})
            self.assertEqual(got["fp_w"]["winner"], "wire_int8")
            self.assertEqual(got["fp_w"]["best_s"], 0.005)
            self.assertEqual(
                set(got["fp_w"]["arms"]),
                {"wire_f32", "wire_int8", "wire_fp8"},
            )
            # the merged file round-trips: the wire winner is served
            autotune.reset()
            self.assertEqual(autotune.load(out), 2)
            self.assertEqual(
                autotune.winner(("fp_w", "cpu")), "wire_int8"
            )


if __name__ == "__main__":
    unittest.main()
