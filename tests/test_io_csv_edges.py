"""CSV / NetCDF edge coverage (reference: heat/core/tests/test_io.py's
csv cases — headers, separators, uneven rows vs the mesh, round-trips)."""

import os
import tempfile

import numpy as np

import heat_tpu as ht
from heat_tpu.core import io as htio
from .base import TestCase


class TestCSVEdges(TestCase):
    def _write(self, d, name, text):
        path = os.path.join(d, name)
        with open(path, "w") as fh:
            fh.write(text)
        return path

    def test_split0_matches_full_parse_odd_rows(self):
        # 13 rows over 8 devices: line-aligned byte ranges + uneven chunks
        rng = np.random.default_rng(0)
        A = np.round(rng.standard_normal((13, 4)), 4).astype(np.float32)
        with tempfile.TemporaryDirectory() as d:
            path = self._write(
                d, "t.csv",
                "\n".join(",".join(f"{v:.4f}" for v in row) for row in A) + "\n",
            )
            x = htio.load_csv(path, split=0)
            # per-shard oracle: layout bugs cannot hide behind a correct
            # gather (base.py assert_array_equal checks each device slab)
            self.assert_array_equal(x, A, rtol=1e-5)
            self.assertEqual(x.split, 0)
            y = htio.load_csv(path)
            np.testing.assert_allclose(y.numpy(), A, rtol=1e-5)

    def test_header_lines_skipped(self):
        with tempfile.TemporaryDirectory() as d:
            path = self._write(
                d, "h.csv", "colA,colB\n# comment\n1.5,2.5\n3.5,4.5\n"
            )
            x = htio.load_csv(path, header_lines=2, split=0)
            np.testing.assert_allclose(
                x.numpy(), [[1.5, 2.5], [3.5, 4.5]], rtol=1e-6
            )

    def test_semicolon_separator(self):
        with tempfile.TemporaryDirectory() as d:
            path = self._write(d, "s.csv", "1.0;2.0\n3.0;4.0\n")
            x = htio.load_csv(path, sep=";", split=0)
            np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]], rtol=1e-6)

    def test_single_column_gives_1d(self):
        with tempfile.TemporaryDirectory() as d:
            path = self._write(d, "c.csv", "1.0\n2.0\n3.0\n4.0\n5.0\n")
            x = htio.load_csv(path, split=0)
            self.assertEqual(x.shape, (5,))
            np.testing.assert_allclose(x.numpy(), [1, 2, 3, 4, 5], rtol=1e-6)

    def test_f64_fallback_path(self):
        # non-f32 dtype bypasses the native parser
        with tempfile.TemporaryDirectory() as d:
            path = self._write(d, "d.csv", "1.25,2.5\n3.75,4.0\n")
            x = htio.load_csv(path, dtype=ht.float64, split=0)
            self.assertIs(x.dtype, ht.float64)
            np.testing.assert_allclose(
                x.numpy(), [[1.25, 2.5], [3.75, 4.0]]
            )

    def test_save_load_roundtrip(self):
        rng = np.random.default_rng(1)
        A = np.round(rng.standard_normal((9, 3)), 4).astype(np.float32)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "rt.csv")
            htio.save_csv(ht.array(A, split=0), path)
            back = htio.load_csv(path, split=0)
            np.testing.assert_allclose(back.numpy(), A, rtol=1e-4)

    def test_rows_fewer_than_devices(self):
        with tempfile.TemporaryDirectory() as d:
            path = self._write(d, "tiny.csv", "1.0,2.0\n3.0,4.0\n")
            x = htio.load_csv(path, split=0)  # 2 rows / 8 devices
            self.assert_array_equal(
                x, np.array([[1.0, 2.0], [3.0, 4.0]], np.float32), rtol=1e-6
            )


class TestNetCDFEdges(TestCase):
    def test_roundtrip_and_missing_variable(self):
        if not htio.supports_netcdf():
            self.skipTest("no netcdf backend")
        rng = np.random.default_rng(2)
        A = rng.standard_normal((11, 3)).astype(np.float32)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.nc")
            htio.save_netcdf(ht.array(A, split=0), path, "DATA")
            x = htio.load_netcdf(path, "DATA", split=0)
            np.testing.assert_allclose(x.numpy(), A, rtol=1e-6)
            with self.assertRaises((KeyError, IndexError, RuntimeError, ValueError)):
                htio.load_netcdf(path, "NOPE", split=0)
