"""Unified telemetry (ISSUE 8): registry laws, flight recorder, spans,
cost ledger, and FaultInjector-driven degradation trails.

Doctrine stays "no mocks": the trail tests inject faults through the real
:class:`~heat_tpu.utils.fault.FaultInjector` / ``guard`` hooks and read
the degradation back out of ``ht.telemetry.events()`` — the flight
recorder must witness the production OOM-backoff and eager-fallback paths
exactly as they ran.
"""

import threading
import unittest
import warnings

import numpy as np

import jax

import heat_tpu as ht
from heat_tpu.core import fusion, guard, telemetry
from heat_tpu.parallel import overlap, transport
from heat_tpu.utils import fault

from .base import TestCase


def _mesh(n):
    from heat_tpu.parallel.mesh import local_mesh

    return local_mesh(n)


def _reset_counters():
    fusion.reset_cache()
    transport.reset_stats()
    overlap.reset_stats()


class _EventsLevel:
    """Scoped events level + clean recorder/ledger on both sides."""

    def __init__(self, level="events"):
        self.level = level

    def __enter__(self):
        self.prev = telemetry.set_level(self.level)
        telemetry.clear_events()
        return self

    def __exit__(self, *exc):
        telemetry.set_level(self.prev)
        telemetry.clear_events()
        return False


class TestRegistryLaws(TestCase):
    """snapshot()/reset_all() vs the per-module shim accessors."""

    def setUp(self):
        _reset_counters()

    def tearDown(self):
        _reset_counters()

    def test_snapshot_covers_all_three_groups(self):
        snap = telemetry.snapshot()
        for group in ("fusion", "transport", "overlap"):
            self.assertIn(group, snap)

    def _law(self, comm):
        """At any mesh size: run real traffic, then (a) each module shim
        returns exactly the registry snapshot, (b) reset_all() restores
        the registered defaults, (c) module-level aliases survive reset."""
        _reset_counters()
        rng = np.random.default_rng(comm.size)
        a = ht.array(
            rng.random((12, 8)).astype(np.float32), split=0, comm=comm
        )
        chained = (a + 1.0) * 2.0 - 0.5
        _ = chained.larray
        if comm.size > 1:
            _ = ((a * 3.0).resplit(1)).larray
        overlap.set_mode("gspmd")
        try:
            with fusion.fuse(False):
                _ = ht.matmul(a, a.T.resplit(None) if comm.size > 1 else a.T)
        finally:
            overlap.set_mode(None)

        snap = telemetry.snapshot()
        self.assertEqual(snap["fusion"], fusion.cache_stats())
        self.assertEqual(snap["transport"], transport.stats())
        self.assertEqual(snap["overlap"], overlap.stats())
        self.assertGreaterEqual(snap["fusion"]["misses"], 1)
        self.assertGreaterEqual(snap["overlap"]["calls"], 1)

        telemetry.reset_all()
        after = telemetry.snapshot()
        self.assertEqual(after["fusion"]["misses"], 0)
        self.assertEqual(after["fusion"]["roots_per_program"], {})
        self.assertEqual(after["transport"]["oom_retries"], 0)
        self.assertEqual(after["transport"]["retries_by_kind"], {})
        self.assertEqual(after["overlap"]["calls"], 0)
        self.assertIsNone(after["overlap"]["last"])
        # the in-place reset keeps module aliases live (the drift class the
        # registry exists to kill: one defaults dict, no hand-kept resets)
        self.assertIs(fusion._FALLBACK_REASONS, fusion._STATS["fallback_reasons"])
        self.assertIs(fusion._ROOTS_PER_PROGRAM, fusion._STATS["roots_per_program"])

    def test_laws_mesh1(self):
        self._law(_mesh(1))

    @unittest.skipUnless(len(jax.devices()) >= 4, "needs >= 4 devices")
    def test_laws_mesh4(self):
        self._law(_mesh(4))

    @unittest.skipUnless(len(jax.devices()) >= 8, "needs >= 8 devices")
    def test_laws_mesh8(self):
        self._law(self.comm)

    def test_prometheus_export_well_formed(self):
        _ = ((ht.arange(16, dtype=ht.float32, split=0) + 1.0) * 2.0).larray
        text = telemetry.export_prometheus()
        lines = [ln for ln in text.splitlines() if ln]
        self.assertTrue(lines)
        helped, typed = set(), set()
        for ln in lines:
            if ln.startswith("# HELP "):
                helped.add(ln.split(" ")[2])
            elif ln.startswith("# TYPE "):
                _, _, metric, mtype = ln.split(" ")
                self.assertEqual(mtype, "gauge")
                typed.add(metric)
            else:
                self.assertFalse(ln.startswith("#"))  # no stray comments
                metric, value = ln.rsplit(" ", 1)
                family = metric.split("{", 1)[0]  # labeled program samples
                self.assertIn(family, typed)   # every sample was typed
                self.assertIn(family, helped)  # ... and documented
                float(value)  # every sample is numeric
        for expected in (
            "heat_tpu_fusion_misses",
            "heat_tpu_transport_oom_retries",
            "heat_tpu_overlap_by_schedule_gspmd",
            "heat_tpu_telemetry_events",
        ):
            self.assertIn(expected, typed)

    def test_prometheus_golden_format(self):
        # one counter, golden exposition: metric-unsafe characters in the
        # group/counter names escape to `_`, the HELP line keeps the
        # original dotted path, TYPE precedes the sample
        telemetry.register_group("weird.group", {"hit rate%": 3})
        try:
            text = telemetry.export_prometheus()
        finally:
            telemetry._GROUPS.pop("weird.group", None)
        golden = (
            "# HELP heat_tpu_weird_group_hit_rate_ "
            "heat_tpu telemetry gauge weird.group.hit rate%\n"
            "# TYPE heat_tpu_weird_group_hit_rate_ gauge\n"
            "heat_tpu_weird_group_hit_rate_ 3"
        )
        self.assertIn(golden, text)
        self.assertTrue(text.endswith("\n"))

    def test_snapshot_has_telemetry_group(self):
        with _EventsLevel():
            telemetry.record_event("probe")
            snap = telemetry.snapshot()
        self.assertIn("telemetry", snap)
        tele = snap["telemetry"]
        self.assertEqual(tele["level"], "events")
        self.assertEqual(tele["events"], 1)
        self.assertEqual(tele["capacity"], telemetry._RING.maxlen)
        self.assertIn("events_dropped", tele)
        self.assertIn("programs", tele)

    def test_snapshot_counts_dropped_events(self):
        prev_cap = telemetry.set_capacity(4)
        try:
            with _EventsLevel():
                for i in range(10):
                    telemetry.record_event("probe", i=i)
                self.assertEqual(
                    telemetry.snapshot()["telemetry"]["events_dropped"], 6
                )
        finally:
            telemetry.set_capacity(prev_cap)


class TestFlightRecorder(TestCase):
    def test_ring_capacity_and_ordering(self):
        with _EventsLevel():
            prev_cap = telemetry.set_capacity(8)
            try:
                for i in range(20):
                    telemetry.record_event("probe", i=i)
                got = telemetry.events("probe")
                self.assertEqual(len(got), 8)
                # newest 8 survive, oldest first, seq strictly ascending
                self.assertEqual([e["i"] for e in got], list(range(12, 20)))
                seqs = [e["seq"] for e in got]
                self.assertEqual(seqs, sorted(seqs))
                ts = [e["ts"] for e in got]
                self.assertEqual(ts, sorted(ts))
            finally:
                telemetry.set_capacity(prev_cap)

    def test_events_since_cursor(self):
        with _EventsLevel():
            seqs = [telemetry.record_event("probe", i=i) for i in range(6)]
            # an external poller feeds back the last seq it saw
            got = telemetry.events(since=seqs[3])
            self.assertEqual([e["i"] for e in got], [4, 5])
            self.assertEqual(telemetry.events("probe", since=seqs[-1]), [])
            # since=None is the full ring (back-compat)
            self.assertEqual(len(telemetry.events("probe")), 6)

    def test_events_carry_thread_ident(self):
        with _EventsLevel():
            telemetry.record_event("probe")
            got = {}

            def worker():
                telemetry.record_event("probe")
                got["tid"] = threading.get_ident()

            t = threading.Thread(target=worker)
            t.start()
            t.join(timeout=5)
            evts = telemetry.events("probe")
            self.assertEqual(evts[0]["tid"], threading.get_ident())
            self.assertEqual(evts[1]["tid"], got["tid"])
            # a caller field named like an envelope key is re-keyed
            telemetry.record_event("probe", tid="shadow")
            self.assertEqual(telemetry.events("probe")[-1]["x_tid"], "shadow")

    def test_off_records_nothing(self):
        prev = telemetry.set_level("off")
        telemetry.clear_events()
        telemetry.reset_programs()
        try:
            x = ht.arange(24, dtype=ht.float32, split=0)
            _ = ((x + 1.0) * 2.0).larray
            self.assertEqual(telemetry.events(), [])
            self.assertEqual(telemetry.programs(), [])
            self.assertIsNone(telemetry.record_event("probe"))
            with telemetry.span("dead"):
                self.assertIsNone(telemetry.current_span())
            self.assertEqual(telemetry.events(), [])
        finally:
            telemetry.set_level(prev)

    def test_counters_level_has_ledger_but_no_events(self):
        prev = telemetry.set_level("counters")
        telemetry.clear_events()
        telemetry.reset_programs()
        fusion.reset_cache()
        try:
            x = ht.arange(24, dtype=ht.float32, split=0)
            _ = ((x + 1.0) * 2.0).larray
            self.assertEqual(telemetry.events(), [])
            self.assertTrue(telemetry.programs())
        finally:
            telemetry.set_level(prev)

    def test_dump_document(self):
        import io
        import json

        with _EventsLevel():
            telemetry.record_event("probe", i=1)
            buf = io.StringIO()
            telemetry.dump(buf)
            doc = json.loads(buf.getvalue())
            self.assertEqual(doc["telemetry_level"], "events")
            self.assertIn("fusion", doc["counters"])
            self.assertTrue(any(e["kind"] == "probe" for e in doc["events"]))


class TestSpans(TestCase):
    def setUp(self):
        fusion.reset_cache()

    @unittest.skipUnless(fusion.enabled(), "fusion engine disabled")
    def test_nesting_under_materialize_all(self):
        with _EventsLevel():
            x = ht.arange(32, dtype=ht.float32, split=0)
            with telemetry.span("user.outer", tag="t"):
                a = (x + 1.0) * 2.0
                b = (x - 3.0) / 4.0
                ht.materialize_all(a, b)
            begins = {e["name"]: e for e in telemetry.events("span_begin")}
            self.assertIn("user.outer", begins)
            self.assertIn("fusion.materialize", begins)
            self.assertIsNone(begins["user.outer"]["parent"])
            self.assertEqual(
                begins["fusion.materialize"]["parent"],
                begins["user.outer"]["id"],
            )
            ends = {e["name"]: e for e in telemetry.events("span_end")}
            self.assertIn("fusion.materialize", ends)
            self.assertGreaterEqual(ends["fusion.materialize"]["dur_s"], 0.0)
            # events inside the region carry the innermost open span id
            miss = telemetry.events("cache_miss")
            self.assertTrue(miss)
            self.assertEqual(
                miss[0]["span"], begins["fusion.materialize"]["id"]
            )

    def test_decorator_form(self):
        @telemetry.span("probe.fn", kind="test")
        def work(n):
            return n + 1

        with _EventsLevel():
            self.assertEqual(work(1), 2)
            self.assertEqual(work(2), 3)
            begins = telemetry.events("span_begin")
            self.assertEqual(len(begins), 2)  # fresh span per call
            self.assertNotEqual(begins[0]["id"], begins[1]["id"])

    def test_open_spans_visible_across_threads(self):
        with _EventsLevel():
            entered = threading.Event()
            release = threading.Event()
            seen = {}

            def worker():
                with telemetry.span("worker.busy"):
                    entered.set()
                    release.wait(timeout=5)

            t = threading.Thread(target=worker)
            t.start()
            try:
                self.assertTrue(entered.wait(timeout=5))
                seen["open"] = [s["name"] for s in telemetry.open_spans()]
            finally:
                release.set()
                t.join(timeout=5)
            self.assertIn("worker.busy", seen["open"])
            self.assertEqual(
                [s["name"] for s in telemetry.open_spans()], []
            )

    def test_span_error_exit_recorded(self):
        with _EventsLevel():
            with self.assertRaises(ValueError):
                with telemetry.span("probe.err"):
                    raise ValueError("boom")
            end = telemetry.events("span_end")[-1]
            self.assertEqual(end["status"], "error")
            self.assertEqual(end["error"], "ValueError")

    def test_decorator_preserves_metadata(self):
        @telemetry.span("probe.meta")
        def documented(n):
            """Adds one."""
            return n + 1

        self.assertEqual(documented.__name__, "documented")
        self.assertEqual(documented.__doc__, "Adds one.")
        self.assertEqual(documented.__wrapped__(41), 42)

    def test_decorated_raise_records_error_status(self):
        @telemetry.span("probe.meta.err")
        def boom():
            raise KeyError("k")

        with _EventsLevel():
            with self.assertRaises(KeyError):
                boom()
            end = telemetry.events("span_end")[-1]
            self.assertEqual(end["name"], "probe.meta.err")
            self.assertEqual(end["status"], "error")
            self.assertEqual(end["error"], "KeyError")

    def test_postmortem_dump_under_concurrent_spans(self):
        # two threads holding open spans while a postmortem fires: the
        # dump must list BOTH open spans, a sibling Chrome trace must be
        # written, and a second postmortem in the same process must take
        # the .2 suffix instead of overwriting the first trail
        import json
        import os
        import tempfile

        with _EventsLevel():
            entered = threading.Event()
            release = threading.Event()

            def worker():
                with telemetry.span("worker.holding"):
                    entered.set()
                    release.wait(timeout=5)

            t = threading.Thread(target=worker)
            t.start()
            try:
                self.assertTrue(entered.wait(timeout=5))
                with tempfile.TemporaryDirectory() as td:
                    path = os.path.join(td, "pm.json")
                    os.environ["HEAT_TPU_TELEMETRY_DUMP"] = path
                    try:
                        with telemetry.span("main.holding"):
                            telemetry.postmortem("test_reason", detail=1)
                            telemetry.postmortem("test_reason_again")
                    finally:
                        del os.environ["HEAT_TPU_TELEMETRY_DUMP"]
                    doc = json.load(open(path))
                    names = [s["name"] for s in doc["open_spans"]]
                    self.assertIn("worker.holding", names)
                    self.assertIn("main.holding", names)
                    self.assertTrue(os.path.exists(path + ".trace.json"))
                    trace = json.load(open(path + ".trace.json"))
                    self.assertTrue(
                        all("ph" in e and "ts" in e for e in trace)
                    )
                    # never-overwrite: the second trail took .2
                    self.assertTrue(os.path.exists(path + ".2"))
                    self.assertTrue(os.path.exists(path + ".2.trace.json"))
                    # ... and the first trail still ends at its own event
                    self.assertEqual(doc["events"][-1]["reason"],
                                     "test_reason")
            finally:
                release.set()
                t.join(timeout=5)


@unittest.skipUnless(fusion.enabled(), "fusion engine disabled")
class TestFaultTrails(TestCase):
    """The full degradation trail of injected faults must be readable out
    of telemetry.events() — budgets, reasons, correlation ids."""

    def setUp(self):
        _reset_counters()

    def tearDown(self):
        _reset_counters()

    def test_injected_oom_leaves_halving_trail(self):
        with _EventsLevel():
            inj = fault.FaultInjector(seed=0).oom_in("transport.resplit", times=2)
            x = ht.array(
                np.arange(64.0, dtype=np.float32).reshape(8, 8),
                split=0, comm=self.comm,
            )
            with fault.injected(inj):
                out = x.resplit(1)
                _ = out.larray
            trail = telemetry.events("oom_retry")
            self.assertEqual(len(trail), 2)
            self.assertTrue(all(e["kernel"] == "resplit" for e in trail))
            # each event carries the NEW budget: strictly halving
            self.assertEqual(
                trail[1]["tile_bytes"], trail[0]["tile_bytes"] // 2
            )
            self.assertEqual(
                transport.stats()["retries_by_kind"].get("resplit"), 2
            )
            # the retried transfer ran inside its transport span
            spans = {e["id"]: e for e in telemetry.events("span_begin")}
            self.assertTrue(
                all(spans[e["span"]]["name"] == "transport.resplit"
                    for e in trail)
            )

    def test_injected_compile_failure_emits_fallback_event(self):
        with _EventsLevel():
            inj = fault.FaultInjector(seed=0).error_in("fusion.compile", times=1)
            x = ht.arange(24, dtype=ht.float32, split=0)
            with fault.injected(inj):
                _ = ((x * 3.0) + 1.0).larray
            reasons = [e["reason"] for e in telemetry.events("fallback")]
            self.assertIn("compile_error", reasons)
            # the failed compile closed its compile_begin with ok=False
            ends = telemetry.events("compile_end")
            self.assertTrue(any(e.get("ok") is False for e in ends))

    def test_warning_carries_blame_event_id(self):
        prev_guard = guard.set_mode("warn")
        try:
            with _EventsLevel():
                x = ht.arange(24, dtype=ht.float32, split=0)
                z = ht.log(x - 100.0)  # negative operand: chain-introduced NaN
                with warnings.catch_warnings(record=True) as caught:
                    warnings.simplefilter("always")
                    _ = z.larray
                trips = [
                    w.message for w in caught
                    if issubclass(w.category, guard.NonFiniteWarning)
                ]
                self.assertTrue(trips)
                eid = trips[0].event_id
                self.assertIsNotNone(eid)
                blames = telemetry.events("guard_blame")
                self.assertTrue(any(e["seq"] == eid for e in blames))
        finally:
            guard.set_mode(prev_guard)

    def test_stall_detector_events(self):
        with _EventsLevel():
            stalls = []
            det = fault.StallDetector(timeout=0.15, on_stall=stalls.append)
            det.start()
            try:
                det.beat()
                with det.pause():
                    pass
                with telemetry.span("user.stalled_work"):
                    deadline = __import__("time").monotonic() + 5.0
                    while not stalls and __import__("time").monotonic() < deadline:
                        __import__("time").sleep(0.02)
            finally:
                det.stop()
            self.assertTrue(stalls)
            self.assertTrue(telemetry.events("heartbeat"))
            self.assertTrue(telemetry.events("stall_pause"))
            self.assertTrue(telemetry.events("stall_resume"))
            stall_events = telemetry.events("stall")
            self.assertTrue(stall_events)
            self.assertGreaterEqual(stall_events[0]["quiet_s"], 0.15)
            # the watchdog thread saw the workload's open span
            self.assertIn(
                "user.stalled_work",
                [s["name"] for s in stall_events[0]["open_spans"]],
            )


class TestCostLedger(TestCase):
    def setUp(self):
        _reset_counters()
        telemetry.reset_programs()

    def tearDown(self):
        _reset_counters()
        telemetry.reset_programs()

    @unittest.skipUnless(fusion.enabled(), "fusion engine disabled")
    def test_fused_moments_program_is_ledgered(self):
        x = ht.array(
            np.random.default_rng(0).random((64, 16)).astype(np.float32),
            split=0, comm=self.comm,
        )
        _ = ht.mean(x)
        _ = float(ht.var(x).larray) if hasattr(ht.var(x), "larray") else None
        progs = [p for p in telemetry.programs() if p["kind"] == "fused"]
        self.assertTrue(progs)
        biggest = max(progs, key=lambda p: p["flops"])
        self.assertGreater(biggest["flops"], 0.0)
        self.assertGreater(biggest["hbm_bytes"], 0.0)
        self.assertGreaterEqual(biggest["ops"], 1)
        self.assertEqual(biggest["mesh"], {"devices": self.comm.size})

    @unittest.skipUnless(len(jax.devices()) >= 4, "needs >= 4 devices")
    def test_ring_matmul_program_is_ledgered(self):
        comm = _mesh(4)
        rng = np.random.default_rng(1)
        m = k = n = 32
        A = rng.random((m, k)).astype(np.float32)
        B = rng.random((k, n)).astype(np.float32)
        a = ht.array(A, split=0, comm=comm)
        b = ht.array(B, split=0, comm=comm)  # row×row is the `ag` case
        overlap.set_mode("ring")
        try:
            with fusion.fuse(False):
                out = ht.matmul(a, b)
        finally:
            overlap.set_mode(None)
        self.assertEqual(overlap.stats()["last"]["schedule"], "ring_ag")
        np.testing.assert_allclose(out.numpy(), A @ B, rtol=2e-5, atol=2e-5)
        rings = [p for p in telemetry.programs() if p["kind"] == "ring_matmul"]
        self.assertTrue(rings)
        self.assertEqual(rings[-1]["flops"], 2.0 * m * k * n)
        self.assertGreater(rings[-1]["hbm_bytes"], 0.0)
        self.assertEqual(rings[-1]["schedule"], "ring_ag")

    @unittest.skipUnless(fusion.enabled(), "fusion engine disabled")
    def test_cache_hit_counts_on_ledger_entry(self):
        x = ht.arange(48, dtype=ht.float32, split=0)
        _ = ((x + 1.0) * 2.0).larray
        y = ht.arange(48, dtype=ht.float32, split=0)
        _ = ((y + 1.0) * 2.0).larray  # same topology: compile-cache hit
        progs = {p["fingerprint"]: p for p in telemetry.programs()}
        self.assertTrue(
            any(p["hits"] >= 1 for p in progs.values()),
            f"no ledger entry saw a hit: {list(progs.values())}",
        )


if __name__ == "__main__":
    unittest.main()
