"""I/O error paths and format edge cases (reference: heat/core/tests/
test_io.py error-branch coverage)."""

import os
import tempfile

import numpy as np

import heat_tpu as ht
from heat_tpu.core import io as htio
from .base import TestCase


class TestLoadSaveErrors(TestCase):
    def test_unsupported_extension(self):
        with self.assertRaises(ValueError):
            ht.load("data.xyz")
        with self.assertRaises(ValueError):
            ht.save(ht.array(np.zeros(3)), "data.xyz")

    def test_non_string_path(self):
        with self.assertRaises(TypeError):
            ht.load(42)

    def test_non_dndarray_save(self):
        with self.assertRaises(TypeError):
            ht.save(np.zeros(3), "x.h5")

    def test_missing_file(self):
        with self.assertRaises(Exception):
            ht.load("/nonexistent/path/data.h5", dataset="D")

    def test_missing_hdf5_dataset(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.h5")
            ht.save(ht.array(np.zeros((4, 2), np.float32)), path, "REAL")
            with self.assertRaises(KeyError):
                ht.load(path, dataset="WRONG", split=0)

    def test_too_many_slices(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.h5")
            ht.save(ht.array(np.zeros((4, 2), np.float32)), path, "D")
            with self.assertRaises(ValueError):
                htio.load_hdf5(path, "D", slices=(slice(None),) * 3)

    def test_bad_slices_type(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.h5")
            ht.save(ht.array(np.zeros((4, 2), np.float32)), path, "D")
            with self.assertRaises(TypeError):
                htio.load_hdf5(path, "D", slices=("bad",))

    def test_ragged_csv_raises_or_nans(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.csv")
            with open(path, "w") as f:
                f.write("1,2,3\n4,5\n6,7,8\n")
            # NumPy's genfromtxt raises on ragged rows; the native parser
            # signals ragged and defers to the same error path
            with self.assertRaises(Exception):
                ht.load(path, split=None)

    def test_csv_empty_data_after_header(self):
        # numpy's genfromtxt warns and returns empty for a data-less file;
        # either an empty result or an error is acceptable, silence is not
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.csv")
            with open(path, "w") as f:
                f.write("h1,h2\n")
            try:
                y = ht.load(path, header_lines=1, split=0)
            except Exception:
                return
            self.assertEqual(int(np.prod(y.shape)), 0)

    def test_scalar_roundtrip_hdf5(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.h5")
            ht.save(ht.array(np.float32(3.5)), path, "S")
            y = ht.load(path, dataset="S")
            self.assertAlmostEqual(float(y), 3.5)

    def test_int_dtype_roundtrip(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.h5")
            A = np.arange(12, dtype=np.int32).reshape(3, 4)
            ht.save(ht.array(A, split=0), path, "D")
            y = ht.load(path, dataset="D", split=0, dtype=ht.int32)
            self.assertEqual(y.dtype, ht.int32)
            np.testing.assert_array_equal(y.numpy(), A)

    def test_csv_append_mode(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.csv")
            A = np.arange(6, dtype=np.float32).reshape(2, 3)
            ht.save(ht.array(A, split=0), path)
            ht.save(ht.array(A, split=0), path, truncate=False)
            got = np.genfromtxt(path, delimiter=",")
            np.testing.assert_allclose(got, np.concatenate([A, A]), atol=1e-5)

    def test_header_written_once_on_append(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.csv")
            A = np.ones((2, 2), np.float32)
            ht.save(ht.array(A), path, header_lines=["c1,c2"])
            ht.save(ht.array(A), path, header_lines=["c1,c2"], truncate=False)
            with open(path) as f:
                content = f.read()
            self.assertEqual(content.count("c1,c2"), 1)
