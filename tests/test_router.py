"""Fleet router (ISSUE 18): consistent-hash placement, the per-replica
circuit breaker, bounded retry/failover, SLO shed ordering, and
zero-downtime rolling weight swaps.

The failure matrix runs against REAL injected faults riding the guard
hooks (``serving.step.<replica>`` fires inside the replica's worker,
``serving.replica.<name>`` inside the router's dispatch) — no mocks.
The laws:

* a replica stall or error burst never loses a caller's future —
  failover re-dispatches, the caller sees added latency at worst;
* an ejected replica re-enters only through a half-open probation
  probe (one real request through the full stack);
* ``rolling_swap`` under concurrent traffic is new operands, not a
  retrace (zero step compiles / fusion misses / ring builds), and a
  regressing canary auto-rolls back with the old weights still serving.

``scripts/ci.sh`` stage 21 re-runs this file at mesh sizes 1/4/8.
"""

import time
import unittest

import numpy as np

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.core import telemetry
from heat_tpu.serving import RequestRejected, ServingFleet
from heat_tpu.serving.router import HEALTHY
from heat_tpu.utils import fault

from .base import TestCase

_RNG = np.random.default_rng(1818)
_F, _O = 8, 4


class _Linear:
    """Swappable model: one resident operand, real mesh matmul."""

    def __init__(self, w):
        self.w = ht.array(w, split=None)

    def predict(self, x):
        return x @ self.w


def _weights():
    return _RNG.normal(size=(_F, _O)).astype(np.float32)


def _fleet(n=2, **kwargs):
    telemetry.reset_group("serving")
    telemetry.reset_group("router")
    kwargs.setdefault("stall_timeout_s", 0.15)
    kwargs.setdefault("cooldown_s", 0.2)
    kwargs.setdefault("error_threshold", 2)
    kwargs.setdefault("probe_timeout_s", 15.0)
    return ServingFleet(replicas=n, **kwargs)


def _register_linear(fleet, w, name="lin", **kwargs):
    models = [_Linear(w) for _ in fleet.replicas]
    kwargs.setdefault("min_bucket", 8)
    kwargs.setdefault("max_batch", 16)
    fleet.register(name, models=models, feature_dim=_F, warm=True, **kwargs)
    return models


def _wait_all_healthy(fleet, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(r.state == HEALTHY for r in fleet.replicas):
            return True
        time.sleep(0.02)
    return False


def _key_for(fleet, name):
    """A request key whose consistent-hash home is replica ``name``."""
    for key in range(4096):
        if fleet._ring_order(key)[0].name == name:
            return key
    raise AssertionError(f"no key hashes home to {name}")


class TestPlacement(TestCase):
    def test_consistent_hash_affinity(self):
        fleet = _fleet(n=4)
        try:
            # same key -> same home replica, every time; keys spread
            # across the fleet rather than piling on one replica
            homes = {key: fleet._ring_order(key)[0].name for key in range(64)}
            for key, home in homes.items():
                for _ in range(3):
                    self.assertEqual(fleet._ring_order(key)[0].name, home)
            self.assertGreaterEqual(len(set(homes.values())), 2)
        finally:
            fleet.close()

    def test_routes_around_ejected_replica(self):
        fleet = _fleet(n=2)
        try:
            _register_linear(fleet, _weights())
            victim = fleet._ring_order("pinned")[0]
            with fleet._lock:
                fleet._eject_locked(victim, "test")
            # the home is benched, but the request still serves — routed
            # to the surviving sibling without a retry
            x = np.ones((2, _F), dtype=np.float32)
            out = fleet.predict("lin", x, key="pinned")
            self.assertEqual(np.asarray(out).shape[0], 2)
        finally:
            fleet.close()


class TestFailoverMatrix(TestCase):
    """The ISSUE 18 acceptance drills, one injected fault per test."""

    def test_replica_stall_fails_over_with_zero_lost_futures(self):
        fleet = _fleet(n=2)
        try:
            _register_linear(fleet, _weights())
            x = np.ones((2, _F), dtype=np.float32)
            inj = fault.FaultInjector().stall_in("serving.step.r0", 1.0, times=1)
            with fault.injected(inj):
                futures = [
                    fleet.submit("lin", x, key=f"k{i}") for i in range(12)
                ]
                results = [f.result(30) for f in futures]
            self.assertEqual(len(results), 12)
            for r in results:
                self.assertEqual(np.asarray(r).shape, (2, _O))
            self.assertEqual(inj.fired, [("stall", "serving.step.r0")])
            stats = fleet.stats()
            self.assertGreaterEqual(stats["ejections"], 1)
            self.assertGreaterEqual(stats["failovers"], 1)
            self.assertEqual(stats["lost_futures"], 0)
            # the circuit reopens via a half-open probe, not a timer alone
            self.assertTrue(_wait_all_healthy(fleet), "r0 never recovered")
            stats = fleet.stats()
            self.assertGreaterEqual(stats["half_opens"], 1)
            self.assertGreaterEqual(stats["probes"], 1)
            self.assertGreaterEqual(stats["recoveries"], 1)
        finally:
            fleet.close()

    def test_error_burst_opens_circuit_then_probe_recovers(self):
        fleet = _fleet(n=2)
        try:
            _register_linear(fleet, _weights())
            x = np.ones((1, _F), dtype=np.float32)
            pinned = _key_for(fleet, "r1")
            inj = fault.FaultInjector().error_in("serving.step.r1", times=5)
            with fault.injected(inj):
                # sequential pinned traffic: each batch on r1 fails for
                # real, fails over to r0, and the consecutive-failure
                # counter marches the circuit open
                for _ in range(4):
                    out = fleet.predict("lin", x, key=pinned)
                    self.assertEqual(np.asarray(out).shape, (1, _O))
                stats = fleet.stats()
                self.assertGreaterEqual(stats["ejections"], 1)
                # remaining armed faults fail the first probation probes
                # (probe_failures re-eject); once the arms run dry a
                # probe succeeds and the circuit closes for real
                self.assertTrue(
                    _wait_all_healthy(fleet),
                    "circuit never reopened after the error burst",
                )
            stats = fleet.stats()
            self.assertGreaterEqual(stats["failovers"], 1)
            self.assertGreaterEqual(stats["probes"], 1)
            self.assertGreaterEqual(stats["recoveries"], 1)
            self.assertEqual(stats["lost_futures"], 0)
        finally:
            fleet.close()

    def test_dispatch_fault_at_replica_site_fails_over(self):
        fleet = _fleet(n=2)
        try:
            _register_linear(fleet, _weights())
            x = np.ones((1, _F), dtype=np.float32)
            home = fleet._ring_order("pin")[0].name
            inj = fault.FaultInjector().error_in(f"serving.replica.{home}", times=1)
            with fault.injected(inj):
                out = fleet.predict("lin", x, key="pin")
            self.assertEqual(np.asarray(out).shape, (1, _O))
            self.assertEqual(inj.fired, [("error", f"serving.replica.{home}")])
            self.assertGreaterEqual(fleet.stats()["failovers"], 1)
        finally:
            fleet.close()

    def test_queue_full_backs_off_and_retries_same_replica(self):
        # one replica, tiny queue: the only way out is jittered backoff
        # against the retry budget, then the drained queue admits
        fleet = _fleet(
            n=1,
            admission_kwargs={"max_queue_rows": 8, "retry_after_s": 0.01},
            max_retries=4,
            retry_budget=64.0,
        )
        try:
            _register_linear(fleet, _weights(), max_delay_s=0.01)
            x = np.ones((4, _F), dtype=np.float32)
            futures = [fleet.submit("lin", x, key=i) for i in range(8)]
            results = [f.result(30) for f in futures]
            self.assertEqual(len(results), 8)
            stats = fleet.stats()
            self.assertGreaterEqual(stats["backoffs"], 1)
            self.assertEqual(stats["lost_futures"], 0)
        finally:
            fleet.close()

    def test_all_replicas_ejected_is_documented_unavailable(self):
        fleet = _fleet(n=2, max_retries=0)
        try:
            _register_linear(fleet, _weights())
            with fleet._lock:
                for replica in fleet.replicas:
                    fleet._eject_locked(replica, "test")
            with self.assertRaisesRegex(RequestRejected, "unavailable"):
                fleet.submit(
                    "lin", np.ones((1, _F), dtype=np.float32)
                ).result(10)
            self.assertTrue(_wait_all_healthy(fleet))  # probes bring them back
        finally:
            fleet.close()


class TestSLOFleet(TestCase):
    def test_low_priority_sheds_first_under_pressure(self):
        fleet = _fleet(
            n=1,
            max_retries=0,
            admission_kwargs={"max_queue_rows": 8},
        )
        _register_linear(fleet, _weights(), max_delay_s=30.0)  # hold queue
        x3 = np.ones((3, _F), dtype=np.float32)
        x2 = np.ones((2, _F), dtype=np.float32)
        held = fleet.submit("lin", x3, priority="high")
        # 3 rows queued: low's bound is int(8 * 0.5) = 4, so a 2-row low
        # request overflows its class first while high still admits
        low = fleet.submit("lin", x2, priority="low")
        with self.assertRaisesRegex(RequestRejected, "queue_full"):
            low.result(10)
        accepted_high = fleet.submit("lin", x2, priority="high")
        serving_stats = telemetry.serving_report()
        self.assertGreaterEqual(serving_stats["shed_by_class"]["low"], 1)
        self.assertGreaterEqual(serving_stats["accepted_by_class"]["high"], 2)
        # closing drains the held queue — nothing accepted is lost
        fleet.close()
        self.assertEqual(np.asarray(held.result(10)).shape, (3, _O))
        self.assertEqual(np.asarray(accepted_high.result(10)).shape, (2, _O))
        self.assertEqual(fleet.stats()["lost_futures"], 0)

    def test_lapsed_deadline_resolves_expired_not_lost(self):
        fleet = _fleet(n=1, max_retries=0)
        try:
            _register_linear(fleet, _weights(), max_delay_s=0.25)
            x = np.ones((2, _F), dtype=np.float32)
            # the client deadline lapses before the 0.25 s flush fires;
            # the batcher drops the request as `expired` — a terminal
            # reject the router never retries
            doomed = fleet.submit("lin", x, deadline_s=0.05, key="d")
            with self.assertRaisesRegex(RequestRejected, "expired"):
                doomed.result(10)
            self.assertGreaterEqual(
                telemetry.serving_report()["shed"]["expired"], 1
            )
            # the lane stays live: a fresh request with headroom serves
            out = fleet.predict("lin", x, key="ok")
            self.assertEqual(np.asarray(out).shape, (2, _O))
        finally:
            fleet.close()


class TestRollingSwap(TestCase):
    def test_rolling_swap_under_traffic_no_retrace(self):
        fleet = _fleet(n=2)
        try:
            w_old, w_new = _weights(), _weights()
            _register_linear(fleet, w_old)
            x = _RNG.normal(size=(2, _F)).astype(np.float32)
            for i in range(8):  # warm reservoirs on both replicas
                fleet.predict("lin", x, key=f"w{i}")
            steps_before = telemetry.serving_report()["step_compiles"]
            fusion_before = telemetry.snapshot_group("fusion").get("misses", 0)
            ring_before = telemetry.snapshot_group("overlap").get("ring_builds", 0)

            futures = [
                fleet.submit("lin", x, key=f"t{i}") for i in range(8)
            ]
            report = fleet.rolling_swap(
                "lin", {"w": ht.array(w_new, split=None)}, canary=1
            )
            for f in futures:
                self.assertEqual(np.asarray(f.result(30)).shape, (2, _O))

            self.assertFalse(report["rolled_back"])
            self.assertEqual(
                sorted(report["swapped"]), sorted(r.name for r in fleet.replicas)
            )
            got = np.asarray(fleet.predict("lin", x, key="post"))
            np.testing.assert_allclose(got, x @ w_new, rtol=1e-4, atol=1e-4)
            self.assertEqual(
                telemetry.serving_report()["step_compiles"], steps_before,
                "a rolling swap is new operands, not a retrace",
            )
            self.assertEqual(
                telemetry.snapshot_group("fusion").get("misses", 0), fusion_before
            )
            self.assertEqual(
                telemetry.snapshot_group("overlap").get("ring_builds", 0),
                ring_before,
            )
        finally:
            fleet.close()

    def test_canary_regression_rolls_back_old_weights_still_serving(self):
        fleet = _fleet(n=2)
        try:
            w_old, w_new = _weights(), _weights()
            _register_linear(fleet, w_old)
            x = _RNG.normal(size=(2, _F)).astype(np.float32)
            for i in range(8):  # baselines come from the warm reservoirs
                fleet.predict("lin", x, key=f"w{i}")
            canary = fleet.replicas[0].name
            # every post-swap canary probe fails through the real step
            # path; concurrent traffic rides failover meanwhile
            inj = fault.FaultInjector().error_in(
                f"serving.step.{canary}", times=64
            )
            with fault.injected(inj):
                futures = [
                    fleet.submit("lin", x, key=f"t{i}") for i in range(8)
                ]
                report = fleet.rolling_swap(
                    "lin", {"w": ht.array(w_new, split=None)}, canary=1
                )
                for f in futures:
                    self.assertEqual(np.asarray(f.result(30)).shape, (2, _O))
            self.assertTrue(report["rolled_back"])
            self.assertIn(canary, report["reason"])
            self.assertEqual(report["swapped"], [])
            self.assertGreaterEqual(fleet.stats()["rollbacks"], 1)
            # both replicas serve the OLD weights again
            for key in ("post0", "post1", "post2", "post3"):
                got = np.asarray(fleet.predict("lin", x, key=key))
                np.testing.assert_allclose(got, x @ w_old, rtol=1e-4, atol=1e-4)
        finally:
            fleet.close()

    def test_shared_model_refuses_canary_swap(self):
        fleet = _fleet(n=2)
        try:
            shared = _Linear(_weights())
            fleet.register(
                "sh", shared, feature_dim=_F, min_bucket=8, max_batch=16
            )
            with self.assertRaisesRegex(ValueError, "models="):
                fleet.rolling_swap("sh", {"w": shared.w})
        finally:
            fleet.close()


class TestRouterTelemetry(TestCase):
    def test_router_gauges_reach_prometheus_and_report(self):
        fleet = _fleet(n=2)
        try:
            _register_linear(fleet, _weights())
            x = np.ones((1, _F), dtype=np.float32)
            for i in range(4):
                fleet.predict("lin", x, key=i)
            prom = telemetry.export_prometheus()
            self.assertIn("heat_tpu_router_dispatched", prom)
            self.assertIn("heat_tpu_router_failovers", prom)
            self.assertIn("heat_tpu_router_ejections", prom)
            report = telemetry.router_report()
            self.assertGreaterEqual(report["dispatched"], 4)
            self.assertEqual(report["lost_futures"], 0)
        finally:
            fleet.close()

    def test_health_transitions_reach_flight_recorder(self):
        with telemetry.telemetry_level("events"):
            telemetry.clear_events()
            fleet = _fleet(n=2)
            try:
                _register_linear(fleet, _weights())
                x = np.ones((1, _F), dtype=np.float32)
                pinned = _key_for(fleet, "r0")
                inj = fault.FaultInjector().error_in("serving.step.r0", times=3)
                with fault.injected(inj):
                    for _ in range(4):
                        fleet.predict("lin", x, key=pinned)
                    self.assertTrue(_wait_all_healthy(fleet))
                kinds = {e["kind"] for e in telemetry.events()}
                self.assertIn("router_health", kinds)
                self.assertIn("router_probe", kinds)
            finally:
                fleet.close()


class TestFleetLifecycle(TestCase):
    def test_close_drains_and_rejects_new_work(self):
        fleet = _fleet(n=2)
        _register_linear(fleet, _weights(), max_delay_s=30.0)
        x = np.ones((2, _F), dtype=np.float32)
        futures = [fleet.submit("lin", x, key=i) for i in range(4)]
        fleet.close()
        for f in futures:
            self.assertEqual(np.asarray(f.result(10)).shape, (2, _O))
        with self.assertRaisesRegex(RequestRejected, "closed"):
            fleet.submit("lin", x)
        fleet.close()  # idempotent

    def test_context_manager(self):
        with _fleet(n=1) as fleet:
            _register_linear(fleet, _weights())
            out = fleet.predict("lin", np.ones((1, _F), dtype=np.float32))
            self.assertEqual(np.asarray(out).shape, (1, _O))

    def test_constructor_validation(self):
        with self.assertRaises(ValueError):
            ServingFleet(replicas=0)
        with self.assertRaises(ValueError):
            ServingFleet(replicas=2, error_threshold=0)
        fleet = _fleet(n=2)
        try:
            with self.assertRaisesRegex(ValueError, "one model per replica"):
                fleet.register(
                    "bad", models=[_Linear(_weights())], feature_dim=_F
                )
            with self.assertRaises(KeyError):
                fleet.submit("nope", np.ones((1, _F), dtype=np.float32))
        finally:
            fleet.close()


if __name__ == "__main__":
    unittest.main()
