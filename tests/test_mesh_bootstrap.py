"""Multi-host bootstrap helpers (heat_tpu/parallel/mesh.py).

``init_distributed`` is the reference's ``mpirun`` + import-time MPI_WORLD
creation (heat/core/communication.py:1909-1921); single-process it must be
a clean no-op.  ``hybrid_mesh`` is the two-tier NCCL-in-node/MPI-across
topology of DASO (heat/optim/dp_optimizer.py:46) as mesh axes.
"""

import heat_tpu as ht
from .base import TestCase


class TestInitDistributed(TestCase):
    def test_single_process_noop(self):
        from heat_tpu.parallel import init_distributed

        rank, size = init_distributed()
        self.assertEqual((rank, size), (0, 1))

    def test_idempotent(self):
        from heat_tpu.parallel import init_distributed

        self.assertEqual(init_distributed(), init_distributed())


class TestHybridMesh(TestCase):
    def test_ici_only(self):
        from heat_tpu.parallel import hybrid_mesh

        mesh = hybrid_mesh({"split": 4, "tp": 2})
        self.assertEqual(mesh.axis_names, ("split", "tp"))
        self.assertEqual(dict(mesh.shape), {"split": 4, "tp": 2})

    def test_unit_dcn_axis_is_plain_mesh(self):
        """dcn sizes of 1 (single slice) keep the axis for spec
        compatibility without needing slice topology info."""
        from heat_tpu.parallel import hybrid_mesh

        mesh = hybrid_mesh({"split": 8}, {"dp": 1})
        self.assertEqual(mesh.axis_names, ("dp", "split"))
        self.assertEqual(dict(mesh.shape), {"dp": 1, "split": 8})

    def test_mesh_drives_sharded_compute(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from heat_tpu.parallel import hybrid_mesh

        mesh = hybrid_mesh({"split": 4, "tp": 2}, {"dp": 1})
        x = jax.device_put(
            jnp.arange(64.0).reshape(8, 8),
            NamedSharding(mesh, P(("dp", "split"), "tp")),
        )
        self.assertAlmostEqual(float(jnp.sum(x * 2)), 2 * 63 * 64 / 2)

    def test_empty_ici_rejected(self):
        from heat_tpu.parallel import hybrid_mesh

        with self.assertRaises(ValueError):
            hybrid_mesh({})

    def test_duplicate_axis_across_tiers_rejected(self):
        from heat_tpu.parallel import hybrid_mesh

        with self.assertRaises(ValueError):
            hybrid_mesh({"dp": 8}, {"dp": 1})


class TestGraftEntryBootstrap(TestCase):
    """The driver imports __graft_entry__ directly and calls
    dryrun_multichip(8) in a fresh process; round 1 failed because the
    CPU-fallback bootstrap lived only in the __main__ block."""

    @staticmethod
    def _import_graft_entry():
        import os
        import sys

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, repo_root)
        try:
            import __graft_entry__ as ge
        finally:
            sys.path.pop(0)
        return ge

    def test_bootstrap_devices_uses_initialized_backend(self):
        # Force backend init so the bootstrap takes the no-probe path.
        import jax

        jax.devices()
        ge = self._import_graft_entry()
        devices = ge._bootstrap_devices(8)
        self.assertEqual(len(devices), 8)

    def test_bootstrap_devices_raises_when_too_small(self):
        # With backends initialized, an oversized request must raise
        # instead of mutating XLA_FLAGS / re-probing.
        import jax

        jax.devices()
        ge = self._import_graft_entry()
        with self.assertRaises(RuntimeError):
            ge._bootstrap_devices(10**6)


class TestMeshCommSplit(TestCase):
    """Sub-communicators via sub-mesh construction (reference:
    MPICommunication.Split, heat/core/communication.py:470-481)."""

    def test_scalar_color_is_whole_mesh(self):
        from heat_tpu.parallel.mesh import sanitize_comm

        comm = sanitize_comm(None)
        sub = comm.Split(0)
        self.assertEqual(sub.size, comm.size)
        self.assertIsNot(sub, comm)

    def test_sequence_color_partitions(self):
        from heat_tpu.parallel.mesh import sanitize_comm

        comm = sanitize_comm(None)
        colors = [i % 2 for i in range(comm.size)]
        even = comm.Split(colors, key=0)
        odd = comm.Split(colors, key=1)
        self.assertEqual(even.size, (comm.size + 1) // 2)
        self.assertEqual(odd.size, comm.size // 2)
        even_devs = {d.id for d in even.mesh.devices.flat}
        odd_devs = {d.id for d in odd.mesh.devices.flat}
        self.assertFalse(even_devs & odd_devs)

    def test_split_groups_covers_all_devices(self):
        from heat_tpu.parallel.mesh import sanitize_comm

        comm = sanitize_comm(None)
        colors = [i % 3 for i in range(comm.size)]
        groups = comm.split_groups(colors)
        self.assertEqual(set(groups), set(colors))
        total = sum(g.size for g in groups.values())
        self.assertEqual(total, comm.size)

    def test_bad_color_shape_rejected(self):
        from heat_tpu.parallel.mesh import sanitize_comm

        comm = sanitize_comm(None)
        with self.assertRaises(ValueError):
            comm.Split([0, 1])  # wrong length

    def test_out_of_range_key_rejected(self):
        # advisor round 2: MPI-ported `key=rank`-style ordering keys must
        # not silently modulo-wrap into an arbitrary color group
        from heat_tpu.parallel.mesh import sanitize_comm

        comm = sanitize_comm(None)
        colors = [i % 2 for i in range(comm.size)]
        with self.assertRaises(ValueError):
            comm.Split(colors, key=comm.size)
        with self.assertRaises(ValueError):
            comm.Split(colors, key=-1)

    def test_estimator_fit_on_submesh(self):
        """Consumer: a sub-communicator scopes an estimator's collectives to
        a device subset (the reference's reason for Split)."""
        import numpy as np

        from heat_tpu.parallel.mesh import sanitize_comm

        comm = sanitize_comm(None)
        half = comm.Split([0] * (comm.size // 2) + [1] * (comm.size - comm.size // 2), key=0)
        rng = np.random.default_rng(0)
        X = np.concatenate(
            [rng.normal(-5, 0.3, (40, 2)), rng.normal(5, 0.3, (40, 2))]
        ).astype(np.float32)
        x = ht.array(X, split=0, comm=half)
        self.assertEqual(x.comm.size, comm.size // 2)
        km = ht.cluster.KMeans(n_clusters=2, init="kmeans++", max_iter=20)
        km.fit(x)
        centers = np.sort(np.asarray(km.cluster_centers_.numpy())[:, 0])
        np.testing.assert_allclose(centers, [-5, 5], atol=0.5)

    def test_daso_reduced_comms_parity(self):
        import jax
        import numpy as np
        import optax
        from jax.sharding import Mesh

        from heat_tpu.optim import DASO, DataParallelOptimizer
        from heat_tpu.parallel.mesh import MeshComm

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici"))
        comm = MeshComm(mesh, split_axis="ici")
        daso = DASO(DataParallelOptimizer(optax.sgd(0.1)), mesh=mesh, comm=comm)
        self.assertEqual(len(daso.reduced_comms), 4)
        for rc in daso.reduced_comms:
            self.assertEqual(rc.size, 2)  # spans the dcn axis
