"""Printing edge cases and cross-op unbalanced-shard chains.

Reference models: heat/core/tests/test_printing.py (repr shapes,
printoptions, summarization) and the unbalanced-interaction cases spread
through test_manipulations.py/test_dndarray.py (round-3 VERDICT missing
#4: these were untested here relative to the reference's depth).
"""

import numpy as np

import heat_tpu as ht
from .base import TestCase


class TestPrintingEdgeCases(TestCase):
    def tearDown(self):
        ht.set_printoptions(profile="default")
        super().tearDown()

    def test_repr_mentions_metadata(self):
        x = ht.array(np.arange(6, dtype=np.float32), split=0)
        s = repr(x)
        self.assertIn("DNDarray", s)
        self.assertIn("float32", s)
        self.assertIn("split=0", s)

    def test_empty_and_scalarish(self):
        self.assertIsInstance(repr(ht.array(np.zeros((0,), np.float32))), str)
        self.assertIsInstance(repr(ht.array(np.float32(3.5))), str)
        self.assertIsInstance(repr(ht.zeros((0, 3))), str)

    def test_large_array_is_summarized(self):
        x = ht.arange(100000, split=0)
        s = repr(x)
        self.assertLess(len(s), 4000)
        self.assertIn("...", s)

    def test_printoptions_precision(self):
        x = ht.array(np.array([1.23456789], np.float32))
        ht.set_printoptions(precision=2)
        s2 = repr(x)
        ht.set_printoptions(precision=6)
        s6 = repr(x)
        self.assertNotEqual(s2, s6)
        self.assertIn("1.23", s2)

    def test_profiles(self):
        x = ht.array(np.random.default_rng(0).standard_normal((30, 30)).astype(np.float32))
        ht.set_printoptions(profile="short")
        short = repr(x)
        ht.set_printoptions(profile="full")
        full = repr(x)
        self.assertLess(len(short), len(full))

    def test_nan_inf_render(self):
        x = ht.array(np.array([np.nan, np.inf, -np.inf, 0.0], np.float32), split=0)
        s = repr(x)
        self.assertIn("nan", s)
        self.assertIn("inf", s)

    def test_bool_and_int_render(self):
        self.assertIn("True", repr(ht.array(np.array([True, False]))))
        self.assertIn("7", repr(ht.array(np.array([7], np.int64))))

    def test_split_invariant_repr(self):
        A = np.arange(13, dtype=np.float32)
        self.assertEqual(repr(ht.array(A, split=0)).replace("split=0", "X"),
                         repr(ht.array(A)).replace("split=None", "X"))

    def test_print0_writes_once(self):
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            ht.print0("hello", 42)
        self.assertEqual(buf.getvalue().strip(), "hello 42")


class TestUnbalancedShardChains(TestCase):
    """Chains of ops over odd-shaped splits: every intermediate carries
    the even-chunk physical pad, and no op may leak it (the reference's
    unbalanced-interaction cases, test_manipulations.py)."""

    def test_arith_reduce_sort_chain(self):
        rng = np.random.default_rng(0)
        A = rng.standard_normal(29).astype(np.float32)  # 29 over 8 devices
        x = ht.array(A, split=0)
        y = (x * 2 + 1).astype(ht.float64)
        v, _ = ht.sort(y)
        np.testing.assert_allclose(
            v.numpy(), np.sort(A.astype(np.float64) * 2 + 1), rtol=1e-6
        )
        self.assertAlmostEqual(
            float(ht.sum(y)), float((A.astype(np.float64) * 2 + 1).sum()),
            places=3,
        )

    def test_concat_resplit_slice_chain(self):
        rng = np.random.default_rng(1)
        A = rng.standard_normal((11, 3)).astype(np.float32)
        B = rng.standard_normal((6, 3)).astype(np.float32)
        a = ht.array(A, split=0)
        b = ht.array(B, split=0)
        c = ht.concatenate([a, b], axis=0)       # 17 rows: odd again
        d = ht.resplit(c, 1)                     # resplit to 3-wide dim
        e = d[3:15]                              # slice through the pad zone
        np.testing.assert_allclose(
            e.numpy(), np.concatenate([A, B])[3:15], rtol=1e-6
        )

    def test_matmul_of_unbalanced_operands(self):
        rng = np.random.default_rng(2)
        A = rng.standard_normal((13, 7)).astype(np.float32)
        B = rng.standard_normal((7, 5)).astype(np.float32)
        got = ht.matmul(ht.array(A, split=0), ht.array(B, split=1))
        np.testing.assert_allclose(got.numpy(), A @ B, rtol=1e-4, atol=1e-5)

    def test_reduction_axes_through_padding(self):
        rng = np.random.default_rng(3)
        A = rng.standard_normal((9, 5)).astype(np.float32)
        x = ht.array(A, split=0)
        np.testing.assert_allclose(
            ht.sum(x, axis=0).numpy(), A.sum(axis=0), rtol=1e-5
        )
        np.testing.assert_allclose(
            ht.mean(x, axis=1).numpy(), A.mean(axis=1), rtol=1e-5
        )
        # argmax over the split axis must ignore pad zeros even when all
        # data is negative (pad would win a naive max)
        N = -np.abs(A) - 1.0
        xn = ht.array(N.astype(np.float32), split=0)
        self.assertEqual(
            int(ht.argmax(xn, axis=0)[0]), int(N.argmax(axis=0)[0])
        )

    def test_indexing_then_stats_chain(self):
        rng = np.random.default_rng(4)
        A = rng.standard_normal((21, 4)).astype(np.float32)
        x = ht.array(A, split=0)
        sel = x[np.array([1, 4, 7, 9, 16, 20]), :]
        self.assertEqual(sel.split, 0)
        np.testing.assert_allclose(
            ht.std(sel, axis=0).numpy(),
            A[[1, 4, 7, 9, 16, 20]].std(axis=0), rtol=1e-4,
        )

    def test_unique_of_concat_chain(self):
        rng = np.random.default_rng(5)
        D = rng.integers(0, 9, 23).astype(np.int32)
        x = ht.array(D, split=0)
        u = ht.unique(ht.concatenate([x, x], axis=0))
        np.testing.assert_array_equal(np.sort(u.numpy()), np.unique(D))
