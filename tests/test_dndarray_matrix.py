"""DNDarray getitem/setitem/surface matrix (reference model:
heat/core/tests/test_dndarray.py, 1670 LoC).

The reference exhausts the indexing key space — int/slice/ellipsis/
newaxis/advanced/boolean, every split, get and set — plus the DNDarray
object surface (casts, balance, lshape bookkeeping, iteration, diagonal
fill).  This suite rebuilds that matrix against NumPy oracles on the
8-device mesh; every distributed assertion goes through
``assert_array_equal``'s per-shard slab check, so physical-layout bugs
fail even when the gathered value is right.
"""

import numpy as np

import heat_tpu as ht
from .base import TestCase


def _splits(ndim):
    return [None] + list(range(ndim))


class TestGetitemBasicKeys(TestCase):
    def setUp(self):
        self.v = np.arange(13, dtype=np.float32)
        self.m = np.arange(91, dtype=np.float32).reshape(13, 7)
        self.t = np.arange(105, dtype=np.float32).reshape(5, 3, 7)

    def test_scalar_int_1d(self):
        for i in (0, 5, 12, -1, -13):
            for s in (None, 0):
                with self.subTest(i=i, split=s):
                    x = ht.array(self.v, split=s)
                    self.assertEqual(float(x[i].numpy()), self.v[i])

    def test_scalar_int_2d_rows(self):
        for i in (0, 6, -1):
            expected = self.m[i]
            for s in _splits(2):
                with self.subTest(i=i, split=s):
                    r = ht.array(self.m, split=s)[i]
                    self.assert_array_equal(r, expected)

    def test_int_pair_2d(self):
        for key in [(0, 0), (12, 6), (-1, -1), (3, -2)]:
            for s in _splits(2):
                with self.subTest(key=key, split=s):
                    x = ht.array(self.m, split=s)
                    self.assertEqual(float(x[key].numpy()), self.m[key])

    def test_slice_sweep_1d(self):
        slices = [
            slice(None), slice(2, 9), slice(None, 5), slice(7, None),
            slice(None, None, 2), slice(1, 12, 3), slice(None, None, -1),
            slice(10, 2, -2), slice(5, 5), slice(20, 30), slice(-4, None),
            slice(None, -8), slice(-1, None, -1),
        ]
        for sl in slices:
            expected = self.v[sl]
            for s in (None, 0):
                with self.subTest(sl=sl, split=s):
                    r = ht.array(self.v, split=s)[sl]
                    self.assert_array_equal(r, expected)

    def test_slice_pairs_2d(self):
        keys = [
            (slice(2, 9), slice(1, 5)),
            (slice(None, None, 2), slice(None, None, 3)),
            (slice(None, None, -1), slice(None)),
            (slice(3, 3), slice(None)),
            (slice(-5, None), slice(None, -2)),
        ]
        for key in keys:
            expected = self.m[key]
            for s in _splits(2):
                with self.subTest(key=key, split=s):
                    r = ht.array(self.m, split=s)[key]
                    self.assert_array_equal(r, expected)

    def test_int_slice_mixes_3d(self):
        keys = [
            (2,),
            (2, slice(None), slice(1, 5)),
            (slice(None), 1, slice(None)),
            (slice(1, 4), slice(None), 3),
            (-1, -1),
            (slice(None), slice(None), -2),
        ]
        for key in keys:
            expected = self.t[key]
            for s in _splits(3):
                with self.subTest(key=key, split=s):
                    r = ht.array(self.t, split=s)[key]
                    self.assert_array_equal(r, expected)

    def test_ellipsis_forms(self):
        keys = [
            (Ellipsis,),
            (Ellipsis, 0),
            (0, Ellipsis),
            (1, Ellipsis, 2),
            (Ellipsis, slice(1, 4)),
        ]
        for key in keys:
            expected = self.t[key]
            for s in _splits(3):
                with self.subTest(key=key, split=s):
                    r = ht.array(self.t, split=s)[key]
                    if np.isscalar(expected) or expected.ndim == 0:
                        np.testing.assert_allclose(r.numpy(), expected)
                    else:
                        self.assert_array_equal(r, expected)

    def test_newaxis_forms(self):
        keys = [
            (None,),
            (None, slice(None)),
            (slice(None), None),
            (None, Ellipsis, None),
        ]
        for key in keys:
            expected = self.v[key]
            for s in (None, 0):
                with self.subTest(key=key, split=s):
                    r = ht.array(self.v, split=s)[key]
                    self.assert_array_equal(r, expected)

    def test_out_of_bounds_raises(self):
        x = ht.array(self.v, split=0)
        with self.assertRaises(IndexError):
            x[13]
        with self.assertRaises(IndexError):
            x[-14]

    def test_too_many_indices_raises(self):
        x = ht.array(self.m, split=0)
        with self.assertRaises(IndexError):
            x[0, 0, 0]


class TestGetitemAdvancedKeys(TestCase):
    def setUp(self):
        rng = np.random.default_rng(61)
        self.v = rng.standard_normal(17).astype(np.float32)
        self.m = rng.standard_normal((11, 6)).astype(np.float32)

    def test_int_array_1d_variants(self):
        idxs = [
            [0], [16], [-1], [3, 3, 3], [2, 9, 4, 0], [-1, -17, 5],
            list(range(17)), list(range(16, -1, -1)),
        ]
        for idx in idxs:
            expected = self.v[idx]
            for s in (None, 0):
                with self.subTest(idx=idx, split=s):
                    r = ht.array(self.v, split=s)[idx]
                    self.assert_array_equal(r, expected)

    def test_int_array_rows_2d(self):
        idx = [0, 5, 10, 2, 2]
        expected = self.m[idx]
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.array(self.m, split=s)[idx]
                self.assert_array_equal(r, expected)

    def test_int_array_cols_2d(self):
        idx = [5, 0, 3]
        expected = self.m[:, idx]
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.array(self.m, split=s)[:, idx]
                self.assert_array_equal(r, expected)

    def test_cross_product_pairs(self):
        rows = np.array([0, 4, 10])
        cols = np.array([1, 5, 2])
        expected = self.m[rows, cols]
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.array(self.m, split=s)[rows, cols]
                self.assert_array_equal(r, expected)

    def test_2d_index_array(self):
        idx = np.array([[0, 3], [7, 1]])
        expected = self.v[idx]
        for s in (None, 0):
            with self.subTest(split=s):
                r = ht.array(self.v, split=s)[idx]
                self.assert_array_equal(r, expected)

    def test_dndarray_as_index(self):
        idx = ht.array(np.array([2, 8, 0]), split=0)
        expected = self.v[[2, 8, 0]]
        r = ht.array(self.v, split=0)[idx]
        self.assert_array_equal(r, expected)

    def test_advanced_plus_slice(self):
        idx = [1, 9, 3]
        expected = self.m[idx, 1:5]
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.array(self.m, split=s)[idx, 1:5]
                self.assert_array_equal(r, expected)

    def test_boolean_1d_masks(self):
        masks = [
            self.v > 0,
            self.v < -10,             # empty result
            np.ones(17, np.bool_),
            np.zeros(17, np.bool_),
        ]
        for mask in masks:
            expected = self.v[mask]
            for s in (None, 0):
                with self.subTest(n=mask.sum(), split=s):
                    r = ht.array(self.v, split=s)[ht.array(mask, split=s)]
                    self.assert_array_equal(r, expected)

    def test_boolean_rowmask_2d(self):
        mask = self.m[:, 0] > 0
        expected = self.m[mask]
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.array(self.m, split=s)[ht.array(mask)]
                self.assert_array_equal(r, expected)

    def test_boolean_full_mask_2d(self):
        mask = self.m > 0.3
        expected = self.m[mask]
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.array(self.m, split=s)[ht.array(mask, split=s)]
                self.assert_array_equal(r, expected)

    def test_mask_then_chain(self):
        # a masked result feeds further ops: shape metadata must be real
        mask = self.v > 0
        x = ht.array(self.v, split=0)[ht.array(mask, split=0)]
        y = (x * 2.0) + 1.0
        self.assert_array_equal(y, self.v[mask] * 2 + 1)
        v, _ = ht.sort(y, axis=0)
        self.assert_array_equal(v, np.sort(self.v[mask] * 2 + 1))

    def test_wrong_mask_length_raises(self):
        x = ht.array(self.v, split=0)
        with self.assertRaises((ValueError, IndexError)):
            x[ht.array(np.ones(5, np.bool_))]


class TestSetitemMatrix(TestCase):
    def setUp(self):
        self.v = np.arange(13, dtype=np.float32)
        self.m = np.arange(91, dtype=np.float32).reshape(13, 7)

    def _roundtrip_1d(self, key, value, split):
        expected = self.v.copy()
        expected[key] = value
        x = ht.array(self.v, split=split)
        x[key] = value
        self.assert_array_equal(x, expected)

    def _roundtrip_2d(self, key, value, split):
        expected = self.m.copy()
        expected[key] = value
        x = ht.array(self.m, split=split)
        x[key] = value
        self.assert_array_equal(x, expected)

    def test_scalar_int_assign(self):
        for i in (0, 6, -1):
            for s in (None, 0):
                with self.subTest(i=i, split=s):
                    self._roundtrip_1d(i, -5.0, s)

    def test_slice_assign_scalar(self):
        for sl in [slice(2, 9), slice(None, None, 2), slice(None, None, -1), slice(8, 3, -2)]:
            for s in (None, 0):
                with self.subTest(sl=sl, split=s):
                    self._roundtrip_1d(sl, 7.5, s)

    def test_slice_assign_array(self):
        sl = slice(3, 9)
        val = np.arange(6, dtype=np.float32) * -1
        for s in (None, 0):
            with self.subTest(split=s):
                self._roundtrip_1d(sl, val, s)

    def test_row_assign_2d(self):
        val = np.full(7, -3.0, np.float32)
        for i in (0, 5, -1):
            for s in _splits(2):
                with self.subTest(i=i, split=s):
                    self._roundtrip_2d(i, val, s)

    def test_col_assign_2d(self):
        key = (slice(None), 3)
        val = np.arange(13, dtype=np.float32)
        for s in _splits(2):
            with self.subTest(split=s):
                self._roundtrip_2d(key, val, s)

    def test_block_assign_2d(self):
        key = (slice(2, 9), slice(1, 5))
        val = np.ones((7, 4), np.float32) * 2.5
        for s in _splits(2):
            with self.subTest(split=s):
                self._roundtrip_2d(key, val, s)

    def test_broadcast_value_2d(self):
        key = (slice(2, 9), slice(None))
        val = np.arange(7, dtype=np.float32)  # broadcasts over rows
        for s in _splits(2):
            with self.subTest(split=s):
                self._roundtrip_2d(key, val, s)

    def test_advanced_assign_1d(self):
        idx = [0, 4, 11]
        for s in (None, 0):
            with self.subTest(split=s):
                self._roundtrip_1d(idx, np.asarray([9.0, 8.0, 7.0], np.float32), s)

    def test_advanced_assign_rows(self):
        idx = [1, 7]
        val = np.ones((2, 7), np.float32) * -1
        for s in _splits(2):
            with self.subTest(split=s):
                self._roundtrip_2d(idx, val, s)

    def test_boolean_assign_1d(self):
        mask = self.v % 2 == 0
        for s in (None, 0):
            with self.subTest(split=s):
                expected = self.v.copy()
                expected[mask] = 0.5
                x = ht.array(self.v, split=s)
                x[ht.array(mask, split=s)] = 0.5
                self.assert_array_equal(x, expected)

    def test_boolean_full_assign_2d(self):
        mask = self.m > 45
        for s in _splits(2):
            with self.subTest(split=s):
                expected = self.m.copy()
                expected[mask] = -1.0
                x = ht.array(self.m, split=s)
                x[ht.array(mask, split=s)] = -1.0
                self.assert_array_equal(x, expected)

    def test_dndarray_value_cross_split(self):
        val_host = np.full((5, 7), 4.0, np.float32)
        for s_target in _splits(2):
            for s_val in _splits(2):
                with self.subTest(s_target=s_target, s_val=s_val):
                    expected = self.m.copy()
                    expected[4:9] = val_host
                    x = ht.array(self.m, split=s_target)
                    x[4:9] = ht.array(val_host, split=s_val)
                    self.assert_array_equal(x, expected)

    def test_value_dtype_casts_to_target(self):
        x = ht.array(self.v.astype(np.int32), split=0)
        x[2:5] = 7.9  # float assigned into int array: trunc-cast like numpy
        expected = self.v.astype(np.int32).copy()
        expected[2:5] = int(7.9)
        self.assert_array_equal(x, expected)
        self.assertEqual(x.dtype, ht.int32)

    def test_setitem_keeps_split(self):
        for s in _splits(2):
            x = ht.array(self.m, split=s)
            x[0] = 0.0
            self.assertEqual(x.split, s)

    def test_setitem_shape_mismatch_raises(self):
        x = ht.array(self.m, split=0)
        with self.assertRaises((ValueError, TypeError)):
            x[0:3] = np.ones((2, 7), np.float32)

    def test_chained_setitems(self):
        expected = self.m.copy()
        x = ht.array(self.m, split=0)
        expected[0] = 1.0
        x[0] = 1.0
        expected[:, 2] = 2.0
        x[:, 2] = 2.0
        expected[5:9, 1:3] = 3.0
        x[5:9, 1:3] = 3.0
        expected[expected > 50] = 0.0
        x[x > 50] = 0.0
        self.assert_array_equal(x, expected)


class TestDNDarraySurface(TestCase):
    def setUp(self):
        self.m = np.arange(91, dtype=np.float32).reshape(13, 7)

    def test_astype_matrix(self):
        pairs = [
            (np.float32, ht.int32), (np.float32, ht.float64),
            (np.float32, ht.bool), (np.int32, ht.float32),
            (np.float32, ht.bfloat16), (np.int64, ht.int32),
        ]
        for src_dt, dst in pairs:
            for s in _splits(2):
                with self.subTest(pair=(src_dt, dst), split=s):
                    host = self.m.astype(src_dt)
                    x = ht.array(host, split=s).astype(dst)
                    self.assertEqual(x.dtype, dst)
                    got = x.numpy().astype(np.float64)
                    want = host.astype(
                        np.dtype(np.bool_) if dst == ht.bool else np.float64
                    ).astype(np.float64)
                    np.testing.assert_allclose(got, want, rtol=1e-2)

    def test_shape_bookkeeping_every_split(self):
        for s in _splits(2):
            x = ht.array(self.m, split=s)
            self.assertEqual(tuple(x.shape), (13, 7))
            self.assertEqual(tuple(x.gshape), (13, 7))
            self.assertEqual(x.ndim, 2)
            self.assertEqual(x.size, 91)
            self.assertEqual(x.split, s)
            if s is not None:
                lmap = np.asarray(x.lshape_map)
                self.assertEqual(lmap.shape, (self.get_size(), 2))
                self.assertEqual(int(lmap[:, s].sum()), self.m.shape[s])
                other = 1 - s
                self.assertTrue((lmap[:, other] == self.m.shape[other]).all())

    def test_lshards_concatenate_to_global(self):
        for s in (0, 1):
            x = ht.array(self.m, split=s)
            parts = x.lshards()
            glued = np.concatenate(parts, axis=s)
            np.testing.assert_array_equal(glued, self.m)

    def test_item_and_casts(self):
        one = ht.array(np.asarray([[3.5]], np.float32), split=0)
        self.assertEqual(one.item(), 3.5)
        self.assertEqual(float(one), 3.5)
        self.assertEqual(int(one), 3)
        self.assertTrue(bool(one))

    def test_cast_multi_element_raises(self):
        x = ht.array(self.m, split=0)
        with self.assertRaises((ValueError, TypeError)):
            bool(x)
        with self.assertRaises((ValueError, TypeError)):
            float(x)

    def test_len_and_iter(self):
        x = ht.array(self.m, split=0)
        self.assertEqual(len(x), 13)
        rows = [r.numpy() for r in x]
        self.assertEqual(len(rows), 13)
        np.testing.assert_array_equal(np.stack(rows), self.m)

    def test_transpose_property(self):
        for s in _splits(2):
            x = ht.array(self.m, split=s)
            self.assert_array_equal(x.T, self.m.T)

    def test_real_imag(self):
        host = (self.m + 1j * (self.m * 2)).astype(np.complex64)
        for s in _splits(2):
            x = ht.array(host, split=s)
            self.assert_array_equal(x.real, host.real)
            self.assert_array_equal(x.imag, host.imag)

    def test_fill_diagonal(self):
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(self.m, split=s)
                x.fill_diagonal(-1.0)
                expected = self.m.copy()
                np.fill_diagonal(expected, -1.0)
                self.assert_array_equal(x, expected)

    def test_array_protocol(self):
        x = ht.array(self.m, split=0)
        np.testing.assert_array_equal(np.asarray(x), self.m)
        self.assertEqual(np.asarray(x, dtype=np.int32).dtype, np.int32)

    def test_tolist(self):
        x = ht.array(self.m[:3], split=0)
        self.assertEqual(x.tolist(), self.m[:3].tolist())

    def test_nbytes_and_lnumel(self):
        x = ht.array(self.m, split=0)
        self.assertEqual(x.nbytes, 91 * 4)
        total = sum(int(np.prod(s.shape)) for s in x.lshards())
        self.assertEqual(total, 91)

    def test_inplace_arith_keeps_identity_and_split(self):
        for s in _splits(2):
            x = ht.array(self.m, split=s)
            x += 1.0
            x *= 2.0
            self.assertEqual(x.split, s)
            self.assert_array_equal(x, (self.m + 1) * 2)

    def test_is_distributed_and_balanced(self):
        x = ht.array(self.m, split=0)
        self.assertTrue(x.is_distributed())
        self.assertTrue(x.is_balanced())
        r = ht.array(self.m, split=None)
        self.assertFalse(r.is_distributed())

    def test_counts_displs(self):
        x = ht.array(self.m, split=0)
        counts, displs = x.counts_displs()
        self.assertEqual(int(np.sum(counts)), 13)
        self.assertEqual(int(displs[0]), 0)
        np.testing.assert_array_equal(
            np.cumsum(counts)[:-1], np.asarray(displs[1:])
        )

    def test_stride_tuple_matches_numpy(self):
        x = ht.array(self.m, split=None)
        self.assertEqual(tuple(x.strides), self.m.strides)


class TestGetSetChains(TestCase):
    """get/set interleavings over distributed arrays — the reference's
    hardest dndarray cases chain mutation with selection."""

    def test_set_then_get_roundtrip(self):
        host = np.arange(60, dtype=np.float32).reshape(12, 5)
        for s in _splits(2):
            with self.subTest(split=s):
                expected = host.copy()
                x = ht.array(host, split=s)
                expected[3:7] = -1
                x[3:7] = -1
                np.testing.assert_array_equal(
                    x[2:8].numpy(), expected[2:8]
                )

    def test_get_slice_set_into_other(self):
        host = np.arange(40, dtype=np.float32).reshape(8, 5)
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                y = ht.zeros((4, 5), split=s)
                y[:] = x[2:6]
                self.assert_array_equal(y, host[2:6])

    def test_masked_set_then_masked_get(self):
        host = np.arange(29, dtype=np.float32)
        x = ht.array(host, split=0)
        mask = x > 20
        x[mask] = 0.0
        expected = host.copy()
        expected[host > 20] = 0.0
        got_mask = x < 5
        self.assert_array_equal(x[got_mask], expected[expected < 5])

    def test_row_swap_via_indexing(self):
        host = np.arange(35, dtype=np.float32).reshape(7, 5)
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                tmp = x[0].numpy().copy()
                x[0] = x[6]
                x[6] = tmp
                expected = host.copy()
                expected[[0, 6]] = expected[[6, 0]]
                self.assert_array_equal(x, expected)

    def test_diagonal_update_chain(self):
        host = np.zeros((9, 9), np.float32)
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                x.fill_diagonal(2.0)
                y = x + ht.array(np.eye(9, dtype=np.float32), split=s)
                expected = np.zeros((9, 9), np.float32)
                np.fill_diagonal(expected, 2.0)
                expected = expected + np.eye(9, dtype=np.float32)
                self.assert_array_equal(y, expected)


class TestScalarBoolKeys(TestCase):
    """Round-4 advisor: scalar bools are 0-d masks, not integer indices."""

    def test_true_on_size1_dim(self):
        host = np.ones((1, 3), np.float32)
        x = ht.array(host)
        self.assert_array_equal(x[True], host[True])

    def test_false_on_size1_dim(self):
        host = np.ones((1, 3), np.float32)
        x = ht.array(host)
        self.assertEqual(x[False].shape, host[False].shape)

    def test_scalar_bool_split_array(self):
        host = np.arange(24, dtype=np.float32).reshape(8, 3)
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                got = x[True]
                self.assert_array_equal(got, host[True])
                self.assertEqual(x[False].shape, host[False].shape)

    def test_scalar_bool_in_tuple(self):
        host = np.arange(12, dtype=np.float32).reshape(4, 3)
        x = ht.array(host, split=0)
        self.assert_array_equal(x[True, 1:], host[True, 1:])

    def test_np_bool_scalar(self):
        host = np.ones((1, 3), np.float32)
        x = ht.array(host)
        self.assert_array_equal(x[np.bool_(True)], host[np.bool_(True)])


class TestBoolListKeys(TestCase):
    """Round-4 advisor: bool lists in tuple keys are masks, not int arrays."""

    def test_bool_list_on_size1_dim(self):
        host = np.ones((1, 3), np.float32)
        x = ht.array(host)
        self.assert_array_equal(x[[True], :], host[[True], :])

    def test_bool_list_mask_rows(self):
        host = np.arange(20, dtype=np.float32).reshape(5, 4)
        sel = [True, False, True, False, True]
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                self.assert_array_equal(x[sel, :], host[sel, :])

    def test_int_list_in_tuple_is_advanced(self):
        host = np.arange(20, dtype=np.float32).reshape(5, 4)
        x = ht.array(host, split=0)
        self.assert_array_equal(x[:, [0, 2]], host[:, [0, 2]])
        with self.assertRaises(IndexError):
            x[[0, 9], :]


class TestStackFamilyErrors(TestCase):
    """Round-4 advisor: explicit TypeError when no DNDarray input."""

    def test_no_dndarray_raises_typeerror(self):
        for fn in (ht.vstack, ht.hstack, ht.dstack, ht.column_stack, ht.stack):
            with self.subTest(fn=fn.__name__):
                with self.assertRaises(TypeError):
                    fn([np.ones(3), np.ones(3)])


class TestReviewFoundEdges(TestCase):
    """Round-5 review findings on the scalar-bool fix itself."""

    def test_scalar_bool_then_mask(self):
        host = np.arange(4, dtype=np.float32)
        x = ht.array(host)
        sel = np.array([True, False, True, False])
        self.assert_array_equal(x[True, sel], host[True, sel])

    def test_ellipsis_with_2d_mask(self):
        host = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        mask = host[0] > 5
        for s in _splits(3):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                self.assert_array_equal(x[..., mask], host[..., mask])

    def test_bare_list_out_of_bounds(self):
        x = ht.array(np.arange(20, dtype=np.float32).reshape(5, 4))
        with self.assertRaises(IndexError):
            x[[0, 9]]


class TestScalarBoolAdvancedBlock(TestCase):
    """Round-5 second review pass: scalar bools (and 0-d bool arrays) join
    the advanced block — contiguity/placement — while consuming and
    producing no dimension."""

    def test_bool_joins_block(self):
        host = np.arange(20, dtype=np.float32).reshape(5, 4)
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                self.assert_array_equal(x[[0, 2], True], host[[0, 2], True])

    def test_bool_forces_front_placement(self):
        host = np.arange(30, dtype=np.float32).reshape(2, 5, 3)
        for s in _splits(3):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                self.assert_array_equal(
                    x[True, :, [0, 2]], host[True, :, [0, 2]])
                self.assert_array_equal(
                    x[:, [0, 2], True], host[:, [0, 2], True])
                self.assert_array_equal(
                    x[0, True, [0, 2]], host[0, True, [0, 2]])

    def test_zero_d_bool_array_is_mask(self):
        host = np.arange(20, dtype=np.float32).reshape(5, 4)
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                self.assert_array_equal(
                    x[np.array(True)], host[np.array(True)])
                self.assert_array_equal(
                    x[:, np.array(True)], host[:, np.array(True)])


class TestSetitemSliceMatrix(TestCase):
    """Negative-step / negative-bound slice assignment at reference depth
    (heat/core/tests/test_dndarray.py's setitem matrix)."""

    SLICES_1D = [
        slice(None), slice(2, 9), slice(-5, None), slice(None, -3),
        slice(None, None, 2), slice(None, None, -1), slice(9, 2, -1),
        slice(-2, 1, -2), slice(11, None, -3), slice(5, 5),
    ]

    def test_scalar_into_1d_slices(self):
        host = np.arange(13, dtype=np.float32)
        for s in (None, 0):
            for sl in self.SLICES_1D:
                with self.subTest(split=s, sl=sl):
                    x = ht.array(host, split=s)
                    e = host.copy()
                    x[sl] = -7.0
                    e[sl] = -7.0
                    self.assert_array_equal(x, e)

    def test_vector_into_1d_slices(self):
        host = np.arange(13, dtype=np.float32)
        for s in (None, 0):
            for sl in self.SLICES_1D:
                want = len(range(*sl.indices(13)))
                if want == 0:
                    continue
                with self.subTest(split=s, sl=sl):
                    x = ht.array(host, split=s)
                    e = host.copy()
                    v = np.linspace(100, 200, want).astype(np.float32)
                    x[sl] = v
                    e[sl] = v
                    self.assert_array_equal(x, e)

    PAIRS_2D = [
        (slice(None, None, -1), slice(None)),
        (slice(2, 11, 2), slice(1, 6)),
        (slice(-1, 2, -3), slice(None, None, -2)),
        (slice(None), slice(6, 0, -1)),
        (slice(10, None, -2), slice(-3, None)),
        (slice(12, 0, -4), slice(0, 7, 3)),
    ]

    def test_2d_mixed_slice_pairs(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            for key in self.PAIRS_2D:
                with self.subTest(split=s, key=key):
                    x = ht.array(host, split=s)
                    e = host.copy()
                    x[key] = 0.5
                    e[key] = 0.5
                    self.assert_array_equal(x, e)

    def test_block_into_reversed_rows(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        block = np.arange(21, dtype=np.float32).reshape(3, 7) * -1
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[8:2:-2] = block
                e[8:2:-2] = block
                self.assert_array_equal(x, e)


class TestSetitemCrossSplitValues(TestCase):
    """DNDarray values whose split differs from the target's (reference:
    cross-split value assignment, test_dndarray.py)."""


    def test_row_from_other_array(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        other = ht.array(host * 10, split=0)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                x[0] = other[12]
                e = host.copy()
                e[0] = host[12] * 10
                self.assert_array_equal(x, e)

    def test_column_cross_split(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        col = ht.array(np.full(13, 9.0, np.float32), split=0)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                x[:, 2] = col
                e = host.copy()
                e[:, 2] = 9.0
                self.assert_array_equal(x, e)


class TestSetitemAdvancedBroadcast(TestCase):
    """Scalar/array broadcast onto advanced keys (reference:
    test_dndarray.py's advanced setitem block)."""

    def test_scalar_onto_int_array_key(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        rows = np.array([0, 5, 12, -1, 3])
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[rows] = 3.25
                e[rows] = 3.25
                self.assert_array_equal(x, e)

    def test_row_vector_broadcast_onto_rows(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        rows = np.array([2, 7, 11])
        v = np.arange(7, dtype=np.float32) * -2
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[rows] = v           # (7,) broadcast over 3 rows
                e[rows] = v
                self.assert_array_equal(x, e)

    def test_full_block_onto_rows(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        rows = np.array([1, 4, 9])
        block = np.arange(21, dtype=np.float32).reshape(3, 7) + 100
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[rows] = block
                e[rows] = block
                self.assert_array_equal(x, e)

    def test_vector_onto_paired_keys(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        rows = np.array([0, 6, 12])
        cols = np.array([1, 0, -1])
        vals = np.array([10.0, 20.0, 30.0], np.float32)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[rows, cols] = vals
                e[rows, cols] = vals
                self.assert_array_equal(x, e)

    def test_scalar_onto_mask_selection(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        mask = (host % 5) == 0
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[mask] = 0.0
                e[mask] = 0.0
                self.assert_array_equal(x, e)

    def test_column_key_with_slice(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        cols = np.array([0, 3, -2])
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[2:9, cols] = -1.0
                e[2:9, cols] = -1.0
                self.assert_array_equal(x, e)

    def test_dtype_cast_on_assign(self):
        host = np.arange(20, dtype=np.float32).reshape(4, 5)
        x = ht.array(host, split=0)
        x[1] = np.arange(5)           # int value into float target
        e = host.copy()
        e[1] = np.arange(5)
        self.assert_array_equal(x, e)
        self.assertIs(x.dtype, ht.float32)


class TestSetitemChainedAndAugmented(TestCase):
    def test_augmented_on_slice(self):
        host = np.arange(13, dtype=np.float32)
        for s in (None, 0):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[2:9] += 10.0
                e[2:9] += 10.0
                self.assert_array_equal(x, e)

    def test_augmented_on_rows_2d(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[3:6] *= 2.0
                e[3:6] *= 2.0
                self.assert_array_equal(x, e)

    def test_sequential_overlapping_writes(self):
        host = np.zeros(29, np.float32)
        x = ht.array(host, split=0)
        e = host.copy()
        for lo, hi, v in ((0, 15, 1.0), (10, 25, 2.0), (20, 29, 3.0)):
            x[lo:hi] = v
            e[lo:hi] = v
        self.assert_array_equal(x, e)

    def test_write_then_reduce(self):
        # pad hygiene: a write followed by a split-axis reduction must not
        # see stale or leaked pad values
        host = np.arange(13, dtype=np.float32)
        x = ht.array(host, split=0)
        x[5:] = 1.0
        e = host.copy()
        e[5:] = 1.0
        self.assertEqual(float(x.sum()), float(e.sum()))
        self.assertEqual(float(x.max()), float(e.max()))


class TestSetitemEmptyAndEdge(TestCase):
    def test_empty_slice_is_noop(self):
        host = np.arange(13, dtype=np.float32)
        for s in (None, 0):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                x[5:5] = 99.0
                self.assert_array_equal(x, host)

    def test_empty_int_array_is_noop(self):
        host = np.arange(13, dtype=np.float32)
        x = ht.array(host, split=0)
        x[np.array([], np.int64)] = 99.0
        self.assert_array_equal(x, host)

    def test_setitem_oob_int_raises(self):
        x = ht.array(np.zeros(5, np.float32), split=0)
        with self.assertRaises(IndexError):
            x[7] = 1.0
        with self.assertRaises(IndexError):
            x[-6] = 1.0

    def test_setitem_oob_array_raises(self):
        x = ht.array(np.zeros((5, 3), np.float32), split=0)
        with self.assertRaises(IndexError):
            x[np.array([0, 5])] = 1.0

    def test_ellipsis_setitem(self):
        host = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        for s in _splits(3):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[..., 1] = -5.0
                e[..., 1] = -5.0
                self.assert_array_equal(x, e)
                x[0, ...] = 7.0
                e[0, ...] = 7.0
                self.assert_array_equal(x, e)

    def test_newaxis_setitem_fallback(self):
        host = np.arange(12, dtype=np.float32).reshape(4, 3)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[None, 2] = 1.5
                e[None, 2] = 1.5
                self.assert_array_equal(x, e)

    def test_iteration_protocol_after_writes(self):
        host = np.arange(15, dtype=np.float32).reshape(5, 3)
        x = ht.array(host, split=0)
        x[2] = 0.0
        e = host.copy()
        e[2] = 0.0
        rows = [r.numpy() for r in x]
        self.assertEqual(len(rows), 5)
        for got, exp in zip(rows, e):
            np.testing.assert_array_equal(got, exp)


class TestGetitemSliceMatrixDeep(TestCase):
    """Negative-step / negative-bound GETITEM matrix mirroring the setitem
    classes above (reference: test_dndarray.py's slice tables)."""


    def test_2d_pair_table(self):
        # same table as the setitem matrix (one literal, two directions)
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            for key in TestSetitemSliceMatrix.PAIRS_2D:
                with self.subTest(split=s, key=key):
                    x = ht.array(host, split=s)
                    self.assert_array_equal(x[key], host[key])

    def test_get_then_set_composition(self):
        # rows 0..5 get rows 1,3,5,7,9,11's values — a sharded get feeding
        # a sharded set on the same array
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                e = host.copy()
                x[0:6] = x[1::2]
                e[0:6] = e[1::2]
                self.assert_array_equal(x, e)


class TestScalarCastsAndProtocols(TestCase):
    """Only the case TestDNDarraySurface doesn't already cover: a fully
    consumed key returns a replicated 0-d DNDarray for every input split."""

    def test_scalar_getitem_returns_0d(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                got = x[4, 5]
                self.assertEqual(got.ndim, 0)
                self.assertIsNone(got.split)
                self.assertEqual(float(got), host[4, 5])


class TestSetitemThreeDMatrix(TestCase):
    """3-D setitem across every split: the reference's matrix includes the
    higher-rank combinations where split-offset bookkeeping breaks."""

    def setUp(self):
        self.host = np.arange(210, dtype=np.float32).reshape(7, 5, 6)

    def test_plane_assignment(self):
        for s in _splits(3):
            with self.subTest(split=s):
                x = ht.array(self.host, split=s)
                e = self.host.copy()
                x[3] = -1.0
                e[3] = -1.0
                self.assert_array_equal(x, e)

    def test_middle_axis_slab(self):
        for s in _splits(3):
            with self.subTest(split=s):
                x = ht.array(self.host, split=s)
                e = self.host.copy()
                x[:, 1:4] = 0.25
                e[:, 1:4] = 0.25
                self.assert_array_equal(x, e)

    def test_reversed_last_axis(self):
        for s in _splits(3):
            with self.subTest(split=s):
                x = ht.array(self.host, split=s)
                e = self.host.copy()
                v = np.arange(6, dtype=np.float32)
                x[2, 3, ::-1] = v
                e[2, 3, ::-1] = v
                self.assert_array_equal(x, e)

    def test_block_cross_split_value_3d(self):
        block = -np.arange(60, dtype=np.float32).reshape(2, 5, 6)
        for st in _splits(3):
            for sv in _splits(3):
                with self.subTest(target=st, value=sv):
                    x = ht.array(self.host, split=st)
                    v = ht.array(block, split=sv)
                    e = self.host.copy()
                    x[4:6] = v
                    e[4:6] = block
                    self.assert_array_equal(x, e)

    def test_int_array_on_each_axis(self):
        idx = np.array([0, 4, 2])
        for axis in range(3):
            for s in _splits(3):
                with self.subTest(axis=axis, split=s):
                    x = ht.array(self.host, split=s)
                    e = self.host.copy()
                    key = tuple(
                        idx if d == axis else slice(None) for d in range(axis + 1)
                    )
                    x[key] = 5.5
                    e[key] = 5.5
                    self.assert_array_equal(x, e)


class TestSetitemResplitInteractions(TestCase):
    """Writes composed with redistribution: the physical-layout scatter
    must stay correct across resplits and halo invalidation (reference:
    test_dndarray.py exercises setitem on freshly-resplit arrays)."""

    def test_write_resplit_write(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        x = ht.array(host, split=0)
        e = host.copy()
        x[0] = -1.0
        e[0] = -1.0
        x.resplit_(1)
        x[:, 3] = -2.0
        e[:, 3] = -2.0
        self.assertEqual(x.split, 1)
        self.assert_array_equal(x, e)
        x.resplit_(0)
        x[-1] = -3.0
        e[-1] = -3.0
        self.assert_array_equal(x, e)

    def test_write_after_gather(self):
        host = np.arange(26, dtype=np.float32).reshape(13, 2)
        x = ht.array(host, split=0)
        x.resplit_(None)
        x[4:9] = 0.0
        e = host.copy()
        e[4:9] = 0.0
        self.assertIsNone(x.split)
        self.assert_array_equal(x, e)

    def test_halo_refresh_after_write(self):
        # convolve consumes halos; a preceding setitem must invalidate them
        host = np.zeros(29, np.float32)
        kernel = np.array([1.0, 1.0, 1.0], np.float32)
        x = ht.array(host, split=0)
        _ = ht.convolve(x, ht.array(kernel), mode="same")  # builds halos
        x[10:20] = 1.0
        got = ht.convolve(x, ht.array(kernel), mode="same")
        e = host.copy()
        e[10:20] = 1.0
        self.assert_array_equal(got, np.convolve(e, kernel, mode="same"))

    def test_dndarray_mask_setitem(self):
        host = np.arange(29, dtype=np.float32)
        x = ht.array(host, split=0)
        mask = x > 20                # DNDarray mask, itself split
        x[mask] = -1.0
        e = host.copy()
        e[host > 20] = -1.0
        self.assert_array_equal(x, e)

    def test_dndarray_int_key_setitem(self):
        host = np.arange(29, dtype=np.float32)
        x = ht.array(host, split=0)
        key = ht.array(np.array([0, 7, 28]), split=0)
        x[key] = 5.0
        e = host.copy()
        e[[0, 7, 28]] = 5.0
        self.assert_array_equal(x, e)


class TestViewChainsAndWrites(TestCase):
    """Chained views feeding writes: slices of slices, writes through
    freshly-sliced unbalanced results, transposed targets."""

    def test_getitem_chain(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s)
                self.assert_array_equal(x[2:][3], host[2:][3])
                self.assert_array_equal(x[1:12][::2, 1:], host[1:12][::2, 1:])

    def test_write_into_sliced_copy_leaves_parent(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        x = ht.array(host, split=0)
        y = x[3:9]          # a COPY in this model (jax arrays are immutable)
        y[0] = -1.0
        self.assert_array_equal(x, host)  # parent untouched
        e = host[3:9].copy()
        e[0] = -1.0
        self.assert_array_equal(y, e)

    def test_transpose_then_write(self):
        host = np.arange(91, dtype=np.float32).reshape(13, 7)
        for s in (None, 0, 1):
            with self.subTest(split=s):
                x = ht.array(host, split=s).T
                e = host.T.copy()
                x[2] = 0.0
                e[2] = 0.0
                self.assert_array_equal(x, e)
