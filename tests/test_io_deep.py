"""IO case matrix (reference model: heat/core/tests/test_io.py — every
format x split x dtype x slicing, plus append modes and error branches).

Each roundtrip is asserted at the VALUE level against the written host
data and at the DISTRIBUTION level (the loaded array's shards match
``comm.chunk``), because slab-per-shard loading is exactly where an
off-by-one in byte ranges or chunk math silently corrupts data.
"""

import os
import tempfile

import numpy as np

import heat_tpu as ht
from .base import TestCase


def _splits(ndim):
    return [None] + list(range(ndim))


class IOBase(TestCase):
    def setUp(self):
        import shutil

        self.dir = tempfile.mkdtemp()
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)

    def path(self, name):
        return os.path.join(self.dir, name)


class TestHDF5Matrix(IOBase):
    def test_roundtrip_dtype_split_matrix(self):
        rng = np.random.default_rng(401)
        for dt in (np.float32, np.float64, np.int32, np.int64):
            host = (rng.standard_normal((13, 7)) * 10).astype(dt)
            for s in _splits(2):
                with self.subTest(dtype=dt, split=s):
                    p = self.path(f"m_{np.dtype(dt).name}_{s}.h5")
                    ht.save(ht.array(host, split=s), p, "data")
                    for load_split in _splits(2):
                        back = ht.load(p, dataset="data", split=load_split)
                        self.assertEqual(back.split, load_split)
                        self.assert_array_equal(back, host)

    def test_roundtrip_1d_and_3d(self):
        rng = np.random.default_rng(403)
        v = rng.standard_normal(29).astype(np.float32)
        t = rng.standard_normal((3, 4, 5)).astype(np.float32)
        pv, pt = self.path("v.h5"), self.path("t.h5")
        ht.save(ht.array(v, split=0), pv, "data")
        ht.save(ht.array(t, split=1), pt, "data")
        self.assert_array_equal(ht.load(pv, dataset="data", split=0), v)
        self.assert_array_equal(ht.load(pt, dataset="data", split=2), t)

    def test_two_datasets_one_file(self):
        a = np.arange(10, dtype=np.float32)
        b = np.arange(20, dtype=np.float32).reshape(4, 5)
        p = self.path("two.h5")
        ht.save(ht.array(a), p, "first")
        ht.save(ht.array(b), p, "second", mode="a")
        self.assert_array_equal(ht.load(p, dataset="first"), a)
        self.assert_array_equal(ht.load(p, dataset="second", split=0), b)

    def test_missing_dataset_raises(self):
        p = self.path("missing.h5")
        ht.save(ht.arange(5), p, "data")
        with self.assertRaises((KeyError, ValueError, OSError)):
            ht.load(p, dataset="nope")


class TestNetCDFMatrix(IOBase):
    def test_roundtrip_split_matrix(self):
        rng = np.random.default_rng(407)
        host = rng.standard_normal((11, 6)).astype(np.float32)
        for s in _splits(2):
            with self.subTest(split=s):
                p = self.path(f"nc_{s}.nc")
                ht.save(ht.array(host, split=s), p, "data")
                for load_split in _splits(2):
                    back = ht.load(p, variable="data", split=load_split)
                    self.assert_array_equal(back, host, rtol=1e-6)

    def test_roundtrip_int_data(self):
        host = np.arange(24, dtype=np.int32).reshape(6, 4)
        p = self.path("int.nc")
        ht.save(ht.array(host, split=0), p, "data")
        self.assert_array_equal(ht.load(p, variable="data", split=1), host)


class TestCSVMatrix(IOBase):
    def test_roundtrip_separator_matrix(self):
        rng = np.random.default_rng(409)
        host = np.round(rng.standard_normal((13, 5)), 4).astype(np.float32)
        for sep in (",", ";", "\t"):
            with self.subTest(sep=repr(sep)):
                p = self.path(f"sep{ord(sep)}.csv")
                ht.save_csv(ht.array(host, split=0), p, sep=sep)
                back = ht.load_csv(p, sep=sep, split=0)
                self.assert_array_equal(back, host, rtol=1e-3, atol=1e-4)

    def test_header_lines_skipped(self):
        host = np.arange(12, dtype=np.float32).reshape(4, 3)
        p = self.path("hdr.csv")
        with open(p, "w") as f:
            f.write("# a comment line\ncol1;col2;col3\n")
            for row in host:
                f.write(";".join(str(float(v)) for v in row) + "\n")
        back = ht.load_csv(p, sep=";", header_lines=2, split=0)
        self.assert_array_equal(back, host, rtol=1e-6)

    def test_uneven_rows_over_mesh(self):
        # 3 rows over 8 devices — empty shards on load
        host = np.arange(9, dtype=np.float32).reshape(3, 3)
        p = self.path("tiny.csv")
        ht.save_csv(ht.array(host), p, sep=",")
        back = ht.load_csv(p, sep=",", split=0)
        self.assert_array_equal(back, host, rtol=1e-6)

    def test_single_column_vector(self):
        host = np.arange(17, dtype=np.float32)
        p = self.path("vec.csv")
        with open(p, "w") as f:
            f.writelines(f"{float(v)}\n" for v in host)
        back = ht.load_csv(p, sep=",")
        got = np.asarray(back.numpy()).reshape(-1)
        np.testing.assert_allclose(got, host, rtol=1e-6)


class TestNpyMatrix(IOBase):
    def test_roundtrip_dtype_matrix(self):
        rng = np.random.default_rng(411)
        for dt in (np.float32, np.int64, np.bool_):
            host = (rng.standard_normal((9, 4)) > 0).astype(dt)
            with self.subTest(dtype=dt):
                p = self.path(f"npy_{np.dtype(dt).name}.npy")
                ht.save(ht.array(host, split=0), p)
                for s in (None, 0, 1):
                    back = ht.load(p, split=s)
                    self.assert_array_equal(back, host)

    def test_numpy_writes_heat_reads(self):
        host = np.linspace(0, 1, 40, dtype=np.float64).reshape(8, 5)
        p = self.path("foreign.npy")
        np.save(p, host)
        back = ht.load(p, split=0)
        self.assert_array_equal(back, host, rtol=1e-12)

    def test_heat_writes_numpy_reads(self):
        host = np.arange(21, dtype=np.float32).reshape(3, 7)
        p = self.path("back.npy")
        ht.save(ht.array(host, split=1), p)
        np.testing.assert_array_equal(np.load(p), host)


class TestDispatchAndErrors(IOBase):
    def test_extension_dispatch(self):
        host = np.arange(6, dtype=np.float32)
        for ext, kw in [("h5", {"dataset": "data"}), ("nc", {"variable": "data"}), ("npy", {})]:
            with self.subTest(ext=ext):
                p = self.path(f"d.{ext}")
                if ext == "npy":
                    ht.save(ht.array(host), p)
                else:
                    ht.save(ht.array(host), p, "data")
                back = ht.load(p, **kw)
                self.assert_array_equal(back, host)

    def test_unknown_extension_raises(self):
        with self.assertRaises(ValueError):
            ht.load(self.path("x.parquet"))

    def test_nonexistent_file_raises(self):
        with self.assertRaises((FileNotFoundError, OSError)):
            ht.load(self.path("absent.h5"), dataset="data")

    def test_save_non_dndarray_raises(self):
        with self.assertRaises((TypeError, AttributeError)):
            ht.save([1, 2, 3], self.path("bad.h5"), "data")


class TestIOChains(IOBase):
    """Save -> load -> compute -> save chains across formats."""

    def test_cross_format_pipeline(self):
        rng = np.random.default_rng(419)
        host = rng.standard_normal((16, 4)).astype(np.float32)
        p1, p2 = self.path("stage1.h5"), self.path("stage2.npy")
        ht.save(ht.array(host, split=0), p1, "data")
        x = ht.load(p1, dataset="data", split=0)
        y = (x - ht.mean(x, axis=0)) / ht.std(x, axis=0)
        ht.save(y, p2)
        z = ht.load(p2, split=0)
        expected = (host - host.mean(axis=0)) / host.std(axis=0)
        self.assert_array_equal(z, expected, rtol=1e-4)

    def test_load_resplit_save_roundtrip(self):
        host = np.arange(42, dtype=np.float32).reshape(6, 7)
        p1, p2 = self.path("r1.h5"), self.path("r2.h5")
        ht.save(ht.array(host, split=0), p1, "data")
        x = ht.load(p1, dataset="data", split=0)
        x = ht.resplit(x, 1)
        ht.save(x, p2, "data")
        back = ht.load(p2, dataset="data", split=None)
        self.assert_array_equal(back, host)

    def test_sharded_epoch_io(self):
        # the data-layer pattern: save a dataset, reload sharded, shuffle,
        # reduce — values survive the whole pipeline
        rng = np.random.default_rng(421)
        host = rng.standard_normal((64, 3)).astype(np.float32)
        p = self.path("epoch.h5")
        ht.save(ht.array(host, split=0), p, "data")
        x = ht.load(p, dataset="data", split=0)
        (shuffled,) = ht.random.shuffle_rows([x])
        np.testing.assert_allclose(
            float(ht.sum(shuffled).numpy()), host.sum(), rtol=1e-4
        )
        np.testing.assert_allclose(
            np.sort(shuffled.numpy()[:, 0]), np.sort(host[:, 0]), rtol=1e-5
        )
