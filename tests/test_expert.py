"""Expert-parallelism tests (heat_tpu/parallel/expert.py).

No reference counterpart (the reference's parallelism checklist marks EP
absent, SURVEY.md §2.5); the oracle is the dense top-k mixture computed in
NumPy, the mesh is the 8-device CPU mesh — real all_to_alls, no mocks
(the reference's test doctrine, SURVEY.md §4).
"""

import numpy as np

from .base import TestCase


def _ref_moe(x, gate_w, w_in, w_out, k):
    """Dense NumPy oracle: every token through its top-k experts, no
    capacity limit."""

    def gelu(v):
        return 0.5 * v * (1.0 + np.tanh(np.sqrt(2 / np.pi) * (v + 0.044715 * v**3)))

    logits = x @ gate_w
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    top_idx = np.argsort(-probs, axis=-1)[:, :k]
    top_w = np.take_along_axis(probs, top_idx, axis=-1)
    top_w = top_w / top_w.sum(-1, keepdims=True)
    y = np.zeros_like(x)
    for t in range(x.shape[0]):
        for j in range(k):
            exp = top_idx[t, j]
            h = gelu(x[t] @ w_in[exp])
            y[t] += top_w[t, j] * (h @ w_out[exp])
    return y


def _params(rng, d, h, num_experts):
    gate_w = rng.standard_normal((d, num_experts)).astype(np.float32) * 0.5
    w_in = rng.standard_normal((num_experts, d, h)).astype(np.float32) / np.sqrt(d)
    w_out = rng.standard_normal((num_experts, h, d)).astype(np.float32) / np.sqrt(h)
    return gate_w, w_in, w_out


class TestMoEFfn(TestCase):
    def _mesh(self, n=8):
        import jax
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:n]), ("ep",))

    def test_dense_path_matches_numpy(self):
        import jax.numpy as jnp
        from heat_tpu.parallel.expert import moe_ffn

        rng = np.random.default_rng(0)
        d, h, E, k = 16, 32, 8, 2
        x = rng.standard_normal((24, d)).astype(np.float32)
        gate_w, w_in, w_out = _params(rng, d, h, E)
        y, aux = moe_ffn(
            jnp.array(x), jnp.array(gate_w), jnp.array(w_in), jnp.array(w_out),
            k=k, capacity_factor=8.0,  # ample: nothing dropped
        )
        self.assertEqual(float(aux["fraction_dropped"]), 0.0)
        np.testing.assert_allclose(
            np.asarray(y), _ref_moe(x, gate_w, w_in, w_out, k), rtol=1e-4, atol=1e-4
        )

    def test_expert_parallel_matches_numpy(self):
        """Sharded path (tokens + experts over the 8-way ep axis, two real
        all_to_alls) against the same dense oracle."""
        import jax.numpy as jnp
        from heat_tpu.parallel.expert import moe_ffn

        rng = np.random.default_rng(1)
        d, h, E, k = 16, 32, 8, 2
        x = rng.standard_normal((64, d)).astype(np.float32)  # 8 tokens/shard
        gate_w, w_in, w_out = _params(rng, d, h, E)
        y, aux = moe_ffn(
            jnp.array(x), jnp.array(gate_w), jnp.array(w_in), jnp.array(w_out),
            k=k, capacity_factor=16.0, mesh=self._mesh(), axis="ep",
        )
        self.assertEqual(float(aux["fraction_dropped"]), 0.0)
        self.assertTrue(np.isfinite(float(aux["load_balance_loss"])))
        np.testing.assert_allclose(
            np.asarray(y), _ref_moe(x, gate_w, w_in, w_out, k), rtol=1e-4, atol=1e-4
        )

    def test_leading_dims_flattened(self):
        """(b, s, d) inputs route over b*s tokens and reshape back."""
        import jax.numpy as jnp
        from heat_tpu.parallel.expert import moe_ffn

        rng = np.random.default_rng(2)
        d, h, E = 8, 16, 8
        x = rng.standard_normal((2, 16, d)).astype(np.float32)
        gate_w, w_in, w_out = _params(rng, d, h, E)
        y, _ = moe_ffn(
            jnp.array(x), jnp.array(gate_w), jnp.array(w_in), jnp.array(w_out),
            k=1, capacity_factor=8.0, mesh=self._mesh(), axis="ep",
        )
        self.assertEqual(y.shape, x.shape)
        np.testing.assert_allclose(
            np.asarray(y).reshape(-1, d),
            _ref_moe(x.reshape(-1, d), gate_w, w_in, w_out, 1),
            rtol=1e-4, atol=1e-4,
        )

    def test_capacity_drops_overflow_tokens(self):
        """With capacity 1 and a router forced to a single expert, all but
        one token per shard is dropped and passes through as zeros."""
        import jax.numpy as jnp
        from heat_tpu.parallel.expert import moe_ffn

        rng = np.random.default_rng(3)
        d, h, E = 8, 16, 4
        x = np.abs(rng.standard_normal((16, d))).astype(np.float32)
        gate_w = np.zeros((d, E), np.float32)
        gate_w[:, 0] = 10.0  # every token picks expert 0
        _, w_in, w_out = _params(rng, d, h, E)
        y, aux = moe_ffn(
            jnp.array(x), jnp.array(gate_w), jnp.array(w_in), jnp.array(w_out),
            k=1, capacity_factor=1.0 / 4,  # capacity = 1 per shard
        )
        dropped = float(aux["fraction_dropped"])
        self.assertGreater(dropped, 0.9)
        # dropped tokens contribute nothing (residual connection's job)
        zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
        self.assertEqual(zero_rows, 15)

    def test_divisibility_errors(self):
        import jax.numpy as jnp
        from heat_tpu.parallel.expert import moe_ffn

        x = jnp.zeros((12, 8))  # 12 tokens not divisible by 8-way mesh
        gate_w = jnp.zeros((8, 8))
        w_in = jnp.zeros((8, 8, 4))
        w_out = jnp.zeros((8, 4, 8))
        with self.assertRaises(ValueError):
            moe_ffn(x, gate_w, w_in, w_out, mesh=self._mesh(), axis="ep")

    def test_grads_flow_through_router_and_experts(self):
        import jax
        import jax.numpy as jnp
        from heat_tpu.parallel.expert import moe_ffn

        rng = np.random.default_rng(4)
        d, h, E = 8, 16, 8
        x = jnp.array(rng.standard_normal((32, d)).astype(np.float32))
        gate_w, w_in, w_out = map(jnp.array, _params(rng, d, h, E))

        def loss(params):
            y, aux = moe_ffn(
                x, params["g"], params["i"], params["o"],
                k=2, capacity_factor=4.0, mesh=self._mesh(), axis="ep",
            )
            return jnp.mean(y * y) + 0.01 * aux["load_balance_loss"]

        grads = jax.grad(loss)({"g": gate_w, "i": w_in, "o": w_out})
        for key in ("g", "i", "o"):
            g = np.asarray(grads[key])
            self.assertTrue(np.isfinite(g).all(), key)
            self.assertGreater(np.abs(g).max(), 0.0, key)


class TestMoETransformer(TestCase):
    def test_moe_lm_forward_and_aux_loss(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        import heat_tpu as ht

        mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
        lm = ht.models.TransformerLM(
            vocab_size=64, num_layers=2, num_heads=4, head_dim=8,
            max_seq_len=32, moe_experts=8, moe_k=2, ep_mesh=mesh,
        )
        toks = jnp.array(np.random.default_rng(0).integers(0, 64, (2, 16)))
        variables = lm.init(jax.random.PRNGKey(0), toks)
        logits, state = lm.apply(variables, toks, mutable=["intermediates"])
        self.assertEqual(logits.shape, (2, 16, 64))
        self.assertTrue(np.isfinite(np.asarray(logits)).all())
        aux = [
            np.asarray(v)
            for v in jax.tree.leaves(state["intermediates"])
        ]
        self.assertEqual(len(aux), 2)  # one sowed loss per MoE block
        for a in aux:
            self.assertTrue(np.isfinite(a).all())
