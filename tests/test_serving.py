"""Serving front door (ISSUE 14): bucketed dynamic batching, the
compile-once step cache, admission control, and the telemetry surface.

The headline law is **no-retrace**: after a warmup pass over an
endpoint's bucket ladder, sustained mixed-size traffic must produce
ZERO new fusion/overlap compile-cache misses and zero new serving step
compiles — every request lands in an already-compiled bucket shape.
``scripts/ci.sh`` stage 18 re-runs this file at mesh sizes 1/4/8.

Doctrine stays "no mocks": correctness tests serve the real fitted
estimators on the real mesh and compare against direct ``predict``;
the stall test wedges a real fused execution through ``FaultInjector``
and asserts the documented ``RequestRejected`` fast-fail instead of a
hang."""

import threading
import time
import unittest
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.core import memtrack, telemetry
from heat_tpu.serving import AdmissionController, DynamicBatcher, RequestRejected
from heat_tpu.serving.batcher import Request
from heat_tpu.serving.engine import _pow2_buckets
from heat_tpu.utils import fault

from .base import TestCase

_RNG = np.random.default_rng(4114)


def _engine(**kwargs):
    telemetry.reset_group("serving")
    return serving.ServingEngine(**kwargs)


def _fitted_kmeans(f=16, clusters=4):
    X = _RNG.normal(size=(64, f)).astype(np.float32)
    km = ht.cluster.KMeans(n_clusters=clusters, init="kmeans++", max_iter=5, random_state=0)
    km.fit(ht.array(X, split=0))
    return km


class TestBucketLadder(TestCase):
    def test_pow2_ladder(self):
        self.assertEqual(_pow2_buckets(8, 32), (8, 16, 32))
        self.assertEqual(_pow2_buckets(3, 20), (4, 8, 16, 32))
        self.assertEqual(_pow2_buckets(16, 16), (16,))
        with self.assertRaises(ValueError):
            _pow2_buckets(0, 8)

    def test_bucket_for_picks_smallest_cover(self):
        eng = _engine()
        try:
            ep = eng.register(
                "e", predict=lambda x: x, feature_dim=4, min_bucket=8, max_batch=32
            )
            self.assertEqual(ep.bucket_for(1), 8)
            self.assertEqual(ep.bucket_for(8), 8)
            self.assertEqual(ep.bucket_for(9), 16)
            self.assertEqual(ep.bucket_for(32), 32)
            with self.assertRaises(ValueError):
                ep.bucket_for(33)
        finally:
            eng.close()

    def test_register_contract(self):
        eng = _engine()
        try:
            with self.assertRaisesRegex(ValueError, "exactly one"):
                eng.register("x", feature_dim=4)
            eng.register("x", predict=lambda x: x, feature_dim=4)
            with self.assertRaisesRegex(ValueError, "already registered"):
                eng.register("x", predict=lambda x: x, feature_dim=4)
            with self.assertRaises(KeyError):
                eng.submit("nope", np.zeros((1, 4), dtype=np.float32))
        finally:
            eng.close()

    def test_submit_shape_validation_and_too_large(self):
        eng = _engine()
        try:
            eng.register("x", predict=lambda x: x, feature_dim=4, max_batch=8)
            with self.assertRaisesRegex(ValueError, r"\(rows, 4\)"):
                eng.submit("x", np.zeros((2, 5), dtype=np.float32))
            with self.assertRaisesRegex(RequestRejected, "too_large"):
                eng.submit("x", np.zeros((9, 4), dtype=np.float32))
            self.assertGreaterEqual(eng.stats()["shed"]["too_large"], 1)
        finally:
            eng.close()


class TestBatcherUnit(unittest.TestCase):
    """Pure queue mechanics — no mesh, stub executor."""

    def _run(self, requests, caps, **kwargs):
        flushed = []
        done = threading.Event()

        def execute(name, reqs, cause):
            flushed.append((name, [r.rows for r in reqs], cause))
            for r in reqs:
                r.future.set_result(r.rows)
            if sum(len(f[1]) for f in flushed) >= len(requests):
                done.set()

        b = DynamicBatcher(execute)
        for r in requests:
            b.enqueue(r, caps[r.endpoint])
        done.wait(5.0)
        return b, flushed

    @staticmethod
    def _req(endpoint, rows, delay):
        now = time.perf_counter()
        return Request(endpoint=endpoint, payload=None, rows=rows, t0=now, deadline=now + delay)

    def test_full_bucket_flushes_immediately_as_max_batch(self):
        reqs = [self._req("a", 4, 10.0), self._req("a", 4, 10.0)]
        b, flushed = self._run(reqs, {"a": 8})
        try:
            self.assertEqual(flushed, [("a", [4, 4], "max_batch")])
        finally:
            b.stop()

    def test_timer_flush_ships_partial_batch(self):
        reqs = [self._req("a", 2, 0.02)]
        b, flushed = self._run(reqs, {"a": 8})
        try:
            self.assertEqual(flushed, [("a", [2], "timer")])
        finally:
            b.stop()

    def test_drain_flushes_everything_with_drain_cause(self):
        flushed = []

        def execute(name, reqs, cause):
            flushed.append(cause)
            for r in reqs:
                r.future.set_result(None)

        b = DynamicBatcher(execute)
        b.enqueue(self._req("a", 1, 60.0), 8)
        b.enqueue(self._req("b", 1, 60.0), 8)
        self.assertTrue(b.drain(timeout=5.0))
        b.stop()
        self.assertEqual(flushed, ["drain", "drain"])

    def test_requests_never_split_across_batches(self):
        # 5 + 4 rows against cap 8: the 4-row request must NOT be torn
        # to fill the first bucket
        reqs = [self._req("a", 5, 0.02), self._req("a", 4, 0.02)]
        b, flushed = self._run(reqs, {"a": 8})
        try:
            self.assertEqual(sorted(rows for _, batch, _ in flushed for rows in batch), [4, 5])
            for _, batch, _ in flushed:
                self.assertLessEqual(sum(batch), 8)
        finally:
            b.stop()


class TestAdmissionUnit(unittest.TestCase):
    """Decision layer alone — no engine, no mesh."""

    def test_queue_bound_and_release(self):
        adm = AdmissionController(max_queue_rows=4)
        adm.admit("e", 3, 0)
        with self.assertRaisesRegex(RequestRejected, "queue_full") as ctx:
            adm.admit("e", 2, 0)
        self.assertEqual(ctx.exception.reason, "queue_full")
        self.assertIsNotNone(ctx.exception.retry_after_s)
        adm.release(3)
        adm.admit("e", 4, 0)  # freed budget admits again

    def test_documented_error_message(self):
        adm = AdmissionController(max_queue_rows=1, retry_after_s=0.25)
        adm.admit("e", 1, 0)
        with self.assertRaisesRegex(
            RequestRejected, r"serving request rejected \(queue_full\).*retry after 0\.25s"
        ):
            adm.admit("e", 1, 0)

    def test_statsless_backend_never_sheds_on_memory(self):
        # CPU reports no memory stats: would_fit is None -> admit
        self.assertIsNone(memtrack.would_fit(1 << 40))
        AdmissionController(max_queue_rows=8).admit("e", 1, 1 << 40)

    def test_hbm_pressure_sheds_under_injected_starvation(self):
        inj = fault.FaultInjector().low_hbm(1024)
        with fault.injected(inj):
            self.assertIs(memtrack.would_fit(10_000, fraction=0.5), False)
            self.assertIs(memtrack.would_fit(256, fraction=0.5), True)
            adm = AdmissionController(max_queue_rows=8, memory_fraction=0.5)
            with self.assertRaisesRegex(RequestRejected, "hbm_pressure"):
                adm.admit("e", 1, 10_000)
            adm.admit("e", 1, 256)

    def test_drain_then_close_reasons(self):
        adm = AdmissionController()
        adm.begin_drain()
        with self.assertRaisesRegex(RequestRejected, "draining"):
            adm.admit("e", 1, 0)
        adm.close()
        with self.assertRaisesRegex(RequestRejected, "closed"):
            adm.admit("e", 1, 0)

    def test_low_class_sheds_first_under_queue_pressure(self):
        # low rides 0.5 of the bound by default: at 3/8 queued rows a
        # 2-row low request overflows its bound (4) while normal/high
        # still admit against the full 8
        adm = AdmissionController(max_queue_rows=8)
        adm.admit("e", 3, 0, priority="normal")
        with self.assertRaisesRegex(RequestRejected, "queue_full") as ctx:
            adm.admit("e", 2, 0, priority="low")
        self.assertIn("'low'", str(ctx.exception))
        adm.admit("e", 2, 0, priority="high")
        adm.admit("e", 2, 0)  # normal keeps the full bound

    def test_class_threshold_validation(self):
        with self.assertRaisesRegex(ValueError, r"\(0, 1\]"):
            AdmissionController(class_thresholds={"low": 0.0})
        adm = AdmissionController(
            max_queue_rows=10, class_thresholds={"batch": 0.2}
        )
        adm.admit("e", 2, 0, priority="batch")
        with self.assertRaisesRegex(RequestRejected, "queue_full"):
            adm.admit("e", 1, 0, priority="batch")
        with self.assertRaisesRegex(ValueError, "unknown SLO class"):
            adm.admit("e", 1, 0, priority="platinum")

    def test_stall_latch_via_subscription_and_recovery(self):
        det = fault.StallDetector(timeout=60.0)  # never fires on its own
        adm = AdmissionController().attach_stall_detector(det)
        det._notify("stall", quiet_s=1.0)
        self.assertTrue(adm.stalled)
        with self.assertRaisesRegex(RequestRejected, "stalled"):
            adm.admit("e", 1, 0)
        det._notify("recover")
        self.assertFalse(adm.stalled)
        adm.admit("e", 1, 0)
        adm.detach_stall_detector()
        det._notify("stall")
        self.assertFalse(adm.stalled)  # detached: no longer listening


class TestServingCorrectness(TestCase):
    """Every served endpoint returns exactly what direct predict returns
    — padding rows and batch coalescing must be invisible."""

    def _serve_and_compare(self, eng, name, model_predict, requests):
        # expected values computed FIRST, single-threaded, on the same mesh
        expected = [np.asarray(model_predict(ht.array(r, split=0)).numpy()) for r in requests]
        futures = [eng.submit(name, r) for r in requests]
        for want, fut in zip(expected, futures):
            got = np.asarray(fut.result(30))
            self.assertEqual(got.shape, want.shape)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_kmeans_endpoint(self):
        km = _fitted_kmeans()
        eng = _engine()
        try:
            eng.register("kmeans", km, feature_dim=16, max_batch=16, warm=True)
            reqs = [_RNG.normal(size=(r, 16)).astype(np.float32) for r in (1, 3, 2, 5)]
            self._serve_and_compare(eng, "kmeans", km.predict, reqs)
        finally:
            eng.close()

    def test_lasso_endpoint(self):
        X = _RNG.normal(size=(32, 8)).astype(np.float32)
        y = (X @ _RNG.normal(size=(8, 1))).astype(np.float32)
        lasso = ht.regression.Lasso(max_iter=10)
        lasso.fit(ht.array(X, split=0), ht.array(y, split=0))
        eng = _engine()
        try:
            eng.register("lasso", lasso, feature_dim=8, max_batch=16)
            reqs = [_RNG.normal(size=(r, 8)).astype(np.float32) for r in (2, 1, 4)]
            self._serve_and_compare(eng, "lasso", lasso.predict, reqs)
        finally:
            eng.close()

    def test_gaussian_nb_endpoint(self):
        X = _RNG.normal(size=(48, 8)).astype(np.float32)
        labels = (X[:, 0] > 0).astype(np.int32)
        gnb = ht.naive_bayes.GaussianNB()
        gnb.fit(ht.array(X, split=0), ht.array(labels, split=0))
        eng = _engine()
        try:
            eng.register("gnb", gnb, feature_dim=8, max_batch=16)
            reqs = [_RNG.normal(size=(r, 8)).astype(np.float32) for r in (3, 2)]
            self._serve_and_compare(eng, "gnb", gnb.predict, reqs)
        finally:
            eng.close()

    def test_knn_endpoint(self):
        X = _RNG.normal(size=(32, 8)).astype(np.float32)
        labels = (X[:, 0] > 0).astype(np.int32)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=3)
        knn.fit(ht.array(X, split=0), ht.array(labels, split=0))
        eng = _engine()
        try:
            eng.register("knn", knn, feature_dim=8, max_batch=16)
            reqs = [_RNG.normal(size=(r, 8)).astype(np.float32) for r in (2, 4)]
            self._serve_and_compare(eng, "knn", knn.predict, reqs)
        finally:
            eng.close()

    def test_nn_linear_endpoint(self):
        w = ht.array(_RNG.normal(size=(4, 8)).astype(np.float32))
        b = ht.array(_RNG.normal(size=(4,)).astype(np.float32))

        def predict(x):
            return ht.nn.functional.linear(x, w, b)

        eng = _engine()
        try:
            eng.register("linear", predict=predict, feature_dim=8, max_batch=16)
            reqs = [_RNG.normal(size=(r, 8)).astype(np.float32) for r in (1, 6)]
            self._serve_and_compare(eng, "linear", predict, reqs)
        finally:
            eng.close()

    def test_single_row_request_accepts_1d(self):
        eng = _engine()
        try:
            eng.register("id", predict=lambda x: x, feature_dim=4, max_batch=8)
            out = eng.predict("id", np.arange(4, dtype=np.float32))
            np.testing.assert_allclose(np.asarray(out).ravel(), np.arange(4.0))
        finally:
            eng.close()

    def test_endpoint_failure_resolves_futures_with_exception(self):
        def boom(x):
            raise RuntimeError("model exploded")

        eng = _engine()
        try:
            eng.register("boom", predict=boom, feature_dim=4, max_batch=8)
            fut = eng.submit("boom", np.zeros((2, 4), dtype=np.float32))
            with self.assertRaisesRegex(RuntimeError, "model exploded"):
                fut.result(10)
            # the failure freed queue budget: the engine still serves
            eng.register("ok", predict=lambda x: x, feature_dim=4, max_batch=8)
            eng.predict("ok", np.zeros((1, 4), dtype=np.float32))
        finally:
            eng.close()


class TestNoRetraceLaw(TestCase):
    """THE acceptance law: after warmup over the bucket ladder, mixed
    steady traffic adds zero fusion misses, zero overlap ring builds,
    and zero serving step compiles — on every mesh size (ci.sh stage 18
    re-runs this at HEAT_TEST_DEVICES=1/4/8)."""

    def test_steady_traffic_over_three_buckets_never_retraces(self):
        km = _fitted_kmeans(f=16)
        eng = _engine()
        try:
            ep = eng.register(
                "kmeans", km, feature_dim=16, min_bucket=8, max_batch=32,
                max_delay_s=0.002, warm=True,
            )
            self.assertEqual(len(ep.buckets), 3)  # 8, 16, 32

            sizes = [1, 3, 8, 2, 16, 5, 7, 4, 1, 12, 32, 6] * 3
            payloads = [_RNG.normal(size=(s, 16)).astype(np.float32) for s in sizes]
            # warm every shape once more via live traffic, then measure
            for p in payloads[: len(ep.buckets)]:
                eng.predict("kmeans", p)

            fusion_before = telemetry.snapshot_group("fusion").get("misses", 0)
            overlap_before = telemetry.snapshot_group("overlap").get("ring_builds", 0)
            steps_before = eng.stats()["step_compiles"]

            with ThreadPoolExecutor(max_workers=8) as pool:
                futures = list(pool.map(lambda p: eng.submit("kmeans", p), payloads))
                results = [f.result(60) for f in futures]
            for p, r in zip(payloads, results):
                self.assertEqual(np.asarray(r).shape[0], p.shape[0])

            stats = eng.stats()
            self.assertEqual(
                telemetry.snapshot_group("fusion").get("misses", 0), fusion_before,
                "steady bucketed traffic must not MISS the fusion compile cache",
            )
            self.assertEqual(
                telemetry.snapshot_group("overlap").get("ring_builds", 0), overlap_before,
                "steady bucketed traffic must not rebuild overlap programs",
            )
            self.assertEqual(stats["step_compiles"], steps_before,
                             "every bucket was compiled during warmup")
            self.assertGreaterEqual(stats["batches"], 1)
            self.assertGreaterEqual(stats["padded_rows"], 1)
            self.assertEqual(stats["batched"], stats["accepted"])
        finally:
            eng.close()


class TestStallShedding(TestCase):
    """A wedged mesh must FAIL requests fast with the documented error,
    not hang them — driven by a real injected stall in fused exec."""

    def test_injected_stall_sheds_then_recovers(self):
        eng = _engine(admission=AdmissionController(retry_after_s=0.05))
        det = fault.StallDetector(timeout=0.08)
        eng.attach_stall_detector(det)
        det.start()
        stalled = threading.Event()
        det.subscribe(lambda kind, info: stalled.set() if kind == "stall" else None)
        try:
            eng.register(
                "exp", predict=lambda x: ht.exp(x), feature_dim=8,
                min_bucket=8, max_batch=8, warm=True,
            )
            det.beat()
            inj = fault.FaultInjector().stall_in("fusion.exec", 0.8, times=1)
            with fault.injected(inj):
                wedged = eng.submit("exp", np.ones((2, 8), dtype=np.float32))
                self.assertTrue(stalled.wait(5.0), "stall never detected")
                with self.assertRaisesRegex(
                    RequestRejected, r"serving request rejected \(stalled\)"
                ) as ctx:
                    eng.submit("exp", np.ones((1, 8), dtype=np.float32))
                self.assertEqual(ctx.exception.reason, "stalled")
                self.assertIsNotNone(ctx.exception.retry_after_s)
                # the wedged request itself completes — shed, not lost
                out = wedged.result(30)
                self.assertEqual(np.asarray(out).shape[0], 2)
            self.assertGreaterEqual(eng.stats()["shed"]["stalled"], 1)
            # the completed batch beat the detector: admission re-admits
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if not eng.admission.stalled:
                    break
                time.sleep(0.01)
            out = eng.predict("exp", np.ones((1, 8), dtype=np.float32), timeout=30)
            self.assertEqual(np.asarray(out).shape[0], 1)
        finally:
            det.stop()
            eng.close()


class TestSLOAndDeadlines(TestCase):
    """ISSUE 18: per-request SLO classes and client deadlines on the
    single-engine path — low sheds first, lapsed deadlines are dropped
    at flush (``expired``) instead of computing dead work."""

    def test_engine_counts_accepted_and_shed_per_class(self):
        eng = _engine(admission=AdmissionController(max_queue_rows=8))
        try:
            eng.register(
                "id", predict=lambda x: x, feature_dim=4, max_batch=8,
                max_delay_s=30.0, warm=True,  # hold the queue open
            )
            eng.submit("id", np.ones((3, 4), dtype=np.float32), priority="high")
            with self.assertRaisesRegex(RequestRejected, "queue_full"):
                eng.submit("id", np.ones((2, 4), dtype=np.float32), priority="low")
            stats = eng.stats()
            self.assertEqual(stats["accepted_by_class"]["high"], 1)
            self.assertEqual(stats["shed_by_class"]["low"], 1)
        finally:
            eng.close()

    def test_lapsed_client_deadline_dropped_at_flush_as_expired(self):
        eng = _engine()
        try:
            eng.register(
                "id", predict=lambda x: x, feature_dim=4, min_bucket=8,
                max_batch=8, max_delay_s=0.25, warm=True,
            )
            # deadline (0.05s) lapses before the flush timer (0.25s):
            # the request must resolve `expired`, not compute
            doomed = eng.submit(
                "id", np.ones((1, 4), dtype=np.float32),
                priority="low", deadline_s=0.05,
            )
            with self.assertRaisesRegex(
                RequestRejected, r"serving request rejected \(expired\)"
            ) as ctx:
                doomed.result(10)
            self.assertEqual(ctx.exception.reason, "expired")
            stats = eng.stats()
            self.assertGreaterEqual(stats["shed"]["expired"], 1)
            self.assertGreaterEqual(stats["shed_by_class"]["low"], 1)
            # the expired rows freed queue budget: the engine still serves
            out = eng.predict("id", np.ones((2, 4), dtype=np.float32))
            self.assertEqual(np.asarray(out).shape[0], 2)
        finally:
            eng.close()

    def test_deadline_validation(self):
        eng = _engine()
        try:
            eng.register("id", predict=lambda x: x, feature_dim=4, max_batch=8)
            with self.assertRaisesRegex(ValueError, "deadline_s"):
                eng.submit(
                    "id", np.ones((1, 4), dtype=np.float32), deadline_s=0.0
                )
        finally:
            eng.close()


class TestErrorPathLiveness(TestCase):
    """Satellite of ISSUE 18: a failing step is liveness, not a stall.
    Before the fix, `_execute`'s exception path never beat the detector,
    so a burst of consecutive injected step errors latched `stalled` and
    shed all traffic from a live worker."""

    def test_error_burst_never_latches_stall(self):
        eng = _engine(admission=AdmissionController(retry_after_s=0.02))
        det = fault.StallDetector(timeout=0.12)
        eng.attach_stall_detector(det)
        det.start()
        try:
            eng.register(
                "id", predict=lambda x: x, feature_dim=4, min_bucket=8,
                max_batch=8, max_delay_s=0.001, warm=True,
            )
            det.beat()
            # every batch for ~4x the stall timeout fails via a real
            # injected fault at the serving.step site
            inj = fault.FaultInjector().error_in("serving.step", times=64)
            with fault.injected(inj):
                deadline = time.monotonic() + 0.5
                while time.monotonic() < deadline:
                    fut = eng.submit("id", np.ones((1, 4), dtype=np.float32))
                    with self.assertRaisesRegex(
                        fault.FaultInjector.InjectedFault, "injected failure"
                    ):
                        fut.result(10)
                    self.assertFalse(
                        eng.admission.stalled,
                        "error burst latched `stalled` on a live worker",
                    )
                    time.sleep(0.03)
            self.assertEqual(eng.stats()["shed"]["stalled"], 0)
            self.assertGreaterEqual(eng.stats()["step_errors"], 3)
            # the worker was never wedged: the next clean batch serves
            out = eng.predict("id", np.ones((2, 4), dtype=np.float32))
            self.assertEqual(np.asarray(out).shape[0], 2)
        finally:
            det.stop()
            eng.close()


class TestWeightSwap(TestCase):
    """ISSUE 18: `swap_weights` exchanges operands under traffic with
    zero step compiles — and refuses shape/dtype/split changes (those
    are retraces, not swaps)."""

    class _Linear:
        def __init__(self, w):
            self.w = ht.array(w, split=None)

        def predict(self, x):
            return x @ self.w

    def test_swap_serves_new_weights_with_zero_step_compiles(self):
        w_old = _RNG.normal(size=(8, 4)).astype(np.float32)
        w_new = _RNG.normal(size=(8, 4)).astype(np.float32)
        model = self._Linear(w_old)
        eng = _engine()
        try:
            eng.register(
                "lin", model, feature_dim=8, min_bucket=8, max_batch=8, warm=True
            )
            x = _RNG.normal(size=(2, 8)).astype(np.float32)
            np.testing.assert_allclose(
                np.asarray(eng.predict("lin", x)), x @ w_old, rtol=1e-4, atol=1e-4
            )
            steps_before = eng.stats()["step_compiles"]
            fusion_before = telemetry.snapshot_group("fusion").get("misses", 0)
            old = eng.swap_weights("lin", {"w": ht.array(w_new, split=None)})
            np.testing.assert_allclose(
                np.asarray(eng.predict("lin", x)), x @ w_new, rtol=1e-4, atol=1e-4
            )
            self.assertEqual(
                eng.stats()["step_compiles"], steps_before,
                "a weight swap is new operands, not a retrace",
            )
            self.assertEqual(
                telemetry.snapshot_group("fusion").get("misses", 0), fusion_before
            )
            self.assertGreaterEqual(eng.stats()["swaps"], 1)
            # the returned old operands roll back
            eng.swap_weights("lin", old)
            np.testing.assert_allclose(
                np.asarray(eng.predict("lin", x)), x @ w_old, rtol=1e-4, atol=1e-4
            )
        finally:
            eng.close()

    def test_swap_refuses_retrace_shapes_and_bare_predict(self):
        model = self._Linear(_RNG.normal(size=(8, 4)).astype(np.float32))
        eng = _engine()
        try:
            eng.register("lin", model, feature_dim=8, max_batch=8)
            eng.register("bare", predict=lambda x: x, feature_dim=8, max_batch=8)
            with self.assertRaisesRegex(ValueError, "shape.*retrace"):
                eng.swap_weights(
                    "lin", {"w": ht.array(np.zeros((8, 5), dtype=np.float32))}
                )
            with self.assertRaisesRegex(ValueError, "dtype"):
                eng.swap_weights(
                    "lin", {"w": ht.array(np.zeros((8, 4), dtype=np.int32))}
                )
            with self.assertRaisesRegex(ValueError, "no operand"):
                eng.swap_weights("lin", {"nope": np.zeros((8, 4))})
            with self.assertRaisesRegex(ValueError, "model="):
                eng.swap_weights("bare", {"w": np.zeros((8, 4))})
        finally:
            eng.close()


class TestDrainAndClose(TestCase):
    def test_close_drains_queued_work(self):
        eng = _engine()
        eng.register(
            "id", predict=lambda x: x, feature_dim=4, max_batch=32,
            max_delay_s=30.0, warm=True,  # timer will never fire
        )
        futures = [eng.submit("id", np.ones((2, 4), dtype=np.float32)) for _ in range(3)]
        eng.close(drain=True)
        for fut in futures:
            self.assertEqual(np.asarray(fut.result(10)).shape[0], 2)
        stats = eng.stats()
        self.assertGreaterEqual(stats["flush_cause"]["drain"], 1)
        self.assertGreaterEqual(stats["drains"], 1)
        with self.assertRaisesRegex(RequestRejected, "closed"):
            eng.submit("id", np.ones((1, 4), dtype=np.float32))
        eng.close()  # idempotent

    def test_close_without_drain_fails_pending_with_closed(self):
        eng = _engine()
        eng.register(
            "id", predict=lambda x: x, feature_dim=4, max_batch=32,
            max_delay_s=30.0, warm=True,
        )
        fut = eng.submit("id", np.ones((1, 4), dtype=np.float32))
        eng.close(drain=False)
        try:
            fut.result(10)
        except RequestRejected as exc:
            self.assertEqual(exc.reason, "closed")
        # drained-before-pop races are fine: either outcome resolved the
        # future, which is the actual contract (never a hang)


class TestTelemetrySurface(TestCase):
    def test_latency_histograms_reach_prometheus(self):
        eng = _engine()
        try:
            eng.register("id", predict=lambda x: x, feature_dim=4, max_batch=8, warm=True)
            for _ in range(4):
                eng.predict("id", np.ones((2, 4), dtype=np.float32))
            lat = eng.stats()["latency"]["id"]
            self.assertEqual(lat["count"], 4)
            self.assertGreater(lat["p50_s"], 0.0)
            self.assertLessEqual(lat["p50_s"], lat["p99_s"])
            prom = telemetry.export_prometheus()
            self.assertIn("heat_tpu_serving_latency_id_p50_s", prom)
            self.assertIn("heat_tpu_serving_latency_id_p99_s", prom)
            self.assertIn("heat_tpu_serving_accepted", prom)
            report = telemetry.serving_report()
            self.assertEqual(report["accepted"], eng.stats()["accepted"])
        finally:
            eng.close()

    def test_shed_and_drain_reach_flight_recorder(self):
        with telemetry.telemetry_level("events"):
            telemetry.clear_events()
            eng = _engine()
            eng.register("id", predict=lambda x: x, feature_dim=4, max_batch=8)
            with self.assertRaises(RequestRejected):
                eng.submit("id", np.ones((9, 4), dtype=np.float32))  # too_large
            eng.close()
            kinds = [e["kind"] for e in telemetry.events()]
            self.assertIn("serving_endpoint", kinds)
            self.assertIn("serving_shed", kinds)
            self.assertIn("serving_drain", kinds)


class TestQuantizedKnnServing(TestCase):
    """ISSUE 15 workload: a k-NN endpoint registered with
    ``quantize=True`` serves batched queries against the int8 corpus —
    correct labels, released f32 master, and the same no-retrace law as
    every other endpoint (steady bucketed traffic adds zero fusion
    misses, zero ring builds, zero step compiles)."""

    def _fitted_knn(self, n=64, f=16):
        X = _RNG.normal(size=(n, f)).astype(np.float32)
        labels = (X[:, 0] > 0).astype(np.int32)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=3)
        knn.fit(ht.array(X, split=0), ht.array(labels, split=0))
        return knn

    def test_register_quantize_requires_hook(self):
        eng = _engine()
        try:
            with self.assertRaisesRegex(ValueError, "quantize_"):
                eng.register(
                    "q", predict=lambda x: x, feature_dim=4, quantize=True
                )
        finally:
            eng.close()

    def test_quantized_endpoint_serves_and_never_retraces(self):
        knn = self._fitted_knn()
        eng = _engine()
        try:
            ep = eng.register(
                "knn_q", knn, feature_dim=16, min_bucket=8, max_batch=32,
                max_delay_s=0.002, warm=True, quantize=True,
            )
            self.assertIsNone(knn.x)  # master released at registration
            self.assertIsNotNone(knn._qx)

            sizes = [1, 3, 8, 2, 16, 5, 7, 4, 1, 12, 32, 6] * 2
            payloads = [
                _RNG.normal(size=(s, 16)).astype(np.float32) for s in sizes
            ]
            for p in payloads[: len(ep.buckets)]:
                eng.predict("knn_q", p)

            fusion_before = telemetry.snapshot_group("fusion").get("misses", 0)
            overlap_before = telemetry.snapshot_group("overlap").get(
                "ring_builds", 0
            )
            steps_before = eng.stats()["step_compiles"]

            with ThreadPoolExecutor(max_workers=8) as pool:
                futures = list(
                    pool.map(lambda p: eng.submit("knn_q", p), payloads)
                )
                results = [f.result(60) for f in futures]
            for p, r in zip(payloads, results):
                self.assertEqual(np.asarray(r).shape[0], p.shape[0])

            self.assertEqual(
                telemetry.snapshot_group("fusion").get("misses", 0),
                fusion_before,
                "steady traffic on the quantized corpus must not miss "
                "the fusion compile cache",
            )
            self.assertEqual(
                telemetry.snapshot_group("overlap").get("ring_builds", 0),
                overlap_before,
                "the quantized ring cdist must reuse its shard program",
            )
            self.assertEqual(eng.stats()["step_compiles"], steps_before)
        finally:
            eng.close()

    def test_quantized_endpoint_labels_agree_with_f32(self):
        knn = self._fitted_knn(n=48, f=8)
        q = _RNG.normal(size=(8, 8)).astype(np.float32)
        ref = np.asarray(knn.predict(ht.array(q, split=0)).numpy())
        eng = _engine()
        try:
            eng.register(
                "knn_q", knn, feature_dim=8, max_batch=16, quantize=True
            )
            got = np.asarray(eng.predict("knn_q", q)).ravel()
            # int8 corpus can flip exact distance ties; near-total
            # agreement is the contract (test_quantize pins the bound)
            self.assertGreaterEqual(float((ref.ravel() == got).mean()), 0.9)
        finally:
            eng.close()


if __name__ == "__main__":
    unittest.main()
