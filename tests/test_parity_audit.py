"""Regression gate: the full reference public API surface stays present.

scripts/parity_audit.py statically scans the reference's ``__all__`` lists
(plus estimator class names) and checks each name against this package —
See docs/PARITY.md for the current name count; all present.  Skipped when the reference tree is
not mounted (the audit is meaningless without it).
"""

import os
import unittest

from .base import TestCase

REFERENCE = os.environ.get("HEAT_REFERENCE_PATH", "/root/reference")


class TestParityAudit(TestCase):
    @unittest.skipUnless(
        os.path.isdir(os.path.join(REFERENCE, "heat")), "reference tree not mounted"
    )
    def test_no_missing_names(self):
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
        try:
            import parity_audit
        finally:
            sys.path.pop(0)

        present, missing = parity_audit.audit()
        n_present = sum(len(v) for v in present.values())
        self.assertEqual(missing, {}, f"missing reference names: {missing}")
        # the audited surface should not silently shrink either
        self.assertGreaterEqual(n_present, 328)
        # signature layer: every reference parameter name is accepted
        sig_problems = parity_audit.audit_signatures()
        self.assertEqual(sig_problems, {}, f"signature gaps: {sig_problems}")
        # class layer: estimator/nn/optim/data methods + parameter names
        cls_problems = parity_audit.audit_class_signatures()
        self.assertEqual(cls_problems, {}, f"class gaps: {cls_problems}")
        # DNDarray layer: the array class's public method surface
        nd_problems = parity_audit.audit_dndarray()
        self.assertEqual(nd_problems, {}, f"DNDarray gaps: {nd_problems}")
