"""Statistics case matrix (reference model: heat/core/tests/
test_statistics.py — every reduction x axis x split x keepdims x dtype,
plus the quantile/histogram family).

NumPy is the oracle throughout; distributed assertions go through
``assert_array_equal``'s per-shard check.  The quantile family runs
through the distributed sort on split inputs, so NaN propagation and
vector-q cases double as end-to-end sort coverage.
"""

import numpy as np

import heat_tpu as ht
from .base import TestCase


def _splits(ndim):
    return [None] + list(range(ndim))


class TestReductionMatrix(TestCase):
    """mean/var/std/min/max over every axis x split x keepdims."""

    def setUp(self):
        rng = np.random.default_rng(71)
        self.m = rng.standard_normal((13, 7)).astype(np.float32)
        self.t = rng.standard_normal((4, 5, 6)).astype(np.float32)

    def _sweep(self, ht_fn, np_fn, data, axes, rtol=1e-4, **kw):
        for axis in axes:
            for keepdims in (False, True):
                expected = np_fn(data, axis=axis, keepdims=keepdims, **kw)
                for s in _splits(data.ndim):
                    with self.subTest(axis=axis, keepdims=keepdims, split=s):
                        x = ht.array(data, split=s)
                        r = ht_fn(x, axis=axis, keepdims=keepdims)
                        if np.isscalar(expected) or expected.ndim == 0:
                            np.testing.assert_allclose(
                                float(r.numpy()), expected, rtol=rtol
                            )
                        else:
                            self.assert_array_equal(r, expected, rtol=rtol)

    def test_mean_matrix_2d(self):
        self._sweep(ht.mean, np.mean, self.m, [None, 0, 1, (0, 1)])

    def test_mean_matrix_3d(self):
        self._sweep(ht.mean, np.mean, self.t, [None, 0, 1, 2, (0, 2), (1, 2)])

    def test_var_matrix_2d(self):
        self._sweep(ht.var, np.var, self.m, [None, 0, 1])

    def test_var_ddof1(self):
        for s in _splits(2):
            r = ht.var(ht.array(self.m, split=s), axis=0, ddof=1)
            self.assert_array_equal(r, np.var(self.m, axis=0, ddof=1), rtol=1e-4)

    def test_std_matrix(self):
        self._sweep(ht.std, np.std, self.m, [None, 0, 1])

    def test_min_max_matrix(self):
        self._sweep(ht.min, np.min, self.m, [None, 0, 1])
        self._sweep(ht.max, np.max, self.m, [None, 0, 1])
        self._sweep(ht.min, np.min, self.t, [0, 2])
        self._sweep(ht.max, np.max, self.t, [1, (0, 1)])

    def test_sum_prod_matrix(self):
        self._sweep(ht.sum, np.sum, self.m, [None, 0, 1, (0, 1)])
        small = (self.m[:4, :4] * 0.5).astype(np.float32)
        self._sweep(ht.prod, np.prod, small, [None, 0, 1], rtol=1e-3)

    def test_int_dtype_reductions(self):
        data = np.arange(35, dtype=np.int32).reshape(5, 7)
        for s in _splits(2):
            self.assertEqual(int(ht.sum(ht.array(data, split=s)).numpy()), data.sum())
            self.assertEqual(int(ht.max(ht.array(data, split=s)).numpy()), data.max())
            self.assertEqual(int(ht.min(ht.array(data, split=s)).numpy()), data.min())

    def test_empty_axis_reduction_on_sharded(self):
        # 3 rows over 8 devices: reductions must ignore pad shards
        data = np.arange(9, dtype=np.float32).reshape(3, 3)
        for s in _splits(2):
            with self.subTest(split=s):
                np.testing.assert_allclose(
                    float(ht.sum(ht.array(data, split=s)).numpy()), data.sum()
                )
                np.testing.assert_allclose(
                    float(ht.min(ht.array(data, split=s)).numpy()), data.min()
                )


class TestArgReductions(TestCase):
    def setUp(self):
        rng = np.random.default_rng(73)
        self.m = rng.permutation(91).reshape(13, 7).astype(np.float32)

    def test_argmax_argmin_matrix(self):
        for fn_ht, fn_np in [(ht.argmax, np.argmax), (ht.argmin, np.argmin)]:
            for axis in (None, 0, 1):
                expected = fn_np(self.m, axis=axis)
                for s in _splits(2):
                    with self.subTest(fn=fn_np.__name__, axis=axis, split=s):
                        r = fn_ht(ht.array(self.m, split=s), axis=axis)
                        got = r.numpy()
                        if axis is None:
                            self.assertEqual(int(got), expected)
                        else:
                            np.testing.assert_array_equal(
                                got.astype(np.int64), expected
                            )

    def test_argmax_ties_take_first(self):
        data = np.asarray([[1, 3, 3], [3, 1, 3]], np.float32)
        for s in _splits(2):
            np.testing.assert_array_equal(
                ht.argmax(ht.array(data, split=s), axis=1).numpy().astype(np.int64),
                np.argmax(data, axis=1),
            )


class TestQuantileFamily(TestCase):
    def setUp(self):
        rng = np.random.default_rng(79)
        self.v = rng.standard_normal(101).astype(np.float32)
        self.m = rng.standard_normal((12, 9)).astype(np.float32)

    def test_median_matrix(self):
        for axis in (None, 0, 1):
            expected = np.median(self.m, axis=axis)
            for s in _splits(2):
                with self.subTest(axis=axis, split=s):
                    r = ht.median(ht.array(self.m, split=s), axis=axis)
                    got = r.numpy()
                    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)

    def test_percentile_scalar_q(self):
        for q in (0, 25, 50, 75, 100):
            expected = np.percentile(self.v, q)
            for s in (None, 0):
                with self.subTest(q=q, split=s):
                    r = ht.percentile(ht.array(self.v, split=s), q)
                    np.testing.assert_allclose(
                        float(r.numpy()), expected, rtol=1e-5, atol=1e-6
                    )

    def test_percentile_vector_q(self):
        q = [10, 50, 90]
        expected = np.percentile(self.v, q)
        for s in (None, 0):
            with self.subTest(split=s):
                r = ht.percentile(ht.array(self.v, split=s), q)
                np.testing.assert_allclose(r.numpy(), expected, rtol=1e-5, atol=1e-6)

    def test_median_with_nan_propagates(self):
        data = self.v.copy()
        data[7] = np.nan
        for s in (None, 0):
            with self.subTest(split=s):
                r = ht.median(ht.array(data, split=s))
                self.assertTrue(np.isnan(float(r.numpy())))

    def test_median_odd_even_lengths(self):
        for n in (5, 6, 13, 16):
            data = np.random.default_rng(n).standard_normal(n).astype(np.float32)
            for s in (None, 0):
                with self.subTest(n=n, split=s):
                    r = ht.median(ht.array(data, split=s))
                    np.testing.assert_allclose(
                        float(r.numpy()), np.median(data), rtol=1e-5, atol=1e-6
                    )


class TestCovCorr(TestCase):
    def setUp(self):
        rng = np.random.default_rng(83)
        self.m = rng.standard_normal((6, 40)).astype(np.float32)

    def test_cov_matrix(self):
        expected = np.cov(self.m)
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.cov(ht.array(self.m, split=s))
                self.assert_array_equal(r, expected.astype(np.float32), rtol=1e-3)

    def test_cov_ddof0(self):
        expected = np.cov(self.m, ddof=0)
        r = ht.cov(ht.array(self.m, split=1), ddof=0)
        self.assert_array_equal(r, expected.astype(np.float32), rtol=1e-3)

    def test_average_weighted(self):
        w = np.abs(np.random.default_rng(5).standard_normal(6)).astype(np.float32)
        expected = np.average(self.m, axis=0, weights=w)
        for s in _splits(2):
            with self.subTest(split=s):
                r = ht.average(
                    ht.array(self.m, split=s), axis=0, weights=ht.array(w)
                )
                self.assert_array_equal(r, expected, rtol=1e-4)

    def test_skew_kurtosis_match_scipy_def(self):
        # ht defaults to unbiased=True (the reference's convention,
        # statistics.py:1679) = scipy's bias=False
        from scipy import stats as sps

        v = np.random.default_rng(11).standard_normal(500).astype(np.float32)
        for s in (None, 0):
            with self.subTest(split=s):
                np.testing.assert_allclose(
                    float(ht.skew(ht.array(v, split=s)).numpy()),
                    sps.skew(v, bias=False), rtol=1e-3, atol=1e-4,
                )
                np.testing.assert_allclose(
                    float(ht.kurtosis(ht.array(v, split=s)).numpy()),
                    sps.kurtosis(v, bias=False), rtol=1e-3, atol=1e-4,
                )
                np.testing.assert_allclose(
                    float(ht.skew(ht.array(v, split=s), unbiased=False).numpy()),
                    sps.skew(v, bias=True), rtol=1e-3, atol=1e-4,
                )


class TestHistogramFamily(TestCase):
    def setUp(self):
        rng = np.random.default_rng(89)
        self.v = rng.standard_normal(200).astype(np.float32)

    def test_histogram_default_bins(self):
        for s in (None, 0):
            with self.subTest(split=s):
                hist, edges = ht.histogram(ht.array(self.v, split=s))
                want_hist, want_edges = np.histogram(self.v)
                np.testing.assert_array_equal(
                    hist.numpy().astype(np.int64), want_hist
                )
                np.testing.assert_allclose(edges.numpy(), want_edges, rtol=1e-5)

    def test_histogram_explicit_range(self):
        hist, edges = ht.histogram(ht.array(self.v, split=0), bins=20, range=(-2, 2))
        want_hist, want_edges = np.histogram(self.v, bins=20, range=(-2, 2))
        np.testing.assert_array_equal(hist.numpy().astype(np.int64), want_hist)
        np.testing.assert_allclose(edges.numpy(), want_edges, rtol=1e-5, atol=1e-6)

    def test_bincount(self):
        data = np.random.default_rng(3).integers(0, 9, 100).astype(np.int32)
        for s in (None, 0):
            with self.subTest(split=s):
                r = ht.bincount(ht.array(data, split=s))
                np.testing.assert_array_equal(
                    r.numpy().astype(np.int64), np.bincount(data)
                )

    def test_bincount_weights(self):
        data = np.random.default_rng(4).integers(0, 5, 50).astype(np.int32)
        w = np.random.default_rng(5).standard_normal(50).astype(np.float32)
        r = ht.bincount(ht.array(data, split=0), weights=ht.array(w, split=0))
        np.testing.assert_allclose(
            r.numpy(), np.bincount(data, weights=w), rtol=1e-4, atol=1e-5
        )

    def test_digitize_bucketize(self):
        bins = np.asarray([-1.0, 0.0, 1.0], np.float32)
        for right in (False, True):
            expected = np.digitize(self.v, bins, right=right)
            for s in (None, 0):
                with self.subTest(right=right, split=s):
                    r = ht.digitize(
                        ht.array(self.v, split=s), ht.array(bins), right=right
                    )
                    np.testing.assert_array_equal(
                        r.numpy().astype(np.int64), expected
                    )


class TestStatChains(TestCase):
    """Statistics over manipulated distributed inputs — reductions must be
    correct on op outputs that carry non-trivial physical layouts."""

    def test_moments_of_concatenated(self):
        rng = np.random.default_rng(97)
        a = rng.standard_normal((9, 5)).astype(np.float32)
        b = rng.standard_normal((6, 5)).astype(np.float32)
        cat = np.concatenate([a, b])
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.concatenate([ht.array(a, split=s), ht.array(b, split=s)], axis=0)
                self.assert_array_equal(ht.mean(x, axis=0), cat.mean(axis=0), rtol=1e-4)
                self.assert_array_equal(ht.var(x, axis=0), cat.var(axis=0), rtol=1e-3)

    def test_median_of_sorted_equals_median(self):
        v = np.random.default_rng(101).standard_normal(51).astype(np.float32)
        x = ht.array(v, split=0)
        sv, _ = ht.sort(x, axis=0)
        np.testing.assert_allclose(
            float(ht.median(sv).numpy()), np.median(v), rtol=1e-5
        )

    def test_standardize_pipeline(self):
        rng = np.random.default_rng(103)
        m = rng.standard_normal((40, 6)).astype(np.float32) * 3 + 1
        for s in _splits(2):
            with self.subTest(split=s):
                x = ht.array(m, split=s)
                z = (x - ht.mean(x, axis=0)) / ht.std(x, axis=0)
                expected = (m - m.mean(axis=0)) / m.std(axis=0)
                self.assert_array_equal(z, expected, rtol=1e-3)
                np.testing.assert_allclose(
                    ht.mean(z, axis=0).numpy(), np.zeros(6), atol=1e-5
                )

    def test_argmax_of_rolled(self):
        v = np.random.default_rng(107).permutation(29).astype(np.float32)
        x = ht.roll(ht.array(v, split=0), 7)
        self.assertEqual(
            int(ht.argmax(x).numpy()), int(np.argmax(np.roll(v, 7)))
        )
