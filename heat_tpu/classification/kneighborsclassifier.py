"""K-nearest-neighbors classification (reference:
heat/classification/kneighborsclassifier.py, 136 LoC).

``predict`` = distance matrix (MXU quadratic expansion) + top-k + one-hot
vote — the reference's cdist-ring + custom MPI top-k reduce (manipulations.py
mpi_topk:3981) collapse into ``lax.top_k`` on the sharded distance matrix."""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray, _ensure_split
from ..core import types
from ..spatial import distance

__all__ = ["KNeighborsClassifier"]


class KNeighborsClassifier(ClassificationMixin, BaseEstimator):
    """KNN classifier (reference: kneighborsclassifier.py:9)."""

    def __init__(self, n_neighbors: int = 5, effective_metric_: Optional[Callable] = None):
        self.n_neighbors = n_neighbors
        self.effective_metric_ = (
            effective_metric_ if effective_metric_ is not None else distance.cdist
        )
        self.x = None
        self.y = None
        self.classes_ = None
        self._qx = None  # quantized corpus (quantize_()); replaces self.x
        self._stream_src = None  # out-of-core corpus handle (fit_stream())
        self._stream_own = False
        self._stream_plan = None
        self._stream_budget = None
        self.last_stream_report = None

    @staticmethod
    def one_hot_encoding(x: DNDarray) -> DNDarray:
        """One-hot-encode a vector / single-column matrix of class indices
        (reference: kneighborsclassifier.py:45)."""
        from ..core import factories, statistics

        labels = x.larray.reshape(-1).astype("int32")
        n_features = int(statistics.max(x).item()) + 1  # ht: HT002 ok — one scalar readback fixes the one-hot width at fit
        encoded = jax.nn.one_hot(labels, n_features, dtype="float32")
        out = factories.array(encoded, split=x.split, device=x.device, comm=x.comm)
        return out

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Store the training set (reference: kneighborsclassifier.py:62).
        Labels may be class indices (1-D) or one-hot (2-D)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        sanitation.sanitize_in(y)
        if x.shape[0] != y.shape[0]:
            raise ValueError(
                f"Number of samples x and y samples mismatch: {x.shape[0]} != {y.shape[0]}"
            )
        self.x = x
        if y.ndim == 1:
            classes = jnp.unique(y.larray)
            self.classes_ = DNDarray(
                classes, tuple(classes.shape),
                types.canonical_heat_type(classes.dtype), None, y.device, y.comm,
            )
            onehot = (y.larray[:, None] == classes[None, :]).astype(jnp.float32)
            self.y = DNDarray(
                onehot, tuple(onehot.shape), types.float32, y.split, y.device, y.comm
            )
        else:
            self.y = y
            self.classes_ = None
        return self

    def quantize_(self, dtype: str = "int8", *, donate: bool = False) -> "KNeighborsClassifier":
        """Quantize the fitted corpus in place (int8/fp8, absmax scales
        per FEATURE — axis 1 of the (n_train, d) corpus) and DROP the
        full-precision master: steady-state HBM residency falls ~4x for
        an f32 corpus, and queries run through the quantized ring cdist
        (int8 blocks on the ICI wire, per-step dequant at the MXU).
        ``donate=True`` additionally donates the master's buffer to the
        quantization program and poisons it for the use-after-donate
        sanitizer.  This is the hook ``serving.register(...,
        quantize=True)`` calls on its model."""
        from ..core import quantize

        if self.x is None:
            raise RuntimeError(
                "fit the model first" if self._qx is None
                else "corpus is already quantized"
            )
        if self.effective_metric_ is not distance.cdist:
            raise ValueError(
                "quantize_ supports the default euclidean metric only"
            )
        self._qx = quantize.quantize_weights(
            self.x, dtype, axis=1, donate=donate
        )
        self.x = None  # release the master — the residency win
        return self

    def fit_stream(self, source, y, dataset: Optional[str] = None, *,
                   comm=None, budget=None) -> "KNeighborsClassifier":
        """Fit on a corpus that does not fit in HBM: store the chunk-source
        HANDLE, not the data.  ``predict`` then streams the corpus past the
        (device-resident) queries once per call, carrying a running best-k
        per query through :func:`distance._stream_topk_merge` — labels
        match the in-memory predict bitwise wherever distances are exact
        (same squared-distance kernel, same stable-tie ``top_k``).

        ``y`` is in-memory (class indices, 1-D, or one-hot, 2-D): the
        label table is a vector-sized side input the votes gather from by
        global corpus id, so it stays replicated on device.  The source
        handle stays open across predicts; :meth:`close_stream` releases
        it."""
        import numpy as np

        from ..core import factories, stream
        from ..parallel.mesh import sanitize_comm

        comm = sanitize_comm(comm)
        src = stream.open_source(source, dataset=dataset,
                                 np_dtype=np.float32)
        if len(src.shape) != 2:
            raise ValueError(
                f"corpus needs to be 2-D, but was {len(src.shape)}-D"
            )
        n = src.shape[0]
        y_host = np.asarray(y.larray if isinstance(y, DNDarray) else y)
        if y_host.shape[0] != n:
            raise ValueError(
                f"Number of samples x and y samples mismatch: {n} != {y_host.shape[0]}"
            )
        if y_host.ndim == 1:
            classes = np.unique(y_host)
            self.classes_ = factories.array(classes, split=None, comm=comm)
            onehot = (y_host[:, None] == classes[None, :]).astype(np.float32)
        else:
            self.classes_ = None
            onehot = y_host.astype(np.float32)
        # replicated: votes gather rows by GLOBAL corpus id
        self.y = factories.array(onehot, split=None, comm=comm)
        self.close_stream()
        self._stream_src = src
        self._stream_own = src is not source
        self._stream_plan = None
        self._stream_budget = budget
        self.x = None
        self._qx = None
        return self

    def close_stream(self) -> None:
        """Release the out-of-core corpus handle (no-op when not streaming
        or when the caller owns the :class:`stream.ChunkSource`)."""
        if self._stream_src is not None and self._stream_own:
            self._stream_src.close()
        self._stream_src = None
        self._stream_plan = None

    def _predict_stream(self, x: DNDarray) -> DNDarray:
        from ..core import stream, telemetry

        src = self._stream_src
        if self._stream_plan is None:
            # plan ONCE and reuse: a stable slab_rows keeps every later
            # predict in the slab bucket warmed by the first (no-retrace
            # law behind the serving front door)
            self._stream_plan = stream.plan_pass(
                src, comm=x.comm, site="knn_predict",
                budget=self._stream_budget,
            )
        pl = self._stream_plan
        q = x.larray
        if not jnp.issubdtype(q.dtype, jnp.floating):
            q = q.astype(jnp.float32)
        k = self.n_neighbors
        nq = q.shape[0]
        best_d = jnp.full((nq, k), jnp.inf, jnp.float32)
        best_i = jnp.zeros((nq, k), jnp.int32)
        sp = stream.StreamPass(src, comm=x.comm, plan=pl)
        for slab in sp:
            best_d, best_i = distance._stream_topk_merge(
                q, slab.x.larray, slab.valid, slab.base, best_d, best_i, k
            )
            del slab  # drop the loop reference: 3-slab residency cap
        rep = stream.finish_pass(sp)
        self.last_stream_report = dict(rep, arm=pl.arm, budget=pl.budget)
        n, f = src.shape
        fp = telemetry.fingerprint(
            ("stream_knn", pl.slab_rows, f, k, nq, x.comm.size)
        )
        telemetry.ensure_program(
            fp, kind="stream_knn", dtype="float32",
            flops=2.0 * n * f * nq, hbm_bytes=float(n) * f * 4,
        )
        telemetry.record_timing(fp, rep["wall_s"])
        telemetry.annotate_program(
            fp, io_stall_frac=round(1.0 - rep["overlap_frac"], 4),
            io_bytes=rep["bytes_read"],
        )
        votes = jnp.sum(self.y.larray[best_i], axis=1)
        winner = jnp.argmax(votes, axis=1)
        if self.classes_ is not None:
            labels = self.classes_.larray[winner]
        else:
            labels = winner
        out = DNDarray(
            labels, tuple(labels.shape), types.canonical_heat_type(labels.dtype),
            x.split, x.device, x.comm,
        )
        return _ensure_split(out, x.split)

    def predict(self, x: DNDarray) -> DNDarray:
        """Majority vote over the k nearest training samples (reference:
        kneighborsclassifier.py:117)."""
        if self._stream_src is not None:
            return self._predict_stream(x)
        if self.x is None and self._qx is None:
            raise RuntimeError("fit the model first")
        if self._qx is not None:
            dd = distance.cdist_quantized(x, self._qx)
            if dd is None:
                # ring-ineligible layout (1-device mesh, replicated
                # queries, ...): dequantize for this call and take the
                # ordinary cdist dispatch
                dd = self.effective_metric_(x, self._qx.dequantize())
            d = dd.larray
        else:
            d = self.effective_metric_(x, self.x).larray  # (n_query, n_train)
        _, idx = jax.lax.top_k(-d, self.n_neighbors)  # nearest k
        onehot = self.y.larray  # (n_train, n_classes)
        votes = jnp.sum(onehot[idx], axis=1)  # (n_query, n_classes)
        winner = jnp.argmax(votes, axis=1)
        if self.classes_ is not None:
            labels = self.classes_.larray[winner]
        else:
            labels = winner
        out = DNDarray(
            labels, tuple(labels.shape), types.canonical_heat_type(labels.dtype),
            x.split, x.device, x.comm,
        )
        return _ensure_split(out, x.split)
