"""Pipeline parallelism (beyond the reference: SURVEY.md §2.5 lists PP as
absent there — no parity requirement; this is the TPU-native extension).

A GPipe-style microbatch pipeline over a mesh axis: every device owns one
*stage* (a slice of a stack of structurally identical layers), activations
flow stage-to-stage with ``lax.ppermute``, and the whole schedule — fill,
steady state, drain — is one ``lax.scan`` inside ``shard_map``.  Because the
schedule is ordinary traced code, ``jax.grad`` through it yields the reverse
pipeline automatically; no hand-built backward schedule exists.

Layout contract: stage parameters are stacked on a leading axis of size
``n_stages`` sharded over the pipeline mesh axis, exactly how
:class:`heat_tpu.optim.DASO` stacks slice parameters over its dcn axis.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .collectives import shard_map_unchecked

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(params_list, mesh: Mesh, axis: str = "pp"):
    """Stack per-stage parameter trees on a leading dim sharded over the
    pipeline axis. All stages must share one tree structure."""
    n_stages = int(mesh.shape[axis])
    if len(params_list) != n_stages:
        raise ValueError(
            f"{len(params_list)} stage trees for a {n_stages}-way {axis!r} axis"
        )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)

    def place(x):
        spec = P(*([axis] + [None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, stacked)


def pipeline_apply(
    fn: Callable,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    n_micro: int,
):
    """Run ``x`` through the stage pipeline; returns the final activations.

    Parameters
    ----------
    fn : callable
        ``fn(stage_param_tree, activation) -> activation`` — one stage's
        compute. Activation shape must be preserved (stage-homogeneous
        pipelines, e.g. stacked transformer blocks).
    stage_params :
        Tree whose leaves carry a leading ``n_stages`` dim sharded over
        ``axis`` (see :func:`stack_stage_params`).
    x : jax.Array
        Batch, leading dim divisible by ``n_micro``.
    n_micro : int
        Microbatch count. Pipeline bubble fraction is
        ``(n_stages - 1) / (n_micro + n_stages - 1)``.
    """
    n_stages = int(mesh.shape[axis])
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading dim(s) {sorted(leading)} must equal the "
            f"mesh's {axis!r} axis size {n_stages}"
        )
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by n_micro={n_micro}")
    micro = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])

    def shard_fn(p, xs):
        # p: this stage's params (leading dim 1); xs: all microbatches,
        # replicated (the fill logic injects them on stage 0 only)
        idx = lax.axis_index(axis)
        stage_p = jax.tree.map(lambda a: a[0], p)
        ticks = n_micro + n_stages - 1
        zero = jnp.zeros_like(xs[0])

        def tick(carry, t):
            incoming = carry  # activation handed to me by the previous stage
            inject = xs[jnp.clip(t, 0, n_micro - 1)]
            cur = jnp.where((idx == 0) & (t < n_micro), inject, incoming)
            out = fn(stage_p, cur)
            nxt = lax.ppermute(
                out, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            # the last stage's output for microbatch (t - n_stages + 1)
            emit = jnp.where(idx == n_stages - 1, out, jnp.zeros_like(out))
            return nxt, emit

        _, emitted = lax.scan(tick, zero, jnp.arange(ticks))
        # valid outputs occupy ticks [n_stages-1, ticks); psum replicates
        # them (every stage but the last contributed zeros)
        outs = lax.psum(emitted[n_stages - 1 :], axis_name=axis)
        return outs

    in_specs = (
        jax.tree.map(lambda _: P(axis), stage_params),
        P(),
    )
    fn_sharded = shard_map_unchecked(shard_fn, mesh, in_specs, P())
    outs = fn_sharded(stage_params, micro)
    return outs.reshape((outs.shape[0] * outs.shape[1],) + outs.shape[2:])
