"""Tiled, donation-aware data-movement engine (round 6).

Every layout change in the system — resplit, split-crossing reshape,
int-array gather — is a data-movement program, and round 5 shipped each
as a MONOLITHIC collective: ``parallel/select.py`` staged the full global
output on every device before its one ``psum_scatter``, and resplit /
reshape round-tripped through the logical array and a ``device_put``
(ADVICE round-5 #2; VERDICT "What's weak" #1).  In the GSPMD lineage
(Xu et al. 2021) and the collective-matmul overlap work (Wang et al.,
ASPLOS'23), layout change is a *tiled transport*: a loop over bounded
tiles, each one collective of tile-sized buffers, so per-device peak
memory is ``O(N/S + tile)`` — the local slab plus one staging tile —
never ``O(N)``.

Three kernels, one discipline:

``tiled_take``
    ``out[t] = in[rows[t]]`` along the split axis.  The output chunk of
    every destination shard is cut into tiles; per tile, each shard
    contributes the requested rows it owns into an ``(S*tile)``-row
    buffer and one ``psum_scatter`` delivers the tile to its owner.
    Staging is ``S*tile`` rows instead of round 5's ``S*per_out``
    (= the whole global output).  ``rows`` may be host-resident
    (``np.ndarray``) or device-resident (``jax.Array`` — e.g. a
    ``nonzero()`` product), already normalized to ``[0, n)``.

``tiled_resplit``
    split ``sa`` → split ``sb``.  The local slab is viewed as
    ``(pa, S, pb)`` over the two split axes; per tile of ``pb`` columns,
    one ``all_to_all`` (split over the destination axis, concat along
    the source axis) lands the canonical destination chunk.  Total wire
    per shard is one local slab — the same volume as the GSPMD
    ``device_put`` route — but staged through bounded tiles, working on
    the PHYSICAL array directly (no unpad/re-pad round trip).

``tiled_reshape``
    split-crossing reshape in three stages: resplit to split-0, a flat
    *rechunk* (row size changes ``rowsz_in → rowsz_out``), resplit to
    the target split.  The rechunk exploits that both chunk boundary
    sets are host-known: each (source, destination) overlap is one
    contiguous interval, grouped by ring shift ``d - r``; one
    ``ppermute`` per distinct shift (typically ≤ 3) moves max-block
    buffers, chunked through ``fori_loop`` when blocks exceed the tile
    budget.  Intermediate stages donate their inputs, so XLA reuses the
    source HBM instead of holding both layouts live.

All tile loops run under ``lax.fori_loop``: a Python loop would let XLA
keep every tile buffer live simultaneously, putting peak memory right
back at ``O(N)``.  Donation is only applied to buffers the engine owns
(stage intermediates) or that the caller explicitly hands over
(``DNDarray.resplit_`` — an in-place, documented-destructive method).

Census laws over these kernels (tests/test_census_structural.py,
benchmarks/scaling/structural_main.py): collective count is 1 per kind
(loops count once), per-instruction bytes are tile-sized, and the
largest live buffer in the compiled program is the local slab — both
asserted at mesh 4 and 8.
"""

from __future__ import annotations

import functools
import os
from functools import lru_cache
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import autotune, guard, memtrack, telemetry
from ..core import wire as _wire
from ..analysis import program_audit, sanitize
from .collectives import shard_map_unchecked

__all__ = [
    "TILE_BYTES",
    "TILE_FLOOR_BYTES",
    "reset_stats",
    "stats",
    "tile_plan",
    "tiled_take",
    "tiled_resplit",
    "resplit_applicable",
    "tiled_reshape",
    "reshape_applicable",
    "rechunk_plan",
]


def _env_tile_bytes(env=None) -> int:
    # one parser with HEAT_TPU_MATMUL_RING_MIN_BYTES (autotune.env_bytes):
    # malformed/non-positive values raise with the same message shape
    return autotune.env_bytes("HEAT_TPU_TILE_BYTES", 8 << 20, env)


# Per-tile staging budget. 8 MiB keeps the per-peer all_to_all/psum_scatter
# message ≥ 1 MiB on an 8-shard mesh (the ICI bandwidth knee) while bounding
# the staging buffer far below any realistic local slab.  Overridable via
# HEAT_TPU_TILE_BYTES (e.g. for memory-starved meshes or backoff testing);
# under RESOURCE_EXHAUSTED pressure the engine halves the budget per retry
# down to TILE_FLOOR_BYTES (see _with_oom_backoff).
TILE_BYTES = _env_tile_bytes()

# Smallest budget the OOM backoff will retry at: below 64 KiB the per-peer
# message is latency-bound and a transfer that still OOMs is not going to
# be saved by smaller tiles — the local slab itself no longer fits.
TILE_FLOOR_BYTES = 64 << 10

# Fraction of measured free HBM the informed first retry claims for its
# tile: the staging tile and its gathered mirror are both in flight during
# an all_to_all step, plus allocator fragmentation headroom.
_FREE_TILE_FRACTION = 0.25


# ------------------------------------------------------------- OOM backoff

# Registered as the "transport" telemetry group: the registry owns the
# reset contract (the `fused_tails` counter previously had to be added
# here AND in reset_stats() by hand — that drift class is gone).
_STATS = telemetry.register_group(
    "transport",
    {
        # successful-but-retried transfers: each budget halving counts 1
        "oom_retries": 0,
        # transfers that still hit RESOURCE_EXHAUSTED at the floor (re-raised)
        "oom_exhausted": 0,
        # budget the most recent tiled transfer ran (and succeeded) at
        "last_tile_bytes": None,
        # per-kernel retry counts: {"resplit": n, "take": n, "reshape": n}
        "retries_by_kind": {},
        # retries whose budget came from measured free HBM (memory_stats)
        # rather than blind halving
        "informed_retries": 0,
        # whether the most recent retry was informed (None: no retry yet)
        "last_retry_informed": None,
        # split-terminated lazy chains whose elementwise tail lowered INTO
        # the per-tile resplit loop (no separate pre-pass materialization)
        "fused_tails": 0,
    },
)


def stats() -> dict:
    """Counters for the OOM-backoff machinery: ``oom_retries`` (budget
    halvings that led to a retry), ``oom_exhausted`` (transfers that still
    OOMed at ``TILE_FLOOR_BYTES`` and re-raised), ``last_tile_bytes`` (the
    budget the most recent transfer succeeded at — equal to the configured
    ``TILE_BYTES`` unless backoff engaged), ``retries_by_kind``,
    ``informed_retries`` / ``last_retry_informed`` (first retries whose
    budget was derived from measured free HBM instead of blind halving —
    see ``_with_oom_backoff``), and ``fused_tails`` (lazy-chain tails
    fused into the resplit tile loop — each one is a materialization
    pre-pass that did NOT happen).

    Thin shim over ``telemetry.snapshot_group("transport")`` — the same
    counters appear in ``ht.telemetry.snapshot()``."""
    return telemetry.snapshot_group("transport")


def reset_stats() -> None:
    """Zero the backoff counters (registry-managed: every counter in the
    registered defaults resets, with no second hand-maintained list)."""
    telemetry.reset_group("transport")


def _is_oom(err: Exception) -> bool:
    """Match XLA's allocation-failure surface (jaxlib raises
    ``XlaRuntimeError`` whose message leads with RESOURCE_EXHAUSTED) plus
    the backend variants that spell it out."""
    msg = str(err)
    return (
        "RESOURCE_EXHAUSTED" in msg
        or "Out of memory" in msg
        or "out of memory" in msg
    )


def _plan_tile_budget(kind: str) -> int:
    """Plan-time tile budget: with the tuning plane live
    (``HEAT_TPU_AUTOTUNE=on``), seed from measured free HBM UP FRONT —
    the same :func:`memtrack.suggest_budget` formula the informed OOM
    retry uses (quarter of free, floored), applied before the first
    attempt so a memory-tight mesh never pays the failed allocation at
    all.  Statsless backends (CPU) and ``HEAT_TPU_AUTOTUNE=off`` keep
    the static ``TILE_BYTES`` default."""
    if not autotune.enabled():
        return TILE_BYTES
    got = memtrack.suggest_budget(
        TILE_BYTES, fraction=_FREE_TILE_FRACTION, floor=TILE_FLOOR_BYTES,
    )
    if got is None or got >= TILE_BYTES:
        return TILE_BYTES
    autotune.note_budget_seed("transport." + kind, got, TILE_BYTES)
    return got


def _with_oom_backoff(kind: str, run, tile_bytes: Optional[int], fp=None,
                      observer=None):
    """Run ``run(tile_bytes)`` with bounded OOM backoff: on a
    RESOURCE_EXHAUSTED failure the tile budget halves and the transfer
    retries, down to ``TILE_FLOOR_BYTES`` — a transient allocation squeeze
    degrades throughput instead of killing the job.  Non-OOM errors
    propagate untouched.  ``guard.fire`` lets an installed FaultInjector
    deterministically raise/stall at each attempt (tests drive the real
    backoff path, no mocks).  ``fp`` is the caller's ledgered program
    fingerprint: when set, successful runs are (sampling-gated)
    wall-clocked into the measured-timing ledger — the first sighting
    includes the shard_map jit build, which the ``min_s``/``p50_s``
    robust statistics absorb.

    Informed first retry: when ``memory_stats()`` is available (TPU, or a
    test override via :func:`memtrack.stats_override` /
    ``FaultInjector.low_hbm``), the FIRST retry sizes its budget from the
    measured tightest free HBM instead of blind halving — capped at the
    halved budget (never larger, so monotone progress and termination are
    unchanged) and floored at ``TILE_FLOOR_BYTES``.  Stats-less backends
    (CPU) keep the pure halving walk.  Every OOM also attaches a buffer
    census (top live buffers with creation sites and pin state, plus the
    failing tile budget) to the flight-recorder trail and — via
    :func:`telemetry.postmortem` — the on-disk forensics dump.

    Donation caveat: a retry after a *failed donating execution* can find
    the input buffer already consumed by XLA; injected faults fire before
    the execution starts, and real RESOURCE_EXHAUSTED surfaces at
    allocation time before donation commits, so in practice the input
    survives — but a mid-execution OOM on a donated transfer is not
    recoverable and will re-raise from the retry."""
    tb = _plan_tile_budget(kind) if tile_bytes is None else int(tile_bytes)
    retried = False
    with telemetry.span(f"transport.{kind}", tile_bytes=tb):
        while True:
            try:
                guard.fire(f"transport.{kind}")
                out = telemetry.timed_call(fp, run, tb, observer=observer)
            except Exception as err:  # noqa: BLE001 — filtered to OOM below
                if not _is_oom(err):
                    raise
                census = (
                    memtrack.census(top=8) if telemetry.events_enabled() else None
                )
                if tb <= TILE_FLOOR_BYTES:
                    _STATS["oom_exhausted"] += 1
                    telemetry.record_event(
                        "oom_exhausted", kernel=kind, tile_bytes=tb,
                        census=census,
                    )
                    telemetry.postmortem(
                        "transport_oom_exhausted", kernel=kind, tile_bytes=tb,
                    )
                    raise
                halved = max(TILE_FLOOR_BYTES, tb >> 1)
                informed = None
                free = None
                if not retried:
                    free = memtrack.min_free_bytes()
                    if free is not None:
                        # size the retry from measured headroom: the tile's
                        # staging buffer and its gathered mirror are both in
                        # flight, so claim a conservative quarter of free —
                        # but never MORE than the halving would grant
                        informed = memtrack.suggest_budget(
                            halved, fraction=_FREE_TILE_FRACTION,
                            floor=TILE_FLOOR_BYTES, free=free,
                        )
                    # a recovered OOM still leaves a forensic trail: the
                    # first failure dumps the census-bearing document
                    telemetry.postmortem(
                        "transport_oom", kernel=kind, tile_bytes=tb,
                    )
                tb = informed if informed is not None else halved
                retried = True
                _STATS["oom_retries"] += 1
                if informed is not None:
                    _STATS["informed_retries"] += 1
                _STATS["last_retry_informed"] = informed is not None
                by_kind = _STATS["retries_by_kind"]
                by_kind[kind] = by_kind.get(kind, 0) + 1
                # the degradation trail: one event per retry, carrying the
                # NEW budget the retry will run at and how it was chosen
                telemetry.record_event(
                    "oom_retry", kernel=kind, tile_bytes=tb,
                    informed=informed is not None, free_bytes=free,
                    census=census,
                )
                continue
            _STATS["last_tile_bytes"] = tb
            # one chain link per successful tiled dispatch: the SPMD
            # lockstep fingerprint (analysis.sanitize) must be identical
            # on every rank
            sanitize.collective_event(kind, site=f"transport.{kind}")
            return guard.corrupt(f"transport.{kind}", out)

# Beyond this many distinct ring shifts the rechunk degenerates toward a
# latency-bound permute chain; callers fall back to the GSPMD route.
_MAX_SHIFTS = 4


def tile_plan(n_units, unit_bytes, tile_bytes=None) -> Tuple[int, int]:
    """Cut ``n_units`` units (each ``unit_bytes`` of per-tile staging) into
    tiles within the staging budget.  Returns ``(units_per_tile, n_tiles)``
    with ``units_per_tile * n_tiles >= n_units`` and tiles even-sized."""
    tb = TILE_BYTES if tile_bytes is None else int(tile_bytes)
    n_units = max(int(n_units), 1)
    per = max(1, tb // max(int(unit_bytes), 1))
    if per >= n_units:
        return n_units, 1
    n_tiles = -(-n_units // per)
    return -(-n_units // n_tiles), n_tiles


def _split_spec(axis_name: str, ndim: int, split: int) -> P:
    return P(*[axis_name if d == split else None for d in range(ndim)])


# --------------------------------------------------------------- int gather


def _build_tiled_gather(mesh, axis_name, split, ndim, per_out, tile_per, n_tiles):
    """Tiled ``out[t] = in[rows[t]]`` along the split axis.

    ``rows`` arrives as an ``(S * n_tiles*tile_per,)`` int32 buffer in
    *destination-grid* layout: entry ``(d, j)`` of the ``(S, padded)``
    view is the source row of destination shard ``d``'s output row ``j``
    (``j >= per_out`` entries are pad, sourcing row 0).  Tile ``t``
    covers rows ``[t*tile_per, (t+1)*tile_per)`` of EVERY destination
    shard simultaneously, so each ``psum_scatter`` delivers canonical
    chunks and the staging buffer is ``S*tile_per`` rows — not the
    ``S*per_out`` (global output) the round-5 monolith staged."""
    S = int(mesh.shape[axis_name])
    padded = n_tiles * tile_per

    def local(vals, rows):
        r = lax.axis_index(axis_name)
        v = jnp.moveaxis(vals, split, 0)
        per_in = v.shape[0]
        rows2 = rows.reshape(S, padded)

        def tile(t, acc):
            rows_t = lax.dynamic_slice(
                rows2, (0, t * tile_per), (S, tile_per)
            ).reshape(-1)
            loc = rows_t - r * per_in
            mine = (loc >= 0) & (loc < per_in)
            safe = jnp.clip(loc, 0, max(per_in - 1, 0))
            picked = jnp.take(v, safe, axis=0)
            mine_b = mine.reshape((-1,) + (1,) * (picked.ndim - 1))
            picked = jnp.where(mine_b, picked, jnp.zeros((), picked.dtype))
            got = lax.psum_scatter(
                picked, axis_name, scatter_dimension=0, tiled=True
            )
            return lax.dynamic_update_slice_in_dim(acc, got, t * tile_per, axis=0)

        acc = jnp.zeros((padded,) + v.shape[1:], v.dtype)
        if n_tiles == 1:
            acc = tile(0, acc)
        else:
            acc = lax.fori_loop(0, n_tiles, tile, acc)
        out = acc[:per_out] if padded != per_out else acc
        return jnp.moveaxis(out, 0, split)

    spec = _split_spec(axis_name, ndim, split)
    smapped = shard_map_unchecked(
        local, mesh, in_specs=(spec, P()), out_specs=spec
    )

    def run(vals, rows):
        # psum_scatter has no bool reduction: route bool payloads via uint8
        isbool = vals.dtype == jnp.bool_
        v = vals.astype(jnp.uint8) if isbool else vals
        out = smapped(v, rows)
        return out.astype(jnp.bool_) if isbool else out

    return run


@lru_cache(maxsize=512)
def _jit_tiled_gather(mesh, axis_name, split, ndim, per_out, tile_per, n_tiles):
    return jax.jit(
        _build_tiled_gather(mesh, axis_name, split, ndim, per_out, tile_per, n_tiles)
    )


def _row_bytes(phys: jax.Array, split: int) -> int:
    itemsize = max(int(jnp.dtype(phys.dtype).itemsize), 1)
    rest = 1
    for d, e in enumerate(phys.shape):
        if d != split:
            rest *= int(e)
    return rest * itemsize


def tiled_take(
    phys_vals: jax.Array,
    rows,
    mesh,
    axis_name: str,
    split: int,
    tile_bytes: Optional[int] = None,
) -> jax.Array:
    """Gather ``phys_vals``'s rows ``rows`` along the sharded axis ``split``
    (canonical physical layout) through the tiled engine.  ``rows`` is 1-D
    int, host- (``np.ndarray``) or device-resident (``jax.Array``), already
    normalized to ``[0, n)`` — out-of-range rows would silently read
    padding.  Returns the physical output: canonical even-chunk layout with
    extent ``len(rows)`` on the split axis.  The output extent is static
    (``rows.shape[0]``), so device-resident rows cost no host sync.
    RESOURCE_EXHAUSTED retries with a halved tile budget (see
    :func:`_with_oom_backoff`).

    The wire plane never quantizes this kernel: the ``psum_scatter``
    SUMS contributions across shards, so the payload IS the data — a
    lossy wire would corrupt the gathered rows, and masked-out lanes
    already ride as exact zeros.  Statically declined (``wire.decline``)
    so the decline is visible in the wire counters."""
    _wire.decline("take")
    S = int(mesh.shape[axis_name])
    n_out = int(rows.shape[0])
    per_out = -(-n_out // S) if n_out else 1

    def run(tb):
        # staging unit = one output row replicated across the S send slots
        tile_per, n_tiles = tile_plan(
            per_out, S * _row_bytes(phys_vals, split), tb
        )
        padded = n_tiles * tile_per
        if isinstance(rows, np.ndarray):
            flat = np.asarray(rows, np.int32)
            grid = np.zeros((S, padded), np.int32)
            jj, dd = np.meshgrid(np.arange(padded), np.arange(S))
            gidx = dd * per_out + jj
            valid = (jj < per_out) & (gidx < n_out)
            grid[valid] = flat[gidx[valid]]
            rows_arg = jnp.asarray(grid.reshape(-1))
        else:
            flat = rows.astype(jnp.int32)
            jj = jnp.arange(padded)[None, :]
            gidx = jnp.arange(S)[:, None] * per_out + jj
            valid = (jj < per_out) & (gidx < n_out)
            grid = jnp.where(valid, flat[jnp.clip(gidx, 0, max(n_out - 1, 0))], 0)
            rows_arg = grid.reshape(-1)
        fn = _jit_tiled_gather(
            mesh, axis_name, int(split), phys_vals.ndim, per_out, tile_per, n_tiles
        )
        return fn(phys_vals, rows_arg)

    fp = None
    if telemetry.ledger_enabled():
        itemsize = max(int(jnp.dtype(phys_vals.dtype).itemsize), 1)
        in_elems = int(phys_vals.size)
        n_split = max(int(phys_vals.shape[split]), 1)
        # read the source slab once, write n_out gathered rows once
        out_bytes = (in_elems // n_split) * n_out * itemsize
        fp = telemetry.fingerprint(
            ("take", tuple(int(d) for d in phys_vals.shape), int(split),
             n_out, S, str(phys_vals.dtype)),
        )
        telemetry.ensure_program(
            fp, kind="transport_take", ops=1, flops=0.0,
            hbm_bytes=float(in_elems * itemsize + out_bytes),
            mesh={"devices": S}, dtype=str(phys_vals.dtype),
        )
    return _with_oom_backoff("take", run, tile_bytes, fp=fp)


# ------------------------------------------------------------------ resplit


def _build_tiled_resplit(mesh, axis_name, ndim, sa, sb, n_a, n_b, tile_cols,
                         n_tiles, wire=""):
    """split ``sa`` → split ``sb`` as a loop over destination-column tiles.

    The local slab (physical ``sa``-chunk, full logical ``sb`` extent) is
    padded to the destination's physical extent and viewed as
    ``(pa, S, pb)`` over the two split axes; per tile, one ``all_to_all``
    splits over the destination axis and concatenates along the source
    axis — landing each shard's canonical destination chunk directly.
    Padding along ``sa`` (the source's physical tail) rides along and is
    sliced off after the loop, so the output carries clean ``sb``-padding
    only.

    ``wire`` (``""`` | ``"int8"`` | ``"fp8"``) is the on-wire format
    (round 17, ``core/wire.py``): per tile, each ``(pa, S)`` row block is
    absmax-quantized to the narrow dtype with one f32 scale per row
    immediately before the ``all_to_all``; the quantized payload and the
    scale table cross the wire as a pair of collectives and the landing
    side dequantizes into the f32-accumulated slab inside the same
    program.  All-zero rows (the zero-pad lanes) carry scale 1 and
    round-trip exactly, so the physical zero-pad contract survives a
    lossy wire."""
    S = int(mesh.shape[axis_name])
    pb = -(-n_b // S)
    padded_b = n_tiles * tile_cols

    def local(xv):
        xv = jnp.moveaxis(xv, (sa, sb), (0, 1))
        pa, nb = xv.shape[0], xv.shape[1]
        rest = xv.shape[2:]
        padw = [(0, 0), (0, S * pb - nb)] + [(0, 0)] * (xv.ndim - 2)
        xv = jnp.pad(xv, padw)
        xr = xv.reshape((pa, S, pb) + rest)
        if padded_b != pb:
            pw = [(0, 0), (0, 0), (0, padded_b - pb)] + [(0, 0)] * len(rest)
            xr = jnp.pad(xr, pw)

        def tile(t, acc):
            blk = lax.dynamic_slice_in_dim(xr, t * tile_cols, tile_cols, axis=2)
            if wire:
                # scale per (pa, S) row: the quantization grain matches
                # the all_to_all's split/concat axes, so each landed row
                # arrives with exactly its own scale
                q, scale = _wire.absmax_encode(blk, wire, axes=(0, 1))
                got_q = lax.all_to_all(
                    q, axis_name, split_axis=1, concat_axis=0, tiled=True
                )
                got_s = lax.all_to_all(
                    scale, axis_name, split_axis=1, concat_axis=0, tiled=True
                )
                got = _wire.absmax_decode(
                    got_q.reshape((S * pa, tile_cols) + rest),
                    got_s.reshape((S * pa,)), (0,), xv.dtype,
                )
            else:
                got = lax.all_to_all(
                    blk, axis_name, split_axis=1, concat_axis=0, tiled=True
                ).reshape((S * pa, tile_cols) + rest)
            return lax.dynamic_update_slice_in_dim(
                acc, got, t * tile_cols, axis=1
            )

        acc = jnp.zeros((S * pa, padded_b) + rest, xv.dtype)
        if n_tiles == 1:
            acc = tile(0, acc)
        else:
            acc = lax.fori_loop(0, n_tiles, tile, acc)
        out = acc[:n_a, :pb]
        return jnp.moveaxis(out, (0, 1), (sa, sb))

    return shard_map_unchecked(
        local,
        mesh,
        in_specs=(_split_spec(axis_name, ndim, sa),),
        out_specs=_split_spec(axis_name, ndim, sb),
    )


@lru_cache(maxsize=512)
def _jit_tiled_resplit(
    mesh, axis_name, ndim, sa, sb, n_a, n_b, tile_cols, n_tiles, donate,
    wire="",
):
    fn = _build_tiled_resplit(
        mesh, axis_name, ndim, sa, sb, n_a, n_b, tile_cols, n_tiles, wire
    )
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def resplit_applicable(gshape: Sequence[int], sa, sb, comm) -> bool:
    """True iff :func:`tiled_resplit` handles this layout change: a real
    axis-to-axis move on a multi-shard mesh with every extent nonzero
    (degenerate cases keep the ``device_put`` route — nothing to tile)."""
    return (
        comm.size > 1
        and sa is not None
        and sb is not None
        and sa != sb
        and len(gshape) >= 2
        and all(int(d) > 0 for d in gshape)
    )


def tiled_resplit(
    phys: jax.Array,
    gshape: Sequence[int],
    sa: int,
    sb: int,
    comm,
    donate: bool = False,
    tile_bytes: Optional[int] = None,
    exact: bool = False,
) -> jax.Array:
    """Move ``phys`` (canonical physical layout, split ``sa``) to split
    ``sb`` through the tiled engine.  ``donate=True`` hands the input
    buffer to XLA for reuse — only pass it for buffers with no other live
    reference (in-place ``resplit_``, stage intermediates).
    RESOURCE_EXHAUSTED retries with a halved tile budget (see
    :func:`_with_oom_backoff`).

    Wire plane (round 17): large float payloads may ship absmax-quantized
    int8/fp8 tiles instead of full-width words — the per-link format is
    an autotune arm over ``autotune.WIRE_ARMS``, forced by
    ``HEAT_TPU_WIRE``, and statically declined for integer/bool dtypes,
    sub-threshold payloads, and ``exact=True`` callers (who need the
    f32-wire bit pattern, e.g. comparison fixtures)."""
    sanitize.check_use(phys, "transport.tiled_resplit")
    S = comm.size
    gshape_t = tuple(int(d) for d in gshape)
    n_a, n_b = gshape_t[sa], gshape_t[sb]
    pa = int(phys.shape[sa]) // S
    pb = -(-n_b // S)
    itemsize = max(int(jnp.dtype(phys.dtype).itemsize), 1)
    rest = 1
    for d, e in enumerate(phys.shape):
        if d not in (sa, sb):
            rest *= int(e)
    nelem = 1
    for d in gshape_t:
        nelem *= d
    logical_bytes = nelem * itemsize

    def _mk_run(wm, donate_arg, fp_arg):
        def run(tb):
            # staging unit = one destination column across (pa, S, rest)
            tile_cols, n_tiles = tile_plan(pb, pa * S * rest * itemsize, tb)
            fn = _jit_tiled_resplit(
                comm.mesh, comm.split_axis, phys.ndim, int(sa), int(sb),
                n_a, n_b, tile_cols, n_tiles, donate_arg, wm,
            )
            if program_audit.enabled():
                program_audit.audit_program(
                    "transport_resplit", fp_arg, fn, (phys,),
                    donate=(0,) if donate_arg else (), expect="any",
                )
            return fn(phys)

        return run

    # on-wire byte model (exact, from shapes): every logical element
    # crosses the wire once at 1 byte, plus one f32 scale per (pa, S)
    # row per tile per shard — computed from the same tile plan the
    # dispatch will use
    tile_cols0, n_tiles0 = tile_plan(pb, pa * S * rest * itemsize, tile_bytes)
    n_scales = pa * S * n_tiles0 * S

    fp = None
    if telemetry.ledger_enabled():
        fp = telemetry.fingerprint(
            ("resplit", gshape_t, int(sa), int(sb), S, str(phys.dtype)),
        )
        # mandatory HBM traffic: read the source slab once, write the
        # destination slab once — the per-tile wire bytes are ICI
        telemetry.ensure_program(
            fp, kind="transport_resplit", ops=1, flops=0.0,
            hbm_bytes=2.0 * nelem * itemsize, mesh={"devices": S},
            dtype=str(phys.dtype),
        )

    def _wire_fp(wm):
        # separate ledger row per wire arm: the roofline report must see
        # the compressed on-wire volume against the same logical bytes
        if not telemetry.ledger_enabled():
            return None
        fpw = telemetry.fingerprint(
            ("resplit_wire", gshape_t, int(sa), int(sb), S,
             str(phys.dtype), wm),
        )
        telemetry.ensure_program(
            fpw, kind="transport_resplit", ops=1, flops=0.0,
            hbm_bytes=2.0 * nelem * itemsize, mesh={"devices": S},
            dtype=str(phys.dtype), wire=wm,
            logical_bytes=float(logical_bytes),
            wire_bytes=float(_wire.payload_nbytes(nelem, n_scales, wm)),
        )
        return fpw

    wire_arm, wire_d = "wire_f32", None
    if _wire.eligible(phys.dtype, logical_bytes, exact=exact):
        wire_arm, wire_d = _wire.choose(
            "resplit", (gshape_t, int(sa), int(sb), S, str(phys.dtype)),
            desc=f"resplit {gshape_t} {sa}->{sb} {phys.dtype} S={S}",
        )

    if wire_d is not None and wire_d.explore:
        # explore: every wire arm runs under measurement (donation
        # suppressed — the same source buffer feeds all runs) and the
        # f32 result is returned, so numerics never depend on tuning
        # state mid-explore
        def run_for(wm):
            fpx = fp if not wm else _wire_fp(wm)
            return _with_oom_backoff(
                "resplit", _mk_run(wm, False, fpx), tile_bytes, fp=fpx,
            )

        return _wire.explore(wire_d, run_for)
    if wire_arm != "wire_f32":
        wm = wire_arm[len("wire_"):]
        fpw = _wire_fp(wm)
        # the sampled observer keeps the degradation watch alive for
        # table-decided arms; forced modes (wire_d None) have no table
        observer = (
            functools.partial(autotune.observe, wire_d.key, wire_arm)
            if wire_d is not None else None
        )
        _wire.account(
            "resplit", wire_arm, logical_bytes,
            _wire.payload_nbytes(nelem, n_scales, wm),
        )
        return _with_oom_backoff(
            "resplit", _mk_run(wm, bool(donate), fpw), tile_bytes, fp=fpw,
            observer=observer,
        )
    return _with_oom_backoff(
        "resplit", _mk_run("", bool(donate), fp), tile_bytes, fp=fp
    )


# ------------------------------------------------- fused elementwise tail

# Op kinds the tile loop can replay per-block: shape-preserving maps whose
# value at an element depends on that element alone.  Reductions, scans,
# matmuls and composite kernels carry axis semantics that do not survive
# the (pa, S, tile_cols) re-view and decline to the pre-pass route.
_FUSED_TAIL_KINDS = frozenset({"elementwise", "cast", "comparison", "predicate"})


def _build_tiled_resplit_fused(
    mesh, axis_name, ndim, sa, sb, n_a, n_b, tile_cols, n_tiles,
    out_slot, instrs, leaf_kinds, out_dtype_str, wire="",
):
    """:func:`_build_tiled_resplit` with the chain's elementwise tail
    evaluated inside the tile loop: tile *k*'s compute overlaps the
    collective for tile *k+1* (same schedule the ring matmul uses for its
    dots), so the chain output is never materialized in the OLD split.

    ``instrs`` is the fusion engine's deduplicated instruction list; every
    full-shape leaf arrives in canonical source-split physical layout and
    is viewed as ``(pa, S, pb)`` exactly like the unfused engine's single
    operand, scalars broadcast per block.  The chain also runs on the
    padding lanes and produces garbage there (``f(0) != 0``, or Inf/NaN
    from e.g. ``1/x`` / ``log`` at zero).  Round 15 hardening: source-
    axis pad rows are zeroed PER TILE before the ``all_to_all`` (garbage
    — in particular non-finite values — never rides the wire or lands in
    the accumulator), and destination-axis pad columns are re-zeroed
    after the loop, so the output keeps the clean zero-pad physical
    contract on both split axes."""
    S = int(mesh.shape[axis_name])
    pb = -(-n_b // S)
    padded_b = n_tiles * tile_cols
    out_dtype = jnp.dtype(out_dtype_str)
    # bool has no all_to_all wire format on some backends: ship uint8
    wire_dtype = jnp.dtype(jnp.uint8) if out_dtype == jnp.dtype(jnp.bool_) else out_dtype

    def local(*leaf_vals):
        prepped = []
        pa = 1
        rest = ()
        for v, kind in zip(leaf_vals, leaf_kinds):
            if kind == "scalar":
                prepped.append(v)
                continue
            xv = jnp.moveaxis(v, (sa, sb), (0, 1))
            nb = xv.shape[1]
            rest = xv.shape[2:]
            padw = [(0, 0), (0, S * pb - nb)] + [(0, 0)] * (xv.ndim - 2)
            xr = jnp.pad(xv, padw).reshape((xv.shape[0], S, pb) + rest)
            if padded_b != pb:
                pw = [(0, 0), (0, 0), (0, padded_b - pb)] + [(0, 0)] * len(rest)
                xr = jnp.pad(xr, pw)
            pa = xr.shape[0]
            prepped.append(xr)

        # source-axis pad-lane mask (transport hazard, round 15): the
        # chain evaluated f on the physical pad rows of axis ``sa``;
        # zero its output there before the collective so garbage never
        # leaves the shard.  Slicing after the loop also removed it, but
        # non-finite values would still have crossed the wire and sat in
        # the accumulator slab.
        src_keep = None
        if S * pa != n_a:
            rows = lax.axis_index(axis_name) * pa + jnp.arange(pa)
            src_keep = (rows < n_a).reshape((pa, 1, 1) + (1,) * len(rest))

        def tile(t, acc):
            env = {}
            for s_i, ins in enumerate(instrs):
                if ins[0] == "L":
                    blk = prepped[ins[1]]
                    if leaf_kinds[ins[1]] == "full":
                        blk = lax.dynamic_slice_in_dim(
                            blk, t * tile_cols, tile_cols, axis=2
                        )
                    env[s_i] = blk
                else:
                    _, fn, kw, ch = ins
                    env[s_i] = fn(*(env[c] for c in ch), **dict(kw))
            blk = env[out_slot].astype(wire_dtype)
            if src_keep is not None:
                blk = jnp.where(src_keep, blk, jnp.zeros((), wire_dtype))
            if wire:
                # the src_keep masking above already zeroed pad rows, so
                # the quantized pad lanes carry scale 1 and round-trip
                # as exact zeros (core/wire.py contract)
                q, scale = _wire.absmax_encode(blk, wire, axes=(0, 1))
                got_q = lax.all_to_all(
                    q, axis_name, split_axis=1, concat_axis=0, tiled=True
                )
                got_s = lax.all_to_all(
                    scale, axis_name, split_axis=1, concat_axis=0, tiled=True
                )
                got = _wire.absmax_decode(
                    got_q.reshape((S * pa, tile_cols) + rest),
                    got_s.reshape((S * pa,)), (0,), wire_dtype,
                )
            else:
                got = lax.all_to_all(
                    blk, axis_name, split_axis=1, concat_axis=0, tiled=True
                ).reshape((S * pa, tile_cols) + rest)
            return lax.dynamic_update_slice_in_dim(
                acc, got, t * tile_cols, axis=1
            )

        acc = jnp.zeros((S * pa, padded_b) + rest, wire_dtype)
        if n_tiles == 1:
            acc = tile(0, acc)
        else:
            acc = lax.fori_loop(0, n_tiles, tile, acc)
        out = acc[:n_a, :pb]
        if S * pb != n_b:
            me = lax.axis_index(axis_name)
            cols = me * pb + jnp.arange(pb)
            keep = (cols < n_b).reshape((1, pb) + (1,) * len(rest))
            out = jnp.where(keep, out, jnp.zeros((), wire_dtype))
        return jnp.moveaxis(out.astype(out_dtype), (0, 1), (sa, sb))

    in_specs = tuple(
        _split_spec(axis_name, ndim, sa) if k == "full" else P()
        for k in leaf_kinds
    )
    return shard_map_unchecked(
        local,
        mesh,
        in_specs=in_specs,
        out_specs=_split_spec(axis_name, ndim, sb),
    )


@lru_cache(maxsize=512)
def _jit_tiled_resplit_fused(
    mesh, axis_name, ndim, sa, sb, n_a, n_b, tile_cols, n_tiles,
    out_slot, instrs, leaf_kinds, out_dtype_str, wire="",
):
    # never donating: the leaves belong to still-pending expressions (the
    # chain may have OTHER consumers that want the old-split value)
    fn = _build_tiled_resplit_fused(
        mesh, axis_name, ndim, sa, sb, n_a, n_b, tile_cols, n_tiles,
        out_slot, instrs, leaf_kinds, out_dtype_str, wire,
    )
    return jax.jit(fn)


def _lower_split_tail(
    instrs, leaves, out_slot, lshapes, gshape, sa, sb, comm, tile_bytes
):
    """Split-boundary terminator (``fusion.register_split_terminator``
    contract): lower a lazy chain that ends at a ``sa -> sb`` resplit
    directly into the tiled transport loop, returning the physical array
    already in split ``sb`` — or ``None`` to decline (caller falls back to
    materialize-then-resplit).

    Accepts exactly the shapes the tile loop can replay: every op is a
    registered shape-preserving map (``_FUSED_TAIL_KINDS``), every leaf is
    either the chain's full-shape operand in canonical source-split
    physical layout or a one-element scalar, and the root is full-shape.
    Anything else — reductions, ``where=`` masks (their ``jnp.where`` /
    ``jnp.zeros`` factory nodes are unregistered), broadcast-shaped
    operands, replicated or foreign-split full leaves — declines."""
    from ..core import fusion

    gshape = tuple(int(d) for d in gshape)
    if not resplit_applicable(gshape, sa, sb, comm):
        return None
    if instrs[out_slot][0] != "O":
        return None
    S = comm.size
    ndim = len(gshape)
    n_a, n_b = gshape[sa], gshape[sb]
    pa = -(-n_a // S)
    phys_shape = tuple(S * pa if i == sa else gshape[i] for i in range(ndim))

    leaf_kinds = []
    for lf, lshape in zip(leaves, lshapes):
        lshape = tuple(int(d) for d in lshape)
        nelem = 1
        for d in lshape:
            nelem *= d
        if lshape == gshape:
            if tuple(int(d) for d in lf.value.shape) != phys_shape:
                return None
            leaf_kinds.append("full")
        elif nelem == 1:
            leaf_kinds.append("scalar")
        else:
            return None
    leaf_kinds = tuple(leaf_kinds)

    avals = []
    for ins in instrs:
        if ins[0] == "L":
            lf = leaves[ins[1]]
            avals.append(
                jax.ShapeDtypeStruct(tuple(lshapes[ins[1]]), lf.value.dtype)
            )
            continue
        _, fn, kw, ch = ins
        meta = fusion._OP_TABLE.get(fn)
        if meta is None or meta[1] not in _FUSED_TAIL_KINDS:
            return None
        child_avals = tuple(avals[c] for c in ch)
        try:
            aval = fusion._infer_aval(fn, child_avals, kw)
        except Exception:
            return None
        shp = tuple(int(d) for d in aval.shape)
        if shp == gshape:
            # a full-shape op must consume at least one full-shape child:
            # childless factories (jnp.zeros) have no tiled source view
            if not any(
                tuple(int(d) for d in ca.shape) == gshape for ca in child_avals
            ):
                return None
        else:
            n = 1
            for d in shp:
                n *= d
            if n != 1:
                return None
        avals.append(aval)
    root_aval = avals[out_slot]
    if tuple(int(d) for d in root_aval.shape) != gshape:
        return None
    out_dtype_str = str(root_aval.dtype)

    # one-element leaves broadcast identically at any rank; rank-0 keeps
    # the per-block broadcast independent of the moveaxis re-view
    leaf_vals = tuple(
        lf.value.reshape(()) if kind == "scalar" else lf.value
        for lf, kind in zip(leaves, leaf_kinds)
    )

    itemsize = max(int(jnp.dtype(root_aval.dtype).itemsize), 1)
    rest = 1
    for d in range(ndim):
        if d not in (sa, sb):
            rest *= gshape[d]
    pb = -(-n_b // S)
    nelem = 1
    for d in gshape:
        nelem *= d

    # wire consult (consume-only): the fused program must not be
    # double-executed by an explore, so this site keys on the SAME
    # ("resplit", geometry) entry the eager engine tunes — an eager
    # explore of the same shape warms this consult, exactly like the
    # lazy matmul chain rides the eager ring explores.  out_dtype (the
    # chain root, what actually crosses the wire) drives eligibility.
    wire_m = ""
    if _wire.eligible(root_aval.dtype, nelem * itemsize):
        wire_m = _wire.consume(
            "resplit", (gshape, int(sa), int(sb), S, out_dtype_str)
        )

    def run(tb):
        tile_cols, n_tiles = tile_plan(pb, pa * S * rest * itemsize, tb)
        fn = _jit_tiled_resplit_fused(
            comm.mesh, comm.split_axis, ndim, int(sa), int(sb), n_a, n_b,
            tile_cols, n_tiles, int(out_slot), instrs, leaf_kinds,
            out_dtype_str, wire_m,
        )
        return fn(*leaf_vals)

    fp = None
    if telemetry.ledger_enabled():
        n_ops = sum(1 for ins in instrs if ins[0] == "O")
        in_bytes = sum(
            int(v.size) * int(jnp.dtype(v.dtype).itemsize)
            for v in leaf_vals
        )
        fp = telemetry.fingerprint(
            ("fused_tail", gshape, int(sa), int(sb), S, instrs,
             out_dtype_str, wire_m),
        )
        # same cost model as the fusion engine: one FLOP per output
        # element per op in the tail; HBM traffic = leaves in + slab out
        extra = {}
        if wire_m:
            _, n_tiles0 = tile_plan(pb, pa * S * rest * itemsize, tile_bytes)
            extra = dict(
                wire=wire_m,
                logical_bytes=float(nelem * itemsize),
                wire_bytes=float(_wire.payload_nbytes(
                    nelem, pa * S * n_tiles0 * S, wire_m
                )),
            )
        telemetry.ensure_program(
            fp, kind="fused_resplit_tail", ops=n_ops,
            flops=float(n_ops * nelem),
            hbm_bytes=float(in_bytes + nelem * itemsize),
            mesh={"devices": S}, dtype=out_dtype_str, **extra,
        )
    if wire_m:
        _, n_tiles0 = tile_plan(pb, pa * S * rest * itemsize, tile_bytes)
        _wire.account(
            "resplit_tail", "wire_" + wire_m, nelem * itemsize,
            _wire.payload_nbytes(nelem, pa * S * n_tiles0 * S, wire_m),
        )
    out = _with_oom_backoff("resplit", run, tile_bytes, fp=fp)
    _STATS["fused_tails"] += 1
    telemetry.record_event(
        "fused_tail", old_split=int(sa), new_split=int(sb), ops=len(instrs),
    )
    return out


_FUSED_TAIL_REGISTERED = False


def ensure_fused_tail_registered() -> None:
    """Idempotently register :func:`_lower_split_tail` with the fusion
    engine's split-terminator registry (called lazily from
    ``fusion.materialize_resplit`` so core never imports parallel at
    module load)."""
    global _FUSED_TAIL_REGISTERED
    if _FUSED_TAIL_REGISTERED:
        return
    from ..core import fusion

    fusion.register_split_terminator(_lower_split_tail)
    _FUSED_TAIL_REGISTERED = True


# ------------------------------------------------------------------ reshape


def rechunk_plan(m_in, rowsz_in, m_out, rowsz_out, S):
    """Host plan for moving the flat element stream from split-0 rows of
    size ``rowsz_in`` to split-0 rows of size ``rowsz_out``.

    Both chunk boundary sets are host-known, so each (source,
    destination) overlap is ONE contiguous interval; entries are grouped
    by ring shift ``(d - r) % S`` — per shift, arrays indexed by SOURCE
    shard of (local source offset, destination-local offset, length).
    Returns a hashable tuple of ``(shift, src_off, dst_off, lens)``
    entries (shift 0 = local copy), or ``None`` when the plan needs more
    than ``_MAX_SHIFTS`` distinct nonzero shifts (latency-bound permute
    chain — callers fall back to the GSPMD route)."""
    M = m_in * rowsz_in
    if M != m_out * rowsz_out or M == 0:
        return None
    pa = -(-m_in // S)
    pb = -(-m_out // S)
    B_in = [min(r * pa, m_in) * rowsz_in for r in range(S + 1)]
    B_out = [min(d * pb, m_out) * rowsz_out for d in range(S + 1)]
    shifts = {}
    for r in range(S):
        lo_r, hi_r = B_in[r], B_in[r + 1]
        if lo_r == hi_r:
            continue
        for d in range(S):
            lo = max(lo_r, B_out[d])
            hi = min(hi_r, B_out[d + 1])
            if lo >= hi:
                continue
            s = (d - r) % S
            ent = shifts.setdefault(
                s, {"src": [0] * S, "dst": [0] * S, "len": [0] * S}
            )
            ent["src"][r] = lo - B_in[r]
            ent["dst"][r] = lo - B_out[d]
            ent["len"][r] = hi - lo
    if sum(1 for s in shifts if s != 0) > _MAX_SHIFTS:
        return None
    return tuple(
        (s, tuple(e["src"]), tuple(e["dst"]), tuple(e["len"]))
        for s, e in sorted(shifts.items())
    )


def _build_rechunk(mesh, axis_name, shape_in, shape_out, plan, chunk,
                   repack="", wire=""):
    """Flat rechunk: split-0 rows of ``shape_in[1:]`` → split-0 rows of
    ``shape_out[1:]`` following a host-computed :func:`rechunk_plan`.

    One ``ppermute`` per distinct nonzero shift moves a max-block-sized
    buffer around the ring; per-shard offsets and lengths ride as static
    ``(S,)`` tables indexed by ``axis_index``, and the receive side
    scatters with an out-of-range sentinel so invalid tails drop.  Blocks
    beyond the tile budget stream through ``fori_loop`` chunks; the
    source slab is padded by one chunk so the final partial chunk's
    ``dynamic_slice`` never clamps (a clamped start would misalign the
    valid head).

    ``repack`` (``""`` | ``"interpret"`` | ``"tpu"``) routes the final
    local reshape through the lane-aware Pallas repack kernel
    (``ops/repack.py``) — the narrow-minor ``kernel`` autotune arm that
    writes the output at ~1x logical bytes instead of the padded
    ~12.8x.  Bit-exact either way; the arm only changes physical
    layout traffic.

    ``wire`` (``""`` | ``"int8"`` | ``"fp8"``) quantizes each permuted
    chunk on the absmax grid with ONE scalar f32 scale per chunk
    (``core/wire.py``): payload and scale ride the same ``ppermute``
    ring hop and the receive side dequantizes before the scatter.  Only
    nonzero shifts quantize — the shift-0 local copy never leaves the
    shard."""
    S = int(mesh.shape[axis_name])
    pa = -(-shape_in[0] // S)
    pb = -(-shape_out[0] // S)
    rowsz_out = 1
    for e in shape_out[1:]:
        rowsz_out *= int(e)
    loc_out = pb * rowsz_out

    def local(xv):
        v = xv.reshape(-1)
        acc = jnp.zeros((loc_out,), v.dtype)
        r = lax.axis_index(axis_name)
        for s, src_off, dst_off, lens in plan:
            so_a = jnp.asarray(src_off, jnp.int32)
            do_a = jnp.asarray(dst_off, jnp.int32)
            ln_a = jnp.asarray(lens, jnp.int32)
            Ls = max(lens)
            ch = min(chunk, Ls)
            n_ch = -(-Ls // ch)
            vp = jnp.pad(v, (0, ch))

            def body(cidx, acc, s=s, so_a=so_a, do_a=do_a, ln_a=ln_a, ch=ch):
                blk = lax.dynamic_slice_in_dim(vp, so_a[r] + cidx * ch, ch)
                if s % S != 0:
                    perm = [(i, (i + s) % S) for i in range(S)]
                    if wire:
                        q, scale = _wire.absmax_encode(blk, wire, axes=())
                        q = lax.ppermute(q, axis_name, perm=perm)
                        scale = lax.ppermute(scale, axis_name, perm=perm)
                        blk = _wire.absmax_decode(q, scale, (), v.dtype)
                    else:
                        blk = lax.ppermute(blk, axis_name, perm=perm)
                rs = (r - s) % S
                i = cidx * ch + jnp.arange(ch)
                pos = jnp.where(i < ln_a[rs], do_a[rs] + i, loc_out)
                return acc.at[pos].set(blk, mode="drop")

            if n_ch == 1:
                acc = body(0, acc)
            else:
                acc = lax.fori_loop(0, n_ch, body, acc)
        loc_shape = (pb,) + tuple(shape_out[1:])
        if repack:
            from ..ops import repack as _repack_kernel

            return _repack_kernel.repack(
                acc, loc_shape, interpret=(repack == "interpret")
            )
        return acc.reshape(loc_shape)

    return shard_map_unchecked(
        local,
        mesh,
        in_specs=(P(*([axis_name] + [None] * (len(shape_in) - 1))),),
        out_specs=P(*([axis_name] + [None] * (len(shape_out) - 1))),
    )


@lru_cache(maxsize=512)
def _jit_rechunk(mesh, axis_name, shape_in, shape_out, plan, chunk, donate,
                 repack="", wire=""):
    fn = _build_rechunk(
        mesh, axis_name, shape_in, shape_out, plan, chunk, repack, wire
    )
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def _build_local_reshape(mesh, axis_name, ndim_in, split, shape_loc_out, out_split):
    """Split-preserving reshape: when the split extent and the flat prefix
    product are both preserved, the global reshape never crosses a chunk
    boundary and each shard reshapes its own slab — collective-free."""

    def local(xv):
        return xv.reshape(shape_loc_out)

    return shard_map_unchecked(
        local,
        mesh,
        in_specs=(_split_spec(axis_name, ndim_in, split),),
        out_specs=_split_spec(axis_name, len(shape_loc_out), out_split),
    )


@lru_cache(maxsize=512)
def _jit_local_reshape(mesh, axis_name, ndim_in, split, shape_loc_out, out_split):
    return jax.jit(
        _build_local_reshape(mesh, axis_name, ndim_in, split, shape_loc_out, out_split)
    )


def _prefix_prod(shape, k):
    p = 1
    for e in shape[:k]:
        p *= int(e)
    return p


def reshape_applicable(gin, si, gout, so, comm) -> bool:
    """True iff :func:`tiled_reshape` handles this reshape: distributed
    input and output, every extent nonzero, and a rechunk plan within the
    shift budget."""
    if comm.size <= 1 or si is None or so is None:
        return False
    if any(int(d) <= 0 for d in gin) or any(int(d) <= 0 for d in gout):
        return False
    if _prefix_prod(gin, si) == _prefix_prod(gout, so) and int(gin[si]) == int(
        gout[so]
    ):
        return True  # split-preserving: the collective-free local path
    rowsz_in = _prefix_prod(gin, len(gin)) // int(gin[0])
    rowsz_out = _prefix_prod(gout, len(gout)) // int(gout[0])
    return (
        rechunk_plan(int(gin[0]), rowsz_in, int(gout[0]), rowsz_out, comm.size)
        is not None
    )


def tiled_reshape(
    phys: jax.Array,
    gin: Sequence[int],
    si: int,
    gout: Sequence[int],
    so: int,
    comm,
    tile_bytes: Optional[int] = None,
    donate: bool = False,
    exact: bool = False,
) -> jax.Array:
    """Split-crossing reshape ``gin``/split ``si`` → ``gout``/split ``so``
    on physical arrays.  Stages: resplit to split-0, flat rechunk, resplit
    to ``so`` — the stage intermediates are donated; the caller's input is
    donated only with ``donate=True`` (pass it solely for buffers with no
    other live reference, e.g. a fused-tail pre-stage output the caller
    owns).  Callers must check :func:`reshape_applicable` first.
    ``exact=True`` pins the f32 wire on every stage (see
    :func:`tiled_resplit`)."""
    sanitize.check_use(phys, "transport.tiled_reshape")
    S = comm.size
    gin = tuple(int(d) for d in gin)
    gout = tuple(int(d) for d in gout)

    # split-preserving fast path: chunk boundaries never crossed
    if _prefix_prod(gin, si) == _prefix_prod(gout, so) and gin[si] == gout[so]:
        pa = int(phys.shape[si]) // S
        loc_out = tuple(
            pa if d == so else int(e) for d, e in enumerate(gout)
        )
        fn = _jit_local_reshape(
            comm.mesh, comm.split_axis, phys.ndim, int(si), loc_out, int(so)
        )
        return fn(phys)

    if si != 0:
        phys = tiled_resplit(phys, gin, si, 0, comm, donate=donate,
                             tile_bytes=tile_bytes, exact=exact)
        mid_owned = True
    else:
        mid_owned = donate

    rowsz_in = _prefix_prod(gin, len(gin)) // gin[0]
    rowsz_out = _prefix_prod(gout, len(gout)) // gout[0]
    plan = rechunk_plan(gin[0], rowsz_in, gout[0], rowsz_out, S)
    if plan is None:  # pragma: no cover - guarded by reshape_applicable
        raise ValueError("rechunk plan out of shift budget")
    itemsize = max(int(jnp.dtype(phys.dtype).itemsize), 1)

    def _mk_run(repack_arm, donate_arg, wm="", phys=phys):
        def run(tb):
            chunk = max(1, tb // itemsize)
            fn = _jit_rechunk(
                comm.mesh, comm.split_axis, gin, gout, plan, chunk,
                donate_arg, repack_arm, wm,
            )
            return fn(phys)

        return run

    # narrow-minor kernel arm (ops/repack.py): eligible when the local
    # output block has a < 128-lane minor dim and the Pallas tier is
    # live; dispatched per fingerprint by the autotune table, measured
    # against the classic lowering.  Safe decline: any ineligibility
    # (layout, backend, kill switch, autotune off) keeps the classic
    # path byte-for-byte, with no table entry created.
    from ..ops import repack as _repack

    pb_out = -(-gout[0] // S)
    loc_out_shape = (pb_out,) + gout[1:]
    kmode = _repack.repack_mode(loc_out_shape, phys.dtype)

    nelem = 1
    for d in gin:
        nelem *= d
    fp = fp_k = None
    if telemetry.ledger_enabled():
        fp = telemetry.fingerprint(
            ("reshape", gin, int(si), gout, int(so), S, str(phys.dtype)),
        )
        telemetry.ensure_program(
            fp, kind="transport_reshape", ops=1, flops=0.0,
            hbm_bytes=2.0 * nelem * itemsize, mesh={"devices": S},
            dtype=str(phys.dtype),
        )
        if kmode != "off":
            # separate ledger row per arm: the roofline report must
            # attribute the repack win (same logical bytes, higher
            # achieved fraction) instead of averaging it into the
            # classic row
            fp_k = telemetry.fingerprint(
                ("reshape_repack", gin, int(si), gout, int(so), S,
                 str(phys.dtype)),
            )
            telemetry.ensure_program(
                fp_k, kind="kernel_repack", ops=1, flops=0.0,
                hbm_bytes=2.0 * nelem * itemsize, mesh={"devices": S},
                dtype=str(phys.dtype),
            )

    # on-wire byte model for the rechunk stage (exact, from the plan):
    # per nonzero shift, each shard ships n_ch chunk-sized blocks (the
    # tail chunk pads to ch) plus one f32 scale per block
    tb0 = TILE_BYTES if tile_bytes is None else int(tile_bytes)
    chunk0 = max(1, tb0 // itemsize)
    wire_elems = wire_scales = 0
    for s_, _so, _do, lens in plan:
        if s_ % S == 0:
            continue
        Ls = max(lens)
        ch = min(chunk0, Ls)
        n_ch = -(-Ls // ch)
        wire_elems += S * n_ch * ch
        wire_scales += S * n_ch
    logical_moved = wire_elems * itemsize

    def _wire_fp(wm):
        if not telemetry.ledger_enabled():
            return None
        fpw = telemetry.fingerprint(
            ("reshape_wire", gin, int(si), gout, int(so), S,
             str(phys.dtype), wm),
        )
        telemetry.ensure_program(
            fpw, kind="transport_reshape", ops=1, flops=0.0,
            hbm_bytes=2.0 * nelem * itemsize, mesh={"devices": S},
            dtype=str(phys.dtype), wire=wm,
            logical_bytes=float(logical_moved),
            wire_bytes=float(_wire.payload_nbytes(wire_elems, wire_scales, wm)),
        )
        return fpw

    wire_arm, wire_d = "wire_f32", None
    if logical_moved and _wire.eligible(phys.dtype, logical_moved,
                                        exact=exact):
        wire_arm, wire_d = _wire.choose(
            "rechunk", (gin, gout, S, str(phys.dtype)),
            desc=f"rechunk {gin}->{gout} {phys.dtype} S={S}",
        )

    arm = "classic"
    key = None
    if wire_d is not None and wire_d.explore:
        # wire explore round: every wire arm runs the classic lowering
        # under measurement, f32 result returned.  The repack arm stays
        # out of this round (one tuning axis per call keeps the explore
        # unambiguous); it gets its own consult on later f32-arm calls.
        def run_for(wm):
            fpx = fp if not wm else _wire_fp(wm)
            return _with_oom_backoff(
                "reshape", _mk_run("", False, wm), tile_bytes, fp=fpx
            )

        phys = _wire.explore(wire_d, run_for)
        arm = "wire"
    elif wire_arm != "wire_f32":
        wm = wire_arm[len("wire_"):]
        fpw = _wire_fp(wm)
        observer = (
            functools.partial(autotune.observe, wire_d.key, wire_arm)
            if wire_d is not None else None
        )
        _wire.account(
            "rechunk", wire_arm, logical_moved,
            _wire.payload_nbytes(wire_elems, wire_scales, wm),
        )
        phys = _with_oom_backoff(
            "reshape", _mk_run("", mid_owned, wm), tile_bytes, fp=fpw,
            observer=observer,
        )
        arm = "wire"
    elif kmode != "off" and autotune.enabled():
        key = autotune.kernel_key(
            "reshape_repack", gin, int(si), gout, int(so), S,
            str(phys.dtype),
        )
        d = autotune.decide(
            key, "classic",
            desc=f"reshape {gin}->{gout} minor={gout[-1]}",
            arms=autotune.KERNEL_ARMS,
        )
        if d.explore:
            # run BOTH arms under measurement; donation suppressed (the
            # same source buffer feeds both runs).  The classic result
            # is returned, so numerics never depend on tuning state
            # (repack is bit-exact anyway — this keeps the invariant
            # uniform across kernel sites).
            out_c, t_c = autotune.timed(
                lambda: _with_oom_backoff(
                    "reshape", _mk_run("", False), tile_bytes, fp=fp
                )
            )
            out_k, t_k = autotune.timed(
                lambda: _with_oom_backoff(
                    "reshape", _mk_run(kmode, False), tile_bytes, fp=fp_k
                )
            )
            autotune.observe(key, "classic", t_c)
            autotune.observe(key, "kernel", t_k)
            memtrack.register_buffer(out_k, tag="staging", split=0)
            phys = out_c
            arm = "explore"
        elif d.arm == "kernel":
            arm = "kernel"
    if arm == "kernel":
        # steady state: the sampled observer keeps the degradation watch
        # alive — a kernel winner gone >2x slower than its recorded best
        # is sent back to explore (same guard as the ring matmul's)
        phys = _with_oom_backoff(
            "reshape", _mk_run(kmode, mid_owned), tile_bytes, fp=fp_k,
            observer=functools.partial(autotune.observe, key, "kernel"),
        )
        memtrack.register_buffer(phys, tag="output", split=0)
    elif arm == "classic":
        phys = _with_oom_backoff(
            "reshape", _mk_run("", mid_owned), tile_bytes, fp=fp
        )

    if so != 0:
        phys = tiled_resplit(phys, gout, 0, so, comm, donate=True,
                             tile_bytes=tile_bytes, exact=exact)
    return phys
