"""Distributed sort along a split axis: block odd-even merge-split.

The reference sorts a split axis with a hand-written sample sort — local
sort, splitter exchange, ragged ``Alltoallv``, local merge
(heat/core/manipulations.py:2261-3047).  Ragged exchanges don't exist on
TPU: XLA collectives are static-shape.  The TPU-native redesign is a
*block odd-even transposition sort*: every shard keeps a fixed-size block,
each round partners exchange whole blocks over ICI (``ppermute``) and run a
merge-split (left partner keeps the lower half, right the upper).  After
``n_shards`` rounds the blocks are globally ordered — a classic result for
merge-split networks (Knuth TAOCP 5.3.4) — with

- static shapes end to end (the padded physical layout *is* the block),
- peak per-device memory of two blocks (the global array never lands in
  one place — the reference's reason for sample sort, kept),
- only ``collective_permute`` on the wire: no all-gather of the data axis.

Correctness detail: each merge orders by the **total** key
``(pad, value, original index)``.  Totality is load-bearing, not a
stylistic choice — the partners concatenate in opposite orders
``(mine, theirs)``, so a mere ``(pad, value)`` key would let them disagree
on tie order and the kept lower/upper halves could double-count one
partner's duplicates while dropping the other's.  The index tiebreak makes
both partners compute the same merged sequence, and as a bonus the sort is
stable and its result independent of the mesh size.

Pads sink to the global tail (their key class orders last), which is
exactly the canonical physical layout of a split DNDarray, and NaNs keep
NumPy's "sorted last among valid" position without sentinel arithmetic.

``payloads`` ride along with the keys: each merge round moves payload
blocks with the same ``ppermute`` and reorders them with the same argsort.
*Aligned* payloads (same shape as the keys) work for any key rank — the
descending float sort rides its untransformed values this way; *row*
payloads (extra trailing dims, 1-D keys only) are the sharded Fisher–Yates
replacement (sort-by-random-key) behind ``randperm``/``permutation`` and
the epoch shuffle (reference: random.py:649, utils/data/datatools.py:246).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import shard_map_unchecked

__all__ = ["distributed_sort", "distributed_topk"]


def _apply_order(order, arrs, axis):
    """Gather every array by ``order`` along ``axis``; payloads with extra
    trailing dims (1-D keys only) use a plain take on axis 0."""
    key_ndim = order.ndim
    out = []
    for a in arrs:
        if a.ndim == key_ndim:
            out.append(jnp.take_along_axis(a, order, axis=axis))
        else:
            out.append(jnp.take(a, order, axis=0))
    return out


def _total_sort(arrs, axis, *, index_presorted=False):
    """Stable-sort ``arrs = [vals, idxs, pad, *payloads]`` by the total key
    ``(pad, value, index)`` via three stable argsort passes (least
    significant first)."""
    if not index_presorted:
        order = jnp.argsort(arrs[1], axis=axis, stable=True)
        arrs = _apply_order(order, arrs, axis)
    order = jnp.argsort(arrs[0], axis=axis, stable=True)
    arrs = _apply_order(order, arrs, axis)
    order = jnp.argsort(arrs[2], axis=axis, stable=True)
    return _apply_order(order, arrs, axis)


def _build_sorter(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims=()):
    """Build the shard_map'd odd-even merge-split sorter (jitted once per
    (mesh, axis, shape-class) through the lru cache below).

    Payloads come in two layouts: *aligned* payloads share the key's shape
    and sharding and are permuted with ``take_along_axis`` (e.g. original
    float values riding a transformed sort key); *row* payloads (1-D keys
    only) are axis-0-sharded row blocks moved with a plain ``take``."""
    nshards = mesh.shape[axis_name]
    spec_list = [None] * ndim
    spec_list[axis] = axis_name
    key_spec = P(*spec_list)
    payload_specs = tuple(
        key_spec if pnd == ndim else P(axis_name) for pnd in payload_ndims
    )

    def local(phys_vals, *payloads):
        r = lax.axis_index(axis_name)
        shape = phys_vals.shape
        axis_shape = tuple(per if d == axis else 1 for d in range(ndim))
        # global position along the sort axis of each local element
        pos = r * per + jnp.arange(per)
        pad = jnp.broadcast_to((pos >= n_valid).reshape(axis_shape), shape)
        idxs = jnp.broadcast_to(pos.reshape(axis_shape), shape).astype(jnp.int32)

        arrs = _total_sort(
            [phys_vals, idxs, pad, *payloads], axis, index_presorted=True
        )

        for round_ in range(nshards):
            parity = round_ % 2
            # partner pairs: even rounds (0,1)(2,3)…, odd rounds (1,2)(3,4)…
            perm = []
            for left in range(parity, nshards - 1, 2):
                perm.append((left, left + 1))
                perm.append((left + 1, left))
            if not perm:
                continue
            others = [lax.ppermute(a, axis_name, perm) for a in arrs]
            has_partner = jnp.zeros((), bool)
            is_left = jnp.zeros((), bool)
            for s, d in perm:
                has_partner = has_partner | (r == s)
                if s < d:
                    is_left = is_left | (r == s)
            merged = _total_sort(
                [
                    jnp.concatenate((a, o), axis=axis if a.ndim == ndim else 0)
                    for a, o in zip(arrs, others)
                ],
                axis,
            )
            lo_hi = []
            for m in merged:
                ax = axis if m.ndim == ndim else 0
                sel_lo = [slice(None)] * m.ndim
                sel_hi = [slice(None)] * m.ndim
                sel_lo[ax] = slice(0, per)
                sel_hi[ax] = slice(per, 2 * per)
                lo_hi.append(
                    jnp.where(is_left, m[tuple(sel_lo)], m[tuple(sel_hi)])
                )
            arrs = [
                jnp.where(has_partner, m, a) for m, a in zip(lo_hi, arrs)
            ]
        vals, idxs, _ = arrs[0], arrs[1], arrs[2]
        return (vals, idxs, *arrs[3:])

    in_specs = (key_spec,) + payload_specs
    out_specs = (key_spec, key_spec) + payload_specs
    return shard_map_unchecked(local, mesh, in_specs=in_specs, out_specs=out_specs)


@lru_cache(maxsize=None)
def _jit_sorter(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims):
    return jax.jit(
        _build_sorter(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims)
    )


def _build_topk(mesh, axis_name, axis, ndim, n_valid, per, k, largest):
    """Shard_map'd distributed top-k: local top-k per shard (any global
    winner is in its own shard's local top-k), then one all-gather of the
    tiny (nshards * min(k, per)) candidate pool — never the data axis
    (the reference's mpi_topk combiner tree, manipulations.py:3981,
    restated as a single small collective)."""
    k_local = min(k, per)
    in_spec_list = [None] * ndim
    in_spec_list[axis] = axis_name
    in_spec = P(*in_spec_list)

    def local(block):
        r = lax.axis_index(axis_name)
        vals = jnp.moveaxis(block, axis, -1)
        dtype = vals.dtype
        if jnp.issubdtype(dtype, jnp.floating):
            worst = jnp.array(-jnp.inf if largest else jnp.inf, dtype)
        elif dtype == jnp.bool_:
            worst = jnp.array(not largest, dtype)
        else:
            info = jnp.iinfo(dtype)
            worst = jnp.array(info.min if largest else info.max, dtype)
        pos = r * per + jnp.arange(per)
        vals = jnp.where(pos >= n_valid, worst, vals)
        # monotone transform for "smallest": negate floats, bitwise-NOT
        # ints/bools (~x = -x-1 — bijective, no INT_MIN overflow)
        if largest:
            tf = lambda a: a  # noqa: E731
        elif jnp.issubdtype(dtype, jnp.floating):
            tf = lambda a: -a  # noqa: E731
        else:
            tf = jnp.invert
        v, i = lax.top_k(tf(vals), k_local)
        v = tf(v)
        gi = (i + r * per).astype(jnp.int32)
        cand_v = lax.all_gather(v, axis_name, axis=v.ndim - 1, tiled=True)
        cand_i = lax.all_gather(gi, axis_name, axis=gi.ndim - 1, tiled=True)
        out_v, sel = lax.top_k(tf(cand_v), k)
        out_v = tf(out_v)
        out_i = jnp.take_along_axis(cand_i, sel, axis=-1)
        return jnp.moveaxis(out_v, -1, axis), jnp.moveaxis(out_i, -1, axis)

    return shard_map_unchecked(
        local, mesh, in_specs=(in_spec,), out_specs=(P(), P())
    )


@lru_cache(maxsize=None)
def _jit_topk(mesh, axis_name, axis, ndim, n_valid, per, k, largest):
    return jax.jit(
        _build_topk(mesh, axis_name, axis, ndim, n_valid, per, k, largest)
    )


def distributed_topk(
    phys_vals: jax.Array, mesh, axis_name: str, axis: int, n_valid: int,
    k: int, largest: bool = True,
):
    """Top-k along a split ``axis`` without gathering it: returns
    replicated ``(values, global indices)`` with the k-extent at ``axis``.
    ``phys_vals`` must carry the canonical even-chunk physical layout."""
    per = phys_vals.shape[axis] // mesh.shape[axis_name]
    fn = _jit_topk(
        mesh, axis_name, axis, phys_vals.ndim, int(n_valid), per, int(k),
        bool(largest),
    )
    return fn(phys_vals)


def distributed_sort(
    phys_vals: jax.Array, mesh, axis_name: str, axis: int, n_valid: int, payloads=()
):
    """Sort a physically even-sharded array along its split ``axis``.

    ``phys_vals`` must carry the canonical even-chunk physical layout
    (split dim a multiple of the mesh axis size; tail beyond ``n_valid``
    is pad).  Returns ``(values, indices, *payloads)`` in the same physical
    layout: logical elements globally ascending (stable on ties) with pads
    at the global tail, ``indices`` the original global positions along
    ``axis`` (int32), and every payload reordered by the same permutation.
    Aligned payloads (``payload.ndim == phys_vals.ndim``, same shape and
    sharding as the keys) work for any key rank; row payloads (extra
    trailing dims, axis-0 sharded) require 1-D keys.
    """
    per = phys_vals.shape[axis] // mesh.shape[axis_name]
    payload_ndims = tuple(p.ndim for p in payloads)
    if any(pnd != phys_vals.ndim for pnd in payload_ndims) and phys_vals.ndim != 1:
        raise ValueError("row payloads require 1-D sort keys")
    fn = _jit_sorter(
        mesh, axis_name, axis, phys_vals.ndim, int(n_valid), per, payload_ndims
    )
    return fn(phys_vals, *payloads)
