"""Distributed sort along a split axis: columnsort at scale, block
odd-even merge-split on small meshes.

The reference sorts a split axis with a hand-written sample sort — local
sort, splitter exchange, ragged ``Alltoallv``, local merge
(heat/core/manipulations.py:2261-3047).  Ragged exchanges don't exist on
TPU: XLA collectives are static-shape.  Two TPU-native redesigns, chosen
by mesh size:

**Columnsort** (Leighton 1985) for ``nshards >= 6`` — the pod-scale path.
Each shard's block is one column of an ``r x s`` matrix.  Five
data-oblivious steps sort it: local sort, transpose-deal (ONE static
``all_to_all`` — the permutation is an involution, so the untranspose is
the *same* collective), local sort, the same all_to_all again, local
sort; after these, every element is provably within half a column of its
final position (requires ``r >= 2(s-1)^2``, checked at dispatch), so
three adjacent merge-split rounds finish the job.  Total wire traffic is
~6 block-volumes regardless of mesh size — O(n), matching the sample
sort's "move the data about once" property with zero dynamic shapes —
where the odd-even network moves O(n * nshards).

**Block odd-even transposition sort** for small meshes (and as the
fallback when the input is too small for columnsort's r-bound): every
shard keeps a fixed-size block, each round partners exchange whole blocks
over ICI (``ppermute``) and run a merge-split (left partner keeps the
lower half, right the upper).  After ``n_shards`` rounds the blocks are
globally ordered (Knuth TAOCP 5.3.4).

Both paths share the properties that matter:

- static shapes end to end (the padded physical layout *is* the block),
- peak per-device memory of a few blocks (the global array never lands in
  one place — the reference's reason for sample sort, kept),
- only static collectives on the wire: no all-gather of the data axis.

Correctness detail: each merge orders by the **total** key
``(pad, value, original index)``.  Totality is load-bearing, not a
stylistic choice — the partners concatenate in opposite orders
``(mine, theirs)``, so a mere ``(pad, value)`` key would let them disagree
on tie order and the kept lower/upper halves could double-count one
partner's duplicates while dropping the other's.  The index tiebreak makes
both partners compute the same merged sequence, and as a bonus the sort is
stable and its result independent of the mesh size.

Pads sink to the global tail (their key class orders last), which is
exactly the canonical physical layout of a split DNDarray, and NaNs keep
NumPy's "sorted last among valid" position without sentinel arithmetic.

``payloads`` ride along with the keys: each merge round moves payload
blocks with the same ``ppermute`` and reorders them with the same argsort.
*Aligned* payloads (same shape as the keys) work for any key rank — the
descending float sort rides its untransformed values this way; *row*
payloads (extra trailing dims, 1-D keys only) are the sharded Fisher–Yates
replacement (sort-by-random-key) behind ``randperm``/``permutation`` and
the epoch shuffle (reference: random.py:649, utils/data/datatools.py:246).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import axis_size, shard_map_unchecked

__all__ = ["distributed_sort", "distributed_topk", "unique_compact_sorted"]


def _apply_order(order, arrs, axis):
    """Gather every array by ``order`` along ``axis``; payloads with extra
    trailing dims (1-D keys only) use a plain take on axis 0."""
    key_ndim = order.ndim
    out = []
    for a in arrs:
        if a.ndim == key_ndim:
            out.append(jnp.take_along_axis(a, order, axis=axis))
        else:
            out.append(jnp.take(a, order, axis=0))
    return out


def _total_sort(arrs, axis, *, index_presorted=False):
    """Stable-sort ``arrs = [vals, idxs, pad, *payloads]`` by the total key
    ``(pad, value, index)`` via three stable argsort passes (least
    significant first)."""
    if not index_presorted:
        order = jnp.argsort(arrs[1], axis=axis, stable=True)
        arrs = _apply_order(order, arrs, axis)
    order = jnp.argsort(arrs[0], axis=axis, stable=True)
    arrs = _apply_order(order, arrs, axis)
    order = jnp.argsort(arrs[2], axis=axis, stable=True)
    return _apply_order(order, arrs, axis)


def _merge_split_round(arrs, axis, ndim, r, per, nshards, parity, axis_name):
    """One odd-even round: adjacent pairs ((0,1)(2,3)… when ``parity`` is
    even, (1,2)(3,4)… when odd) exchange whole blocks over ICI and run a
    merge-split — the left partner keeps the lower ``per`` of the merged
    2*per block, the right the upper.  Shards without a partner this round
    pass through unchanged."""
    perm = []
    for left in range(parity, nshards - 1, 2):
        perm.append((left, left + 1))
        perm.append((left + 1, left))
    if not perm:
        return arrs
    others = [lax.ppermute(a, axis_name, perm) for a in arrs]
    has_partner = jnp.zeros((), bool)
    is_left = jnp.zeros((), bool)
    for s, d in perm:
        has_partner = has_partner | (r == s)
        if s < d:
            is_left = is_left | (r == s)
    merged = _total_sort(
        [
            jnp.concatenate((a, o), axis=axis if a.ndim == ndim else 0)
            for a, o in zip(arrs, others)
        ],
        axis,
    )
    lo_hi = []
    for m in merged:
        ax = axis if m.ndim == ndim else 0
        sel_lo = [slice(None)] * m.ndim
        sel_hi = [slice(None)] * m.ndim
        sel_lo[ax] = slice(0, per)
        sel_hi[ax] = slice(per, 2 * per)
        lo_hi.append(jnp.where(is_left, m[tuple(sel_lo)], m[tuple(sel_hi)]))
    return [jnp.where(has_partner, m, a) for m, a in zip(lo_hi, arrs)]


def _build_sorter(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims=()):
    """Build the shard_map'd odd-even merge-split sorter (jitted once per
    (mesh, axis, shape-class) through the lru cache below).

    Payloads come in two layouts: *aligned* payloads share the key's shape
    and sharding and are permuted with ``take_along_axis`` (e.g. original
    float values riding a transformed sort key); *row* payloads (1-D keys
    only) are axis-0-sharded row blocks moved with a plain ``take``."""
    nshards = mesh.shape[axis_name]
    spec_list = [None] * ndim
    spec_list[axis] = axis_name
    key_spec = P(*spec_list)
    payload_specs = tuple(
        key_spec if pnd == ndim else P(axis_name) for pnd in payload_ndims
    )

    def local(phys_vals, *payloads):
        r = lax.axis_index(axis_name)
        shape = phys_vals.shape
        axis_shape = tuple(per if d == axis else 1 for d in range(ndim))
        # global position along the sort axis of each local element
        pos = r * per + jnp.arange(per)
        pad = jnp.broadcast_to((pos >= n_valid).reshape(axis_shape), shape)
        idxs = jnp.broadcast_to(pos.reshape(axis_shape), shape).astype(jnp.int32)

        arrs = _total_sort(
            [phys_vals, idxs, pad, *payloads], axis, index_presorted=True
        )

        for round_ in range(nshards):
            arrs = _merge_split_round(
                arrs, axis, ndim, r, per, nshards, round_ % 2, axis_name
            )
        vals, idxs, _ = arrs[0], arrs[1], arrs[2]
        return (vals, idxs, *arrs[3:])

    in_specs = (key_spec,) + payload_specs
    out_specs = (key_spec, key_spec) + payload_specs
    return shard_map_unchecked(local, mesh, in_specs=in_specs, out_specs=out_specs)


@lru_cache(maxsize=None)
def _jit_sorter(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims):
    return jax.jit(
        _build_sorter(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims)
    )


def columnsort_applicable(nshards: int, per: int) -> bool:
    """Leighton's r-bound: a column of ``r`` rows over ``s`` columns is
    sortable by the 5-step schedule iff ``r >= 2(s-1)^2`` (r here is the
    block padded up to a multiple of s for the transpose-deal).  Below 6
    shards the odd-even network needs <= 5 rounds anyway, so columnsort's
    fixed ~6-block-volume cost wouldn't pay."""
    per_pad = -(-per // nshards) * nshards
    return nshards >= 6 and per_pad >= 2 * (nshards - 1) ** 2


def _build_columnsort(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims=()):
    """Build the shard_map'd columnsort (see the module docstring).

    The sort axis is normalized to axis 0 inside the kernel: keys and
    aligned payloads are ``moveaxis``-ed so every step (local total sort,
    the transpose-deal all_to_all, merge-split cleanup, compaction) is an
    axis-0 operation for every carried array, row payloads included.
    """
    nshards = mesh.shape[axis_name]
    b_sub = -(-per // nshards)          # ceil: rows per transpose sub-block
    per_pad = b_sub * nshards           # column height r (divisible by s)
    extra = per_pad - per
    n_total = per * nshards             # size of the physical layout
    spec_list = [None] * ndim
    spec_list[axis] = axis_name
    key_spec = P(*spec_list)
    payload_specs = tuple(
        key_spec if pnd == ndim else P(axis_name) for pnd in payload_ndims
    )

    def local(phys_vals, *payloads):
        r = lax.axis_index(axis_name)
        x = jnp.moveaxis(phys_vals, axis, 0)
        pls = [
            jnp.moveaxis(p, axis, 0) if p.ndim == ndim else p for p in payloads
        ]
        lead = (per,) + (1,) * (x.ndim - 1)
        pos = r * per + jnp.arange(per)
        pad = jnp.broadcast_to((pos >= n_valid).reshape(lead), x.shape)
        idxs = jnp.broadcast_to(pos.reshape(lead), x.shape).astype(jnp.int32)
        arrs = [x, idxs, pad, *pls]

        if extra:
            # pad the column up to r = per_pad: extension rows carry the
            # pad flag (they sort to the global tail) and unique indices
            # beyond every real position (deterministic tie order)
            epos = (n_total + r * extra + jnp.arange(extra)).astype(jnp.int32)
            elead = (extra,) + (1,) * (x.ndim - 1)

            def extend(a, fill_rows):
                return jnp.concatenate((a, fill_rows), axis=0)

            arrs = [
                extend(x, jnp.zeros((extra,) + x.shape[1:], x.dtype)),
                extend(
                    idxs,
                    jnp.broadcast_to(
                        epos.reshape(elead), (extra,) + x.shape[1:]
                    ),
                ),
                extend(pad, jnp.ones((extra,) + pad.shape[1:], bool)),
                *[
                    extend(p, jnp.zeros((extra,) + p.shape[1:], p.dtype))
                    for p in pls
                ],
            ]

        # Leighton's transpose is a round-robin deal: element i of column
        # j goes to column (i mod s), landing at row j*b + i//s.  The
        # cyclic subsequence destined for shard c is made contiguous by a
        # local (b, s) reshape + swap, so ONE static tiled all_to_all
        # ships it; the untranspose is the inverse — the same all_to_all
        # followed by the mirrored local permute.
        def deal(a):
            rest = a.shape[1:]
            y = jnp.swapaxes(a.reshape((b_sub, nshards) + rest), 0, 1)
            y = y.reshape((per_pad,) + rest)
            return lax.all_to_all(
                y, axis_name, split_axis=0, concat_axis=0, tiled=True
            )

        def undeal(a):
            rest = a.shape[1:]
            z = lax.all_to_all(
                a, axis_name, split_axis=0, concat_axis=0, tiled=True
            )
            z = jnp.swapaxes(z.reshape((nshards, b_sub) + rest), 0, 1)
            return z.reshape((per_pad,) + rest)

        # steps 1-5: sort, transpose, sort, untranspose, sort
        arrs = _total_sort(arrs, 0, index_presorted=True)
        arrs = [deal(a) for a in arrs]
        arrs = _total_sort(arrs, 0)
        arrs = [undeal(a) for a in arrs]
        arrs = _total_sort(arrs, 0)

        # steps 6-8: every element is now within r/2 of its final position
        # (Leighton's bound under r >= 2(s-1)^2), i.e. within one column of
        # home and only dirty across a single boundary — adjacent
        # merge-split rounds (even, odd + one spare even) finish the sort
        # without the shift's conceptual extra column
        for parity in (0, 1, 0):
            arrs = _merge_split_round(
                arrs, 0, arrs[0].ndim, r, per_pad, nshards, parity, axis_name
            )

        if extra:
            # compact the per_pad layout back to the canonical per layout:
            # output shard q needs sorted positions [q*per, (q+1)*per),
            # which lie in source shards {q-1, q} (per_pad - per < s and
            # per_pad >= 2(s-1)^2 >= s^2 bound the drift to one shard), so
            # one neighbor ppermute + a static-length slice suffice
            ring = [(i, (i + 1) % nshards) for i in range(nshards)]
            prevs = [lax.ppermute(a, axis_name, ring) for a in arrs]
            start = r * per - (r - 1) * per_pad
            arrs = [
                lax.dynamic_slice_in_dim(
                    jnp.concatenate((pv, a), axis=0), start, per, axis=0
                )
                for pv, a in zip(prevs, arrs)
            ]

        vals = jnp.moveaxis(arrs[0], 0, axis)
        idxs_out = jnp.moveaxis(arrs[1], 0, axis)
        outs = [
            jnp.moveaxis(a, 0, axis) if pnd == ndim else a
            for a, pnd in zip(arrs[3:], payload_ndims)
        ]
        return (vals, idxs_out, *outs)

    in_specs = (key_spec,) + payload_specs
    out_specs = (key_spec, key_spec) + payload_specs
    return shard_map_unchecked(local, mesh, in_specs=in_specs, out_specs=out_specs)


@lru_cache(maxsize=None)
def _jit_columnsort(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims):
    return jax.jit(
        _build_columnsort(mesh, axis_name, axis, ndim, n_valid, per, payload_ndims)
    )


def _build_topk(mesh, axis_name, axis, ndim, n_valid, per, k, largest):
    """Shard_map'd distributed top-k: local top-k per shard (any global
    winner is in its own shard's local top-k), then one all-gather of the
    tiny (nshards * min(k, per)) candidate pool — never the data axis
    (the reference's mpi_topk combiner tree, manipulations.py:3981,
    restated as a single small collective)."""
    k_local = min(k, per)
    in_spec_list = [None] * ndim
    in_spec_list[axis] = axis_name
    in_spec = P(*in_spec_list)

    def local(block):
        r = lax.axis_index(axis_name)
        vals = jnp.moveaxis(block, axis, -1)
        dtype = vals.dtype
        if jnp.issubdtype(dtype, jnp.floating):
            worst = jnp.array(-jnp.inf if largest else jnp.inf, dtype)
        elif dtype == jnp.bool_:
            worst = jnp.array(not largest, dtype)
        else:
            info = jnp.iinfo(dtype)
            worst = jnp.array(info.min if largest else info.max, dtype)
        pos = r * per + jnp.arange(per)
        vals = jnp.where(pos >= n_valid, worst, vals)
        # monotone transform for "smallest": negate floats, bitwise-NOT
        # ints/bools (~x = -x-1 — bijective, no INT_MIN overflow)
        if largest:
            tf = lambda a: a  # noqa: E731
        elif jnp.issubdtype(dtype, jnp.floating):
            tf = lambda a: -a  # noqa: E731
        else:
            tf = jnp.invert
        v, i = lax.top_k(tf(vals), k_local)
        v = tf(v)
        gi = (i + r * per).astype(jnp.int32)
        cand_v = lax.all_gather(v, axis_name, axis=v.ndim - 1, tiled=True)
        cand_i = lax.all_gather(gi, axis_name, axis=gi.ndim - 1, tiled=True)
        out_v, sel = lax.top_k(tf(cand_v), k)
        out_v = tf(out_v)
        out_i = jnp.take_along_axis(cand_i, sel, axis=-1)
        return jnp.moveaxis(out_v, -1, axis), jnp.moveaxis(out_i, -1, axis)

    return shard_map_unchecked(
        local, mesh, in_specs=(in_spec,), out_specs=(P(), P())
    )


@lru_cache(maxsize=None)
def _jit_topk(mesh, axis_name, axis, ndim, n_valid, per, k, largest):
    return jax.jit(
        _build_topk(mesh, axis_name, axis, ndim, n_valid, per, k, largest)
    )


def distributed_topk(
    phys_vals: jax.Array, mesh, axis_name: str, axis: int, n_valid: int,
    k: int, largest: bool = True,
):
    """Top-k along a split ``axis`` without gathering it: returns
    replicated ``(values, global indices)`` with the k-extent at ``axis``.
    ``phys_vals`` must carry the canonical even-chunk physical layout."""
    per = phys_vals.shape[axis] // mesh.shape[axis_name]
    fn = _jit_topk(
        mesh, axis_name, axis, phys_vals.ndim, int(n_valid), per, int(k),
        bool(largest),
    )
    return fn(phys_vals)


def distributed_sort(
    phys_vals: jax.Array, mesh, axis_name: str, axis: int, n_valid: int,
    payloads=(), method: str = "auto",
):
    """Sort a physically even-sharded array along its split ``axis``.

    ``phys_vals`` must carry the canonical even-chunk physical layout
    (split dim a multiple of the mesh axis size; tail beyond ``n_valid``
    is pad).  Returns ``(values, indices, *payloads)`` in the same physical
    layout: logical elements globally ascending (stable on ties) with pads
    at the global tail, ``indices`` the original global positions along
    ``axis`` (int32), and every payload reordered by the same permutation.
    Aligned payloads (``payload.ndim == phys_vals.ndim``, same shape and
    sharding as the keys) work for any key rank; row payloads (extra
    trailing dims, axis-0 sharded) require 1-D keys.

    ``method``: "auto" uses columnsort (O(n) wire traffic) when the mesh
    is large enough and the block satisfies Leighton's r-bound, the
    odd-even network otherwise; "columnsort"/"network" force a path (the
    total key makes both produce the identical permutation).
    """
    nshards = mesh.shape[axis_name]
    per = phys_vals.shape[axis] // nshards
    payload_ndims = tuple(p.ndim for p in payloads)
    if any(pnd != phys_vals.ndim for pnd in payload_ndims) and phys_vals.ndim != 1:
        raise ValueError("row payloads require 1-D sort keys")
    if method == "auto":
        method = "columnsort" if columnsort_applicable(nshards, per) else "network"
    if method == "columnsort":
        per_pad = -(-per // nshards) * nshards
        if per_pad < 2 * (nshards - 1) ** 2:
            raise ValueError(
                f"columnsort needs a padded block of >= 2(s-1)^2 = "
                f"{2 * (nshards - 1) ** 2} rows per shard, got {per_pad}; "
                "use method='network'"
            )
        fn = _jit_columnsort(
            mesh, axis_name, axis, phys_vals.ndim, int(n_valid), per,
            payload_ndims,
        )
    elif method == "network":
        fn = _jit_sorter(
            mesh, axis_name, axis, phys_vals.ndim, int(n_valid), per,
            payload_ndims,
        )
    else:
        raise ValueError(f"unknown sort method {method!r}")
    return fn(phys_vals, *payloads)


def _build_unique_compact(mesh, axis_name, n_valid, per):
    """Per-shard dedup + compaction of a SORTED split axis, on device
    (round 3; the previous host loop pulled every sorted slab to numpy —
    O(n) tunnel traffic per call).  Each shard receives its left
    neighbor's last element with one ppermute, keeps elements that differ
    from their predecessor (NaNs compare EQUAL here: numpy's unique
    collapses them, equal_nan=True), and compacts survivors to its slab
    front.  The host then reads the tiny per-shard counts and transfers
    exactly the uniques."""

    def local(vals):
        r = lax.axis_index(axis_name)
        nshards = axis_size(axis_name)
        pos = r * per + jnp.arange(per)
        validm = pos < n_valid
        ring = [(i, (i + 1) % nshards) for i in range(nshards)]
        prev_last = lax.ppermute(vals[-1:], axis_name, ring)
        prev = jnp.concatenate([prev_last, vals[:-1]])
        same = vals == prev
        if jnp.issubdtype(vals.dtype, jnp.floating):
            # numpy's unique collapses NaNs (equal_nan=True default)
            same = same | (jnp.isnan(vals) & jnp.isnan(prev))
        keep = validm & (~same | (pos == 0))
        order = jnp.argsort(~keep, stable=True)
        cvals = jnp.take(vals, order)
        return cvals, keep.sum(dtype=jnp.int32)[None]

    return shard_map_unchecked(
        local, mesh, in_specs=(P(axis_name),),
        out_specs=(P(axis_name), P(axis_name)),
    )


@lru_cache(maxsize=None)
def _jit_unique_compact(mesh, axis_name, n_valid, per):
    return jax.jit(_build_unique_compact(mesh, axis_name, n_valid, per))


def unique_compact_sorted(phys_sorted: jax.Array, mesh, axis_name: str, n_valid: int):
    """On-device dedup of a sorted physical 1-D split axis: returns
    ``(compacted_slabs, counts)`` — shard r's uniques are
    ``compacted_slabs[r*per : r*per + counts[r]]``."""
    per = phys_sorted.shape[0] // mesh.shape[axis_name]
    fn = _jit_unique_compact(mesh, axis_name, int(n_valid), per)
    return fn(phys_sorted)
