"""Expert parallelism: mixture-of-experts FFN with all-to-all dispatch.

No reference counterpart — the reference has no MoE and its parallelism
checklist marks expert parallelism absent (SURVEY.md §2.5).  This module
supplies the capability TPU-first, completing the parallelism matrix
(dp / tp / pp / sp / ep) alongside :mod:`heat_tpu.parallel.pipeline` and
:mod:`heat_tpu.parallel.sequence`:

* tokens stay sharded along the ``ep`` mesh axis (the data axis);
* expert weights are sharded along the same axis (``E // N`` experts
  resident per device);
* dispatch is the GShard/Switch schedule: top-k routing with a static
  per-expert capacity, one ``all_to_all`` to move token slabs to their
  experts' devices, the expert FFN as one batched einsum over the local
  experts, and the inverse ``all_to_all`` + weighted combine back.
  Token→slot movement is a scatter-add / gather pair — O(tokens·k·d)
  HBM traffic — not GShard's dense one-hot dispatch einsum, whose
  O(tokens·experts·capacity·d) FLOPs dwarf the expert GEMMs themselves
  at transformer sizes (measured 4.5x slower end-to-end on one v5e;
  docs/PERFORMANCE.md).

Everything is shape-static so the whole step jits into a single XLA
program; the two all-to-alls ride ICI.  ``mesh=None`` runs the same
routing on one device; since capacity and drop priority are enforced per
shard, the two paths agree exactly only while nothing is dropped
(``fraction_dropped == 0`` — the regime training aims for).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .collectives import all_to_all, axis_size, psum, shard_map_unchecked

__all__ = ["top_k_routing", "moe_ffn", "expert_capacity"]


def expert_capacity(
    tokens_per_shard: int, num_experts: int, k: int, capacity_factor: float
) -> int:
    """Static per-expert, per-shard token capacity (GShard's rule).

    ``capacity_factor`` > 1 leaves headroom over the perfectly-balanced
    load ``k * tokens / E``; tokens routed past an expert's capacity are
    dropped (their combine weight is zero, so they pass through the
    residual connection unchanged in a transformer block).
    """
    cap = int(math.ceil(capacity_factor * k * tokens_per_shard / num_experts))
    return max(cap, 1)


def _route(gate_logits: jax.Array, k: int, capacity: int):
    """Top-k token→expert assignment with capacity-limited slot positions.

    Returns ``(top_w, top_idx, pos_in_expert, kept, aux)`` — each of the
    first four is (t, k); ``aux`` holds the Switch load-balancing loss and
    the dropped fraction for this shard.

    Position assignment is token-major: when an expert oversubscribes,
    earlier tokens win — the same deterministic priority for any mesh
    size, since routing happens on each shard's local tokens.
    """
    t, num_experts = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_w, top_idx = jax.lax.top_k(probs, k)  # (t, k)
    top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)

    # position of each (token, choice) within its expert's queue; choices
    # are ranked token-major then slot-major so priority is deterministic
    flat_idx = top_idx.reshape(-1)  # (t*k,) in token-major order
    onehot = jax.nn.one_hot(flat_idx, num_experts, dtype=jnp.int32)  # (t*k, E)
    position = jnp.cumsum(onehot, axis=0) * onehot - onehot  # pos within expert
    pos_in_expert = jnp.sum(position, axis=-1).reshape(t, k)  # (t, k)
    kept = pos_in_expert < capacity

    # Switch-style auxiliary load-balancing loss: E * sum_e f_e * p_e where
    # f_e is the fraction of routed choices sent to expert e and p_e the
    # mean router probability of e over the shard's tokens.
    f = jnp.mean(jax.nn.one_hot(top_idx, num_experts, dtype=jnp.float32), axis=(0, 1))
    p = jnp.mean(probs, axis=0)
    aux = {
        "load_balance_loss": num_experts * jnp.sum(f * p),
        "fraction_dropped": 1.0 - jnp.mean(kept.astype(jnp.float32)),
    }
    return top_w, top_idx, pos_in_expert, kept, aux


def top_k_routing(gate_logits: jax.Array, k: int, capacity: int):
    """GShard-style dense routing tensors (reference formulation, kept for
    inspection/debugging; the hot path uses the scatter/gather form).

    Returns ``(dispatch, combine, aux)``: dispatch (t, E, C) one-hot,
    combine = dispatch scaled by the normalized top-k router weight, and
    the aux dict of :func:`_route`.
    """
    num_experts = gate_logits.shape[1]
    top_w, top_idx, pos_in_expert, kept, aux = _route(gate_logits, k, capacity)
    dispatch = (
        jax.nn.one_hot(top_idx, num_experts, dtype=jnp.float32)[..., None]
        * jax.nn.one_hot(jnp.minimum(pos_in_expert, capacity - 1), capacity)[
            :, :, None, :
        ]
        * kept[..., None, None]
    )  # (t, k, E, C)
    combine = jnp.sum(dispatch * top_w[..., None, None], axis=1)  # (t, E, C)
    dispatch = jnp.sum(dispatch, axis=1)  # (t, E, C)
    return dispatch, combine, aux


def _moe_shard(
    x,
    gate_w,
    w_in,
    w_out,
    s_in=None,
    s_out=None,
    *,
    k: int,
    capacity: int,
    activation: Callable,
    axis: Optional[str],
):
    """One shard's MoE FFN. ``x`` (t, d); ``w_in`` (E_local, d, h),
    ``w_out`` (E_local, h, d); ``gate_w`` (d, E_global) replicated.

    ``s_in``/``s_out`` (E_local, h) / (E_local, d) switch the expert
    GEMMs to the quantized form: ``w_in``/``w_out`` are then int8/fp8
    buffers whose upcast to the f32 accumulator dtype fuses into the
    GEMM read (HBM and the all-to-alls never carry the dequantized
    copy), with the per-(expert, out-channel) scales folded in as
    epilogue multiplies."""
    t, d = x.shape
    num_experts = gate_w.shape[1]
    top_w, top_idx, pos_in_expert, kept, aux = _route(x @ gate_w, k, capacity)

    # token→slot scatter: each kept (token, choice) lands in flat slot
    # e*C + pos; dropped choices land in a trash slot that is sliced off.
    # O(t·k·d) HBM traffic vs the dense dispatch einsum's O(t·E·C·d) FLOPs.
    n_slots = num_experts * capacity
    # dropped choices get index n_slots — out of bounds, discarded by
    # mode="drop"; the in-bounds (kept) indices are unique by construction
    # (each expert slot is assigned at most once)
    dest = jnp.where(kept, top_idx * capacity + pos_in_expert, n_slots)  # (t, k)
    src = jnp.broadcast_to(x[:, None, :], (t, k, d)).reshape(t * k, d)
    # (no unique_indices hint: every dropped choice shares the sentinel
    # index, which would violate the uniqueness contract)
    slots = jnp.zeros((n_slots, d), x.dtype).at[dest.reshape(-1)].add(src, mode="drop")
    expert_inputs = slots.reshape(num_experts, capacity, d)
    if axis is not None:
        # exchange slabs so each device holds ALL shards' tokens for its
        # resident experts: (E, C, d) -> (E/N, N*C, d)
        expert_inputs = all_to_all(expert_inputs, axis, split_axis=0, concat_axis=1)

    if s_in is None:
        hidden = activation(jnp.einsum("ecd,edh->ech", expert_inputs, w_in))
        expert_outputs = jnp.einsum("ech,ehd->ecd", hidden, w_out)
    else:
        comp = jnp.promote_types(x.dtype, jnp.float32)
        pre = jnp.einsum(
            "ecd,edh->ech", expert_inputs.astype(comp), w_in.astype(comp)
        )
        hidden = activation(pre * s_in[:, None, :].astype(comp)).astype(x.dtype)
        pre = jnp.einsum("ech,ehd->ecd", hidden.astype(comp), w_out.astype(comp))
        expert_outputs = (pre * s_out[:, None, :].astype(comp)).astype(x.dtype)

    if axis is not None:
        # inverse exchange: (E/N, N*C, d) -> (E, C, d), back token-resident
        expert_outputs = all_to_all(expert_outputs, axis, split_axis=1, concat_axis=0)
        aux = {key: psum(val, axis) / axis_size(axis) for key, val in aux.items()}

    # slot→token gather + weighted combine; the trash row returns zeros
    # for dropped choices (they pass through the residual unchanged)
    out_flat = jnp.concatenate(
        [expert_outputs.reshape(n_slots, d), jnp.zeros((1, d), expert_outputs.dtype)]
    )
    gathered = out_flat[dest.reshape(-1)].reshape(t, k, d)
    y = jnp.sum(gathered * top_w[..., None].astype(gathered.dtype), axis=1)
    return y.astype(x.dtype), aux


def moe_ffn(
    x: jax.Array,
    gate_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    *,
    k: int = 2,
    capacity_factor: float = 2.0,
    activation: Callable = jax.nn.gelu,
    mesh: Optional[Mesh] = None,
    axis: str = "ep",
):
    """Mixture-of-experts feed-forward over an expert-parallel mesh axis.

    Args:
        x: (..., t, d) tokens; leading dims are flattened into the token
            dim for routing. When ``mesh`` is given, the token dim must be
            divisible by the ``axis`` mesh size (tokens sharded over it).
        gate_w: (d, E) router weights (replicated).
        w_in: (E, d, h) expert up-projections (sharded over ``axis``).
        w_out: (E, h, d) expert down-projections (sharded over ``axis``).
        k: experts per token.
        capacity_factor: headroom over perfectly-balanced expert load.
        mesh: expert-parallel mesh; ``None`` = single-device dense path
            (no collectives; matches the sharded path exactly while
            ``fraction_dropped == 0`` — capacity is per shard).
        axis: mesh axis name carrying both tokens and experts.

    Returns:
        (y, aux): y shaped like ``x``; aux holds ``load_balance_loss``
        (add ``alpha * loss`` to the training objective) and
        ``fraction_dropped``.

    ``w_in``/``w_out`` may also be :class:`~heat_tpu.core.quantize
    .QuantizedTensor` pairs (``quantize_tensor(w, axis=(0, 2))`` /
    ``quantize_params``): the expert GEMMs then read the int8/fp8
    buffers directly with the per-(expert, channel) scales folded in,
    dispatched per geometry as ``("bf16", "int8")`` autotune arms with
    the usual explore-returns-reference guarantee.
    """
    from ..core import quantize as _quantize

    q_in = isinstance(w_in, _quantize.QuantizedTensor)
    q_out = isinstance(w_out, _quantize.QuantizedTensor)
    if q_in != q_out:
        raise ValueError(
            "moe_ffn: quantize both w_in and w_out or neither "
            f"(got {type(w_in).__name__} / {type(w_out).__name__})"
        )
    if q_in:
        return _moe_ffn_quantized(
            x, gate_w, w_in, w_out, k=k, capacity_factor=capacity_factor,
            activation=activation, mesh=mesh, axis=axis,
        )
    return _moe_run(
        x, gate_w, w_in, w_out, None, None, k=k,
        capacity_factor=capacity_factor, activation=activation, mesh=mesh,
        axis=axis,
    )


def _moe_run(
    x, gate_w, w_in, w_out, s_in, s_out, *, k, capacity_factor, activation,
    mesh, axis,
):
    """The (possibly quantized) MoE step body behind :func:`moe_ffn`:
    ``s_in``/``s_out`` are None for the master-dtype path, per-(expert,
    channel) scales for the quantized one (they enter the shard program
    as runtime operands — a re-quantized checkpoint never retraces)."""
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    tokens = x2.shape[0]
    num_experts = gate_w.shape[1]
    quantized = s_in is not None

    if mesh is None:
        cap = expert_capacity(tokens, num_experts, k, capacity_factor)
        y, aux = _moe_shard(
            x2, gate_w, w_in, w_out, s_in, s_out, k=k, capacity=cap,
            activation=activation, axis=None,
        )
        return y.reshape(orig_shape), aux

    n = mesh.shape[axis]
    if tokens % n:
        raise ValueError(f"token count {tokens} not divisible by mesh axis {axis}={n}")
    if num_experts % n:
        raise ValueError(f"num_experts {num_experts} not divisible by mesh axis {axis}={n}")
    cap = expert_capacity(tokens // n, num_experts, k, capacity_factor)

    w_spec = NamedSharding(mesh, P(axis, None, None))
    s_spec = NamedSharding(mesh, P(axis, None))
    in_specs = [P(axis, None), P(), P(axis, None, None), P(axis, None, None)]
    operands = [
        jax.device_put(x2, NamedSharding(mesh, P(axis, None))),
        gate_w,
        jax.device_put(w_in, w_spec),
        jax.device_put(w_out, w_spec),
    ]
    if quantized:
        # scales shard with their experts, like the weights they scale
        in_specs += [P(axis, None), P(axis, None)]
        operands += [
            jax.device_put(s_in, s_spec),
            jax.device_put(s_out, s_spec),
        ]
    shard_fn = shard_map_unchecked(
        partial(_moe_shard, k=k, capacity=cap, activation=activation, axis=axis),
        mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(axis, None), P()),
    )
    y, aux = shard_fn(*operands)
    return y.reshape(orig_shape), aux


def _moe_ffn_quantized(
    x, gate_w, qw_in, qw_out, *, k, capacity_factor, activation, mesh, axis,
):
    """Arm-dispatched quantized MoE FFN: bf16 = dequantize both experts'
    weights and run the master-dtype path (the reference arm — bitwise
    the unquantized flow over the same dequantized values); int8 = the
    low-precision buffers ride the expert GEMMs directly."""
    from ..core import quantize as _quantize

    for name, qt in (("w_in", qw_in), ("w_out", qw_out)):
        if qt.axes != (0, 2):
            raise ValueError(
                f"moe_ffn: quantized {name} needs per-(expert, "
                f"out-channel) scales — quantize with axis=(0, 2), got "
                f"axes {qt.axes}"
            )

    def _bf16():
        return _moe_run(
            x, gate_w, _quantize.dequantize_tensor(qw_in),
            _quantize.dequantize_tensor(qw_out), None, None, k=k,
            capacity_factor=capacity_factor, activation=activation,
            mesh=mesh, axis=axis,
        )

    def _int8():
        return _moe_run(
            x, gate_w, qw_in.q, qw_out.q, qw_in.scale, qw_out.scale, k=k,
            capacity_factor=capacity_factor, activation=activation,
            mesh=mesh, axis=axis,
        )

    if _quantize._is_traced(x):
        # inside someone else's trace (grad/training): no timing, no
        # table writes — the reference arm, unconditionally
        return _bf16()
    tokens = 1
    for dim in x.shape[:-1]:
        tokens *= dim
    d = x.shape[-1]
    n = 1 if mesh is None else mesh.shape[axis]
    geometry = (
        tokens, d, qw_in.shape[2], gate_w.shape[1], n, k, str(qw_in.q.dtype),
    )
    return _quantize.tuned_arm(
        "moe_ffn", geometry, _bf16, _int8,
        desc=f"moe_ffn t={tokens} d={d} h={qw_in.shape[2]} "
             f"E={gate_w.shape[1]} S={n}",
    )
