"""Mesh-backed communication context.

TPU-native replacement for the reference's ``MPICommunication``
(heat/core/communication.py:120-1895).  Where the reference wraps ~40 MPI
primitives around torch tensors, here a :class:`MeshComm` wraps a
``jax.sharding.Mesh``:

* the reference's *rank/size* become device positions along the mesh's split
  axis (``heat/core/communication.py:120-160``),
* the reference's ``chunk()`` block-distribution rule
  (``heat/core/communication.py:161-218``) is re-derived for GSPMD's canonical
  even-chunk layout (``ceil(n/N)`` per shard, trailing shards truncated), so
  ``lshape_map`` metadata always matches what XLA actually places on each
  device,
* every explicit collective disappears into XLA — a ``DNDarray`` op under
  ``jit`` with the right ``PartitionSpec`` emits all-reduce / all-gather /
  all-to-all / collective-permute on ICI automatically.

Multi-host initialization (the reference's ``mpirun`` bootstrap,
communication.py:1909-1921) maps to :func:`init_distributed` — call it once
before building a mesh; :func:`hybrid_mesh` then lays DCN-spanning axes over
slices/hosts and ICI axes within a slice.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core import envparse

__all__ = [
    "Communication",
    "MeshComm",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "world",
    "local_mesh",
    "init_distributed",
    "hybrid_mesh",
]

#: canonical name of the mesh axis that backs the DNDarray ``split`` dimension
SPLIT_AXIS = "split"


class Communication:
    """Abstract base for communication contexts (reference: Communication ABC,
    heat/core/communication.py:88-118)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class MeshComm(Communication):
    """A communication context backed by a JAX device mesh.

    Parameters
    ----------
    mesh : jax.sharding.Mesh, optional
        The device mesh. If ``None``, a 1-D mesh over all visible devices is
        created with axis name ``"split"``.
    split_axis : str
        The mesh axis name that DNDarray ``split`` dimensions are sharded over.

    Notes
    -----
    ``nranks``/``rank`` mirror the reference's process semantics
    (communication.py:151-160) but count *devices along the split axis*, since
    on TPU the unit of SPMD parallelism is the chip, not the host process.
    """

    def __init__(self, mesh: Optional[Mesh] = None, split_axis: str = SPLIT_AXIS):
        if mesh is None:
            devices = np.array(jax.devices())
            mesh = Mesh(devices, (split_axis,))
        if split_axis not in mesh.axis_names:
            raise ValueError(
                f"split_axis {split_axis!r} not in mesh axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.split_axis = split_axis

    # ------------------------------------------------------------------ basic
    @property
    def size(self) -> int:
        """Number of devices along the split axis."""
        return int(self.mesh.shape[self.split_axis])

    @property
    def rank(self) -> int:
        """Index of this *process* (multi-host); 0 in single-controller runs."""
        return jax.process_index()

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @staticmethod
    def is_distributed() -> bool:
        return len(jax.devices()) > 1

    def __repr__(self) -> str:
        return f"MeshComm(mesh={self.mesh!r}, split_axis={self.split_axis!r})"

    # ------------------------------------------------------------- partitions
    def chunk(
        self, shape: Tuple[int, ...], split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Compute the (offset, local shape, slices) of one device's shard.

        The reference distributes ``size % nranks`` extra elements to the first
        ranks (communication.py:161-218).  GSPMD instead uses even
        ``ceil(n/N)`` chunks with the trailing shards truncated (possibly to
        zero); we follow the hardware so that metadata matches the actual
        layout of every ``jax.Array``.
        """
        if split is None:
            return 0, tuple(shape), tuple(slice(0, end) for end in shape)
        rank = 0 if rank is None else int(rank)
        nranks = self.size
        dims = len(shape)
        split = split % dims if dims else 0
        size = shape[split]
        per = _ceil_div(size, nranks) if size > 0 else 0
        start = min(rank * per, size)
        end = min((rank + 1) * per, size)
        lshape = list(shape)
        lshape[split] = end - start
        slices = tuple(
            slice(start, end) if i == split else slice(0, shape[i]) for i in range(dims)
        )
        return start, tuple(lshape), slices

    def lshape_map(self, shape: Tuple[int, ...], split: Optional[int]) -> np.ndarray:
        """(size, ndim) matrix of per-device shard shapes (reference:
        DNDarray.create_lshape_map, dndarray.py:598-629)."""
        n = self.size
        out = np.empty((n, max(len(shape), 1)), dtype=np.int64)
        for r in range(n):
            _, lshape, _ = self.chunk(shape, split, rank=r)
            out[r, : len(shape)] = lshape
        if len(shape) == 0:
            out = np.zeros((n, 0), dtype=np.int64)
        return out

    def counts_displs_shape(
        self, shape: Tuple[int, ...], axis: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]:
        """Per-rank counts and displacements along ``axis``
        (reference: communication.py:220-248)."""
        counts, displs = [], []
        for r in range(self.size):
            off, lshape, _ = self.chunk(shape, axis, rank=r)
            counts.append(lshape[axis])
            displs.append(off)
        out_shape = list(shape)
        out_shape[axis] = -1
        return tuple(counts), tuple(displs), tuple(out_shape)

    # -------------------------------------------------------------- shardings
    def spec(self, split: Optional[int], ndim: int) -> PartitionSpec:
        """PartitionSpec placing mesh axis ``split_axis`` at dim ``split``."""
        if split is None or ndim == 0:
            return PartitionSpec()
        split = split % ndim
        parts: List[Optional[str]] = [None] * ndim
        parts[split] = self.split_axis
        return PartitionSpec(*parts)

    def sharding(self, split: Optional[int], ndim: int) -> NamedSharding:
        """NamedSharding for a DNDarray of ``ndim`` dims split at ``split``."""
        return NamedSharding(self.mesh, self.spec(split, ndim))

    def replicated(self, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    # --------------------------------------------------------------- factory
    def _submesh(self, indices) -> "MeshComm":
        """New MeshComm over the given positions along the split axis (other
        mesh axes are preserved)."""
        if not len(indices):
            raise ValueError("sub-communicator needs at least one device")
        axis_pos = self.mesh.axis_names.index(self.split_axis)
        devices = np.take(self.mesh.devices, np.asarray(indices), axis=axis_pos)
        return MeshComm(Mesh(devices, self.mesh.axis_names), split_axis=self.split_axis)

    def Split(self, color: int = 0, key: int = 0) -> "MeshComm":
        """Sub-communicator creation (reference: communication.py:470-481).

        MPI semantics restated for a single controller: the split-axis
        positions are partitioned into color groups, and the result is one
        group's communicator over a sub-mesh of its devices.

        * scalar ``color`` — the common MPI idiom where every member passes
          the same value: returns a fresh communicator over all split-axis
          devices.
        * sequence ``color`` (one entry per split-axis position) — returns
          the group containing position ``key``.  (MPI gives every rank its
          own group; a single controller must name one — ``key`` doubles as
          that perspective.  Use :meth:`split_groups` for all groups at
          once; within a group, device order is preserved.)
        """
        colors = np.asarray(color)
        if colors.ndim == 0:
            return self._submesh(list(range(self.size)))
        if colors.shape != (self.size,):
            raise ValueError(
                f"per-device colors must have shape ({self.size},), got {colors.shape}"
            )
        key = int(key)
        if not 0 <= key < self.size:
            # MPI's key is an intra-group ordering hint; here it selects
            # the perspective position, so a silent modulo wrap would pick
            # an arbitrary group for MPI-ported `key=rank`-style values
            # (advisor round 2).  Reject instead.
            raise ValueError(
                f"key must be a split-axis position in [0, {self.size}), got {key}; "
                "use split_groups() for all groups at once"
            )
        mine = colors[key]
        return self._submesh([i for i in range(self.size) if colors[i] == mine])

    def split_groups(self, colors) -> dict:
        """All color-group sub-communicators at once: ``{color: MeshComm}``
        (the single-controller face of MPI's per-rank ``Split``)."""
        colors = np.asarray(colors)
        if colors.shape != (self.size,):
            raise ValueError(
                f"per-device colors must have shape ({self.size},), got {colors.shape}"
            )
        return {
            c: self._submesh([i for i in range(self.size) if colors[i] == c])
            for c in np.unique(colors).tolist()
        }


# ---------------------------------------------------------------------- world
_world_comm: Optional[MeshComm] = None
_default_comm: Optional[MeshComm] = None


def world() -> MeshComm:
    """The all-device communication context (reference: MPI_WORLD,
    communication.py:1909).  Fixed once created: narrowing the *default*
    context via :func:`use_comm` never changes what ``world()`` returns,
    just as MPI.COMM_WORLD is unaffected by the reference's ``use_comm``."""
    global _world_comm
    if _world_comm is None:
        _world_comm = MeshComm()
    return _world_comm


def get_comm() -> MeshComm:
    """Return the current default context (reference: communication.py:1927).
    Starts as :func:`world`; redirected by :func:`use_comm`."""
    return _default_comm if _default_comm is not None else world()


def use_comm(comm: Optional[MeshComm] = None) -> None:
    """Set the default context (reference: communication.py:1950)."""
    global _default_comm
    if comm is not None and not isinstance(comm, MeshComm):
        raise TypeError(f"comm must be a MeshComm, got {type(comm)}")
    _default_comm = comm


def sanitize_comm(comm: Optional[Communication]) -> MeshComm:
    """Validate-or-default a communication context (reference:
    communication.py:1933-1947)."""
    if comm is None:
        return get_comm()
    if isinstance(comm, MeshComm):
        return comm
    raise TypeError(f"comm must be None or a MeshComm, got {type(comm)}")


def local_mesh(n: Optional[int] = None, axis: str = SPLIT_AXIS) -> MeshComm:
    """Build a MeshComm over the first ``n`` devices (testing helper)."""
    devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return MeshComm(Mesh(np.array(devices), (axis,)), split_axis=axis)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> Tuple[int, int]:
    """Multi-host bootstrap (the reference's ``mpirun`` + import-time
    ``MPI_WORLD`` creation, heat/core/communication.py:1909-1921).

    Wraps ``jax.distributed.initialize`` so user scripts stay launcher
    agnostic:

    * already initialized → no-op;
    * explicit arguments → passed straight through (errors propagate: the
      caller asked for a specific topology and should hear when it fails);
    * no arguments → delegate to JAX's own cluster auto-detection (Slurm,
      Open MPI, GCE TPU metadata, GKE env, ``JAX_COORDINATOR_ADDRESS``);
      when no cluster is detectable — a plain single-process run — this is
      a clean no-op rather than an error.

    Call it before any other JAX usage (backend initialization pins the
    process topology); called later in a single-process program it simply
    no-ops.  Returns ``(process_index, process_count)`` — the reference's
    ``(rank, size)``.
    """
    already = False
    try:
        already = jax.distributed.is_initialized()
    except AttributeError:  # pragma: no cover - older jax
        from jax._src import distributed as _dist

        already = getattr(_dist.global_state, "client", None) is not None
    if not already:
        explicit = (
            coordinator_address is not None
            or num_processes is not None
            or process_id is not None
            or bool(kwargs)
        )
        if explicit:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        else:
            try:
                from jax._src import xla_bridge as _xla_bridge

                backend_up = _xla_bridge.backends_are_initialized()
            except (ImportError, AttributeError):  # pragma: no cover
                backend_up = True  # conservatively skip auto-init
            if not backend_up:
                try:
                    # jax's ClusterEnv chain detects Slurm/MPI/GCE/GKE and
                    # reads JAX_COORDINATOR_ADDRESS itself
                    jax.distributed.initialize()
                except (ValueError, RuntimeError) as exc:
                    # "no cluster detected" is a clean single-process no-op;
                    # a cluster that WAS detected but failed to come up must
                    # fail loudly — silently degrading to N independent
                    # rank-0 jobs corrupts results
                    if _looks_multiprocess():
                        raise RuntimeError(
                            "a multi-process launcher environment was "
                            "detected but jax.distributed.initialize() "
                            f"failed: {exc}"
                        ) from exc
            elif _looks_multiprocess():
                import warnings

                warnings.warn(
                    "init_distributed() was called after the JAX backend was "
                    "initialized; multi-host setup was skipped although a "
                    "multi-process launcher environment is present. Call "
                    "init_distributed() before any other JAX usage.",
                    RuntimeWarning,
                    stacklevel=2,
                )
    return jax.process_index(), jax.process_count()


def _looks_multiprocess() -> bool:
    """Cheap launcher-env sniff: does this look like one process of many?"""

    def _int(name: str) -> int:
        # strict parse (envparse.env_int): a malformed launcher variable
        # must refuse to start, not silently come up single-process
        return envparse.env_int(name, 1)

    tpu_workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return (
        _int("SLURM_NTASKS") > 1
        or _int("OMPI_COMM_WORLD_SIZE") > 1
        or _int("PMI_SIZE") > 1
        or bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))
        or len([w for w in tpu_workers.split(",") if w.strip()]) > 1
    )


def hybrid_mesh(
    ici: dict, dcn: Optional[dict] = None, *, process_is_granule: bool = False
) -> Mesh:
    """Build a DCN × ICI device mesh (the reference's two-tier topology —
    NCCL inside a node, MPI across, heat/optim/dp_optimizer.py:46 — expressed
    as mesh axes).

    Args:
        ici: ordered ``{axis_name: size}`` for axes riding intra-slice ICI
            links (fast: tensor/sequence/expert parallelism belong here).
        dcn: ordered ``{axis_name: size}`` for axes spanning the slow outer
            network (data parallelism, DASO's outer tier). Sizes of 1 are
            allowed and make the result a plain single-slice mesh.
        process_is_granule: what the dcn tier spans. ``False`` (default):
            TPU slices (`slice_index`) — multi-slice pods over DCN.
            ``True``: host processes — e.g. the hosts of one TPU slice, or
            any multi-host cluster whose devices carry no slice topology.

    Returns a ``jax.sharding.Mesh`` with dcn axes leading (slowest-varying),
    so collectives along ici axes never cross a granule boundary.

    >>> mesh = hybrid_mesh({"split": 4}, {"dp": 2})   # 2 slices x 4 chips
    >>> MeshComm(mesh)                                 # split rides ICI
    """
    from jax.experimental import mesh_utils

    dcn = dict(dcn or {})
    ici = dict(ici)
    if not ici:
        raise ValueError("ici must name at least one mesh axis")
    if set(dcn) & set(ici):
        raise ValueError(
            f"axis names must be distinct across tiers: {sorted(set(dcn) & set(ici))}"
        )
    names = tuple(dcn) + tuple(ici)
    dcn_shape = tuple(dcn.values())
    ici_shape = tuple(ici.values())
    n_dcn = int(np.prod(dcn_shape)) if dcn_shape else 1
    if n_dcn > 1:
        # create_hybrid_device_mesh wants rank-aligned shapes: dcn axes are
        # size 1 in the inner (ICI) shape and vice versa
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1,) * len(dcn_shape) + ici_shape,
            dcn_mesh_shape=dcn_shape + (1,) * len(ici_shape),
            process_is_granule=process_is_granule,
        )
        return Mesh(devices, names)
    devices = mesh_utils.create_device_mesh(ici_shape)
    return Mesh(devices.reshape(dcn_shape + ici_shape), names)
