"""Mesh-backed communication context.

TPU-native replacement for the reference's ``MPICommunication``
(heat/core/communication.py:120-1895).  Where the reference wraps ~40 MPI
primitives around torch tensors, here a :class:`MeshComm` wraps a
``jax.sharding.Mesh``:

* the reference's *rank/size* become device positions along the mesh's split
  axis (``heat/core/communication.py:120-160``),
* the reference's ``chunk()`` block-distribution rule
  (``heat/core/communication.py:161-218``) is re-derived for GSPMD's canonical
  even-chunk layout (``ceil(n/N)`` per shard, trailing shards truncated), so
  ``lshape_map`` metadata always matches what XLA actually places on each
  device,
* every explicit collective disappears into XLA — a ``DNDarray`` op under
  ``jit`` with the right ``PartitionSpec`` emits all-reduce / all-gather /
  all-to-all / collective-permute on ICI automatically.

Multi-host initialization (the reference's ``mpirun`` bootstrap,
communication.py:1909-1921) maps to ``jax.distributed.initialize()`` which the
user calls once before building a mesh.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "Communication",
    "MeshComm",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "world",
    "local_mesh",
]

#: canonical name of the mesh axis that backs the DNDarray ``split`` dimension
SPLIT_AXIS = "split"


class Communication:
    """Abstract base for communication contexts (reference: Communication ABC,
    heat/core/communication.py:88-118)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class MeshComm(Communication):
    """A communication context backed by a JAX device mesh.

    Parameters
    ----------
    mesh : jax.sharding.Mesh, optional
        The device mesh. If ``None``, a 1-D mesh over all visible devices is
        created with axis name ``"split"``.
    split_axis : str
        The mesh axis name that DNDarray ``split`` dimensions are sharded over.

    Notes
    -----
    ``nranks``/``rank`` mirror the reference's process semantics
    (communication.py:151-160) but count *devices along the split axis*, since
    on TPU the unit of SPMD parallelism is the chip, not the host process.
    """

    def __init__(self, mesh: Optional[Mesh] = None, split_axis: str = SPLIT_AXIS):
        if mesh is None:
            devices = np.array(jax.devices())
            mesh = Mesh(devices, (split_axis,))
        if split_axis not in mesh.axis_names:
            raise ValueError(
                f"split_axis {split_axis!r} not in mesh axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.split_axis = split_axis

    # ------------------------------------------------------------------ basic
    @property
    def size(self) -> int:
        """Number of devices along the split axis."""
        return int(self.mesh.shape[self.split_axis])

    @property
    def rank(self) -> int:
        """Index of this *process* (multi-host); 0 in single-controller runs."""
        return jax.process_index()

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @staticmethod
    def is_distributed() -> bool:
        return len(jax.devices()) > 1

    def __repr__(self) -> str:
        return f"MeshComm(mesh={self.mesh!r}, split_axis={self.split_axis!r})"

    # ------------------------------------------------------------- partitions
    def chunk(
        self, shape: Tuple[int, ...], split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Compute the (offset, local shape, slices) of one device's shard.

        The reference distributes ``size % nranks`` extra elements to the first
        ranks (communication.py:161-218).  GSPMD instead uses even
        ``ceil(n/N)`` chunks with the trailing shards truncated (possibly to
        zero); we follow the hardware so that metadata matches the actual
        layout of every ``jax.Array``.
        """
        if split is None:
            return 0, tuple(shape), tuple(slice(0, end) for end in shape)
        rank = 0 if rank is None else int(rank)
        nranks = self.size
        dims = len(shape)
        split = split % dims if dims else 0
        size = shape[split]
        per = _ceil_div(size, nranks) if size > 0 else 0
        start = min(rank * per, size)
        end = min((rank + 1) * per, size)
        lshape = list(shape)
        lshape[split] = end - start
        slices = tuple(
            slice(start, end) if i == split else slice(0, shape[i]) for i in range(dims)
        )
        return start, tuple(lshape), slices

    def lshape_map(self, shape: Tuple[int, ...], split: Optional[int]) -> np.ndarray:
        """(size, ndim) matrix of per-device shard shapes (reference:
        DNDarray.create_lshape_map, dndarray.py:598-629)."""
        n = self.size
        out = np.empty((n, max(len(shape), 1)), dtype=np.int64)
        for r in range(n):
            _, lshape, _ = self.chunk(shape, split, rank=r)
            out[r, : len(shape)] = lshape
        if len(shape) == 0:
            out = np.zeros((n, 0), dtype=np.int64)
        return out

    def counts_displs_shape(
        self, shape: Tuple[int, ...], axis: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]:
        """Per-rank counts and displacements along ``axis``
        (reference: communication.py:220-248)."""
        counts, displs = [], []
        for r in range(self.size):
            off, lshape, _ = self.chunk(shape, axis, rank=r)
            counts.append(lshape[axis])
            displs.append(off)
        out_shape = list(shape)
        out_shape[axis] = -1
        return tuple(counts), tuple(displs), tuple(out_shape)

    # -------------------------------------------------------------- shardings
    def spec(self, split: Optional[int], ndim: int) -> PartitionSpec:
        """PartitionSpec placing mesh axis ``split_axis`` at dim ``split``."""
        if split is None or ndim == 0:
            return PartitionSpec()
        split = split % ndim
        parts: List[Optional[str]] = [None] * ndim
        parts[split] = self.split_axis
        return PartitionSpec(*parts)

    def sharding(self, split: Optional[int], ndim: int) -> NamedSharding:
        """NamedSharding for a DNDarray of ``ndim`` dims split at ``split``."""
        return NamedSharding(self.mesh, self.spec(split, ndim))

    def replicated(self, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    # --------------------------------------------------------------- factory
    def Split(self, color: int = 0, key: int = 0) -> "MeshComm":
        """Sub-communicator creation (reference: communication.py:470-481).

        TPU meshes are static; a true sub-mesh requires constructing a new
        ``Mesh`` over a device subset, which we expose via :func:`local_mesh`.
        """
        raise NotImplementedError(
            "sub-communicators: build a new MeshComm over a device subset via local_mesh()"
        )


# ---------------------------------------------------------------------- world
_world_comm: Optional[MeshComm] = None
_default_comm: Optional[MeshComm] = None


def world() -> MeshComm:
    """The all-device communication context (reference: MPI_WORLD,
    communication.py:1909).  Fixed once created: narrowing the *default*
    context via :func:`use_comm` never changes what ``world()`` returns,
    just as MPI.COMM_WORLD is unaffected by the reference's ``use_comm``."""
    global _world_comm
    if _world_comm is None:
        _world_comm = MeshComm()
    return _world_comm


def get_comm() -> MeshComm:
    """Return the current default context (reference: communication.py:1927).
    Starts as :func:`world`; redirected by :func:`use_comm`."""
    return _default_comm if _default_comm is not None else world()


def use_comm(comm: Optional[MeshComm] = None) -> None:
    """Set the default context (reference: communication.py:1950)."""
    global _default_comm
    if comm is not None and not isinstance(comm, MeshComm):
        raise TypeError(f"comm must be a MeshComm, got {type(comm)}")
    _default_comm = comm


def sanitize_comm(comm: Optional[Communication]) -> MeshComm:
    """Validate-or-default a communication context (reference:
    communication.py:1933-1947)."""
    if comm is None:
        return get_comm()
    if isinstance(comm, MeshComm):
        return comm
    raise TypeError(f"comm must be None or a MeshComm, got {type(comm)}")


def local_mesh(n: Optional[int] = None, axis: str = SPLIT_AXIS) -> MeshComm:
    """Build a MeshComm over the first ``n`` devices (testing helper)."""
    devices = jax.devices()
    if n is not None:
        devices = devices[:n]
    return MeshComm(Mesh(np.array(devices), (axis,)), split_axis=axis)
