"""Explicit collectives for schedule-controlled algorithms.

Most of the framework never names a collective: XLA's GSPMD partitioner inserts
them from shardings.  The few algorithms that control their own schedule
(TSQR panel merges, ring pairwise distances, halo-exchange convolution — the
TPU counterparts of the reference's hand-written Send/Recv rings in
heat/core/linalg/qr.py, heat/spatial/distance.py:209 and
heat/core/dndarray.py:383) run under ``jax.shard_map`` and use these
wrappers.

Mapping from the reference's MPI calls (SURVEY.md §2.5):

==================  =========================================
reference (MPI)     here (XLA over ICI/DCN)
==================  =========================================
Allreduce           :func:`psum` / :func:`pmax` / :func:`pmin`
Allgather(v)        :func:`all_gather`
Alltoall(v/w)       :func:`all_to_all`
Send/Recv rings     :func:`ring_shift` (collective-permute)
Bcast               sharding (replicate) or :func:`bcast`
Exscan/Scan         :func:`exscan`
==================  =========================================
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.6 top-level shard_map
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = [
    "shard_map",
    "shard_map_unchecked",
    "jit_shard_map_cached",
    "psum",
    "pmax",
    "pmin",
    "all_gather",
    "all_to_all",
    "ring_shift",
    "bcast",
    "exscan",
    "axis_index",
    "axis_size",
]

shard_map = _shard_map


def shard_map_unchecked(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across jax versions (the
    kwarg is ``check_vma`` on jax>=0.6, ``check_rep`` before)."""
    try:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


@lru_cache(maxsize=None)
def jit_shard_map_cached(builder: Callable, mesh, *key):
    """Build-and-jit a shard_map'd kernel once per ``(builder, mesh, *key)``.

    ``builder(mesh, *key)`` must return the shard_map'd callable.  Rebuilding
    the closure per call would defeat jit's trace cache and recompile the
    kernel on every invocation (~12 s per call through a remote TPU tunnel);
    every hot shard_map site (spatial.cdist, linalg TSQR) routes through
    this cache."""
    return jax.jit(builder(mesh, *key))


def axis_index(axis: str):
    """This shard's position along the mesh axis (reference: comm.rank)."""
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Number of shards along the mesh axis (reference: comm.size)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    # jax < 0.5 has no lax.axis_size; axis_frame returns the size (int on
    # 0.4.x, a frame with .size on some releases)
    frame = jax.core.axis_frame(axis)
    return frame if isinstance(frame, int) else frame.size


def psum(x, axis: str):
    """All-reduce sum (reference: MPICommunication.Allreduce with MPI.SUM,
    heat/core/communication.py:774)."""
    return lax.psum(x, axis_name=axis)


def pmax(x, axis: str):
    return lax.pmax(x, axis_name=axis)


def pmin(x, axis: str):
    return lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str, *, concat_axis: int = 0, tiled: bool = True):
    """All-gather along an array axis (reference: axis-aware Allgather(v),
    heat/core/communication.py:1027-1220).

    With ``tiled=True`` the per-shard blocks are concatenated along
    ``concat_axis`` (matching Allgatherv's flattened layout); otherwise a new
    leading axis indexes the source shard.
    """
    return lax.all_gather(x, axis_name=axis, axis=concat_axis, tiled=tiled)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    """All-to-all redistribution (reference: Alltoall(v/w) with derived
    datatypes for axis permutation, heat/core/communication.py:1222-1492)."""
    return lax.all_to_all(
        x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def ring_shift(x, axis: str, *, shift: int = 1):
    """Pass each shard to the neighbor ``shift`` positions up the ring.

    This is the TPU idiom for every Send/Recv ring in the reference (e.g. the
    moving block in heat/spatial/distance.py:209, redistribute_'s pairwise
    exchanges in dndarray.py:1161-1318): a ``collective_permute`` rides the ICI
    torus links directly.
    """
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def bcast(x, axis: str, *, root: int = 0):
    """Broadcast the ``root`` shard's value to all shards (reference: Bcast,
    communication.py:714-772). Implemented as mask + psum, which XLA lowers to
    an efficient broadcast."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name=axis)


def exscan(x, axis: str, *, op: Callable = jnp.add, neutral=0):
    """Exclusive prefix scan over the mesh axis (reference: Exscan,
    communication.py:925-1025). Gathers the per-shard values (small — one
    scalar/slab per shard) and combines prefixes locally."""
    n = axis_size(axis)
    idx = lax.axis_index(axis)
    gathered = lax.all_gather(x, axis_name=axis, axis=0, tiled=False)  # (n, ...)
    mask = (jnp.arange(n) < idx).reshape((n,) + (1,) * (gathered.ndim - 1))
    neutral_arr = jnp.full_like(gathered, neutral)
    contrib = jnp.where(mask, gathered, neutral_arr)
    out = contrib[0]
    for i in range(1, n):
        out = op(out, contrib[i])
    return out
