"""Device-mesh and sharding layer — the TPU-native replacement for the reference's
MPI communication backend (heat/core/communication.py).

On TPU there is no explicit message-passing backend: a :class:`Communication`
object owns a ``jax.sharding.Mesh`` and a distinguished *split* axis name; all
"collectives" are emitted by XLA from sharded computations (``psum`` /
``all_gather`` / ``all_to_all`` / ``ppermute`` over ICI/DCN).  An explicit
facade of shard_map-level collectives lives in :mod:`heat_tpu.parallel.collectives`
for the algorithms that control their own schedule (TSQR, ring cdist, halo
exchange).
"""

from .mesh import (
    Communication,
    MeshComm,
    get_comm,
    use_comm,
    sanitize_comm,
    world,
    local_mesh,
    init_distributed,
    hybrid_mesh,
)
from . import collectives
from . import overlap
# imported at package load so the "transport" telemetry group is
# registered (and visible in ht.telemetry.snapshot()) before any traffic
from . import transport
from . import pipeline
from .pipeline import pipeline_apply, stack_stage_params
from . import expert
from .expert import moe_ffn

__all__ = [
    "Communication",
    "MeshComm",
    "get_comm",
    "use_comm",
    "sanitize_comm",
    "world",
    "local_mesh",
    "init_distributed",
    "hybrid_mesh",
    "collectives",
    "overlap",
    "transport",
    "pipeline",
    "pipeline_apply",
    "stack_stage_params",
    "expert",
    "moe_ffn",
]
