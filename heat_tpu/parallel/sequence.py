"""Sequence / context parallelism: ring attention and Ulysses all-to-all.

No reference counterpart — Heat has no attention or sequence models at all
(SURVEY.md §5, "long-context: absent"); its closest primitives are the ring
dataflow of ``heat/spatial/distance.py:209`` and the halo exchange of
``heat/core/dndarray.py:383``, which generalize to exactly these patterns.
This module supplies the missing long-context capability TPU-first:

* :func:`ring_attention` — blockwise attention over a sequence-sharded
  mesh axis.  K/V shards rotate around the ring via ``ppermute`` (ICI
  neighbor links) while each device accumulates online-softmax statistics
  for its resident Q shard: memory O(seq/N) per device, compute overlapped
  with the rotation by XLA's scheduler.  Exact — not an approximation.
* :func:`ulysses_attention` — the all-to-all alternative: resharding from
  sequence-sharded to head-sharded (one ``all_to_all``), full-sequence
  attention per local head group, and the inverse reshard.  Cheaper at
  moderate sequence lengths when heads ≥ mesh size; ring wins when seq is
  huge or heads are few.

Both are *shard-level* functions (call under ``shard_map`` with ``q, k, v``
sharded along the sequence dim) — :func:`sequence_parallel_attention` is the
array-level wrapper that sets up the shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collectives import axis_size, shard_map_unchecked

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "sequence_parallel_attention",
]

_NEG_INF = -1e30


def _block_stats(q, k, v, scale, mask):
    """Unnormalized attention of one (Q-shard, K/V-shard) block pair.

    Returns running-max ``m`` (…, sq, 1), normalizer ``l`` (…, sq, 1) and
    unnormalized output ``o`` (…, sq, d) for online-softmax combination."""
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # guard fully-masked rows: exp(-inf - -inf) → nan otherwise
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32))
    return m_safe, l, o


def _combine(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partials (the flash-attention combine rule)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return m, l1 * a1 + l2 * a2, o1 * a1 + o2 * a2


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Exact attention over a sequence sharded along ``axis_name``
    (shard-level; call inside ``shard_map``).

    ``q, k, v``: ``(..., seq_local, head_dim)``.  Device ``i`` holds global
    sequence rows ``[i*seq_local, (i+1)*seq_local)``.  Each of the ``N`` ring
    steps attends the resident Q block to one K/V block, then rotates K/V one
    position down the ring (``ppermute`` on neighboring ICI links) — the
    Ring Attention schedule (Liu et al., 2023), built from the same ring
    dataflow as the reference's pairwise-distance loop
    (heat/spatial/distance.py:209)."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    sq = q.shape[-2]
    sk = k.shape[-2]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d**0.5)

    q_pos = idx * sq + jnp.arange(sq)[:, None]  # global row ids (sq, 1)

    bshape = q.shape[:-2]
    m0 = jnp.full(bshape + (sq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros(bshape + (sq, 1), jnp.float32)
    o0 = jnp.zeros(bshape + (sq, d), jnp.float32)

    perm = [(i, (i - 1) % n) for i in range(n)]

    def step(carry, r):
        m, l, o, kb, vb = carry
        # K/V block r came from device (idx + r) mod n
        src = (idx + r) % n
        k_pos = src * sk + jnp.arange(sk)[None, :]  # (1, sk)
        mask = jnp.ones((sq, sk), bool)
        if causal:
            mask = q_pos >= k_pos
        mb, lb, ob = _block_stats(q, kb, vb, scale, mask)
        m, l, o = _combine(m, l, o, mb, lb, ob)
        # rotate K/V to the next device (skip the final, unused rotation is
        # harmless under scan's static trip count)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (m, l, o, kb, vb), None

    (m, l, o, _, _), _ = lax.scan(step, (m0, l0, o0, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zero output
    return (o / l).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style; shard-level).

    ``q, k, v``: ``(heads, seq_local, head_dim)`` with heads divisible by the
    axis size.  One ``all_to_all`` swaps the sharded dim from sequence to
    heads, each device runs full-sequence attention for its head group
    (through the Pallas flash kernel on TPU), and the inverse ``all_to_all``
    restores sequence sharding."""
    from ..ops.attention import flash_attention

    n = axis_size(axis_name)
    h = q.shape[0]
    if h % n:
        raise ValueError(f"heads {h} not divisible by mesh axis size {n}")

    def seq_to_head(x):
        # (h, s_loc, d) → (h/n, s_glob, d)
        return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1, tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0, tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    return head_to_seq(out)


def sequence_parallel_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str,
    *,
    causal: bool = False,
    strategy: str = "ring",
):
    """Array-level entry: attention with the sequence dim sharded over
    ``axis_name``.

    ``q, k, v``: ``(batch, heads, seq, head_dim)`` global arrays; the ``seq``
    dim is (re)sharded over ``axis_name``.  ``strategy`` is ``"ring"`` or
    ``"ulysses"``."""
    if strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown strategy {strategy!r}")
    # batch rides the remaining mesh axes (dp) so each dp group keeps its own
    # batch shard; only the sequence dim is gathered/rotated over axis_name
    other = tuple(a for a in mesh.axis_names if a != axis_name)
    spec = P(other if other else None, None, axis_name, None)

    if strategy == "ring":

        def fn(qs, ks, vs):
            return ring_attention(qs, ks, vs, axis_name, causal=causal)

    else:

        def fn(qs, ks, vs):
            # fold batch into heads for the (h, s, d) shard-level layout
            b, h, s, d = qs.shape

            def one(x):
                return x.reshape(b * h, s, d)

            out = ulysses_attention(
                one(qs), one(ks), one(vs), axis_name, causal=causal
            )
            return out.reshape(b, h, s, d)

    return shard_map_unchecked(
        fn,
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
