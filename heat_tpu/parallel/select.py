"""Distributed boolean-mask selection along the split axis.

The reference keeps ``x[mask]`` distributed with unbalanced output: each
rank selects from its local shard and the result's lshape map is whatever
the mask left behind (heat/core/dndarray.py:779-1035).  GSPMD arrays hold
the canonical even-chunk layout instead, so the TPU-native design is a
*compact-and-rebalance* program (round 4, closing the last indexing path
that replicated):

1. **count** — one tiny host readback of ``mask.sum()`` fixes the output
   extent ``n_sel`` (XLA needs static shapes; the reference pays the same
   sync in its Allgather of local counts).
2. **shard-local compact** — each shard keeps its selected elements,
   front-compacted by a stable argsort (the ``unique_compact_sorted``
   pattern, parallel/sort.py:445).
3. **count exchange** — an ``all_gather`` of ONE int32 per shard gives
   every shard its exclusive prefix, hence each selected element's global
   destination position.
4. **rebalance** — each shard scatters its survivors into a zero buffer at
   their global destinations and ONE ``psum_scatter`` (reduce-scatter over
   ICI) hands every shard exactly its canonical output slab.

The input is never gathered: per-shard peak memory is one output-sized
send buffer (``n_sel``-sized — the thing being *produced*), never the
input-sized replicated intermediate the eager path materialized.  Wire
traffic is one reduce-scatter of the output volume plus S scalars.

``flatten=True`` serves the full-``ndim`` mask form ``x[m]`` with
``m.shape == x.shape`` (row-major flattened output): with split=0 the
global row-major flatten is shard-contiguous, so the same program runs on
the per-shard flattened slabs.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import shard_map_unchecked

__all__ = ["distributed_mask_select", "distributed_take", "distributed_pair_take"]


def _build_mask_select(mesh, axis_name, split, ndim, n_valid, per_out, flatten):
    S = int(mesh.shape[axis_name])

    def local(vals, mask):
        r = lax.axis_index(axis_name)
        v = jnp.moveaxis(vals, split, 0)
        if flatten:
            v = v.reshape(-1)
            m = jnp.moveaxis(mask, split, 0).reshape(-1)
        else:
            m = mask
        per = v.shape[0]
        pos = r * per + jnp.arange(per)
        keep = m & (pos < n_valid)
        c = keep.sum(dtype=jnp.int32)
        counts = lax.all_gather(c, axis_name)  # (S,) int32 — the count exchange
        prefix = jnp.sum(jnp.where(jnp.arange(S) < r, counts, 0))
        order = jnp.argsort(~keep, stable=True)  # survivors to the slab front
        sel = jnp.take(v, order, axis=0)
        i = jnp.arange(per)
        # destination global position of the i-th survivor; past-count rows
        # get an out-of-range sentinel and are dropped by the scatter
        dest = jnp.where(i < c, prefix + i, S * per_out)
        buf = jnp.zeros((S * per_out,) + sel.shape[1:], sel.dtype)
        buf = buf.at[dest].set(sel, mode="drop")
        out = lax.psum_scatter(buf, axis_name, scatter_dimension=0, tiled=True)
        if not flatten:
            out = jnp.moveaxis(out, 0, split)
        return out

    dim_spec = lambda nd, sdim: P(*[axis_name if d == sdim else None for d in range(nd)])
    vals_spec = dim_spec(ndim, split)
    mask_spec = vals_spec if flatten else P(axis_name)
    out_spec = P(axis_name) if flatten else vals_spec
    smapped = shard_map_unchecked(
        local, mesh, in_specs=(vals_spec, mask_spec), out_specs=out_spec
    )

    def run(vals, mask):
        # psum_scatter has no bool reduction: route bool payloads via uint8
        isbool = vals.dtype == jnp.bool_
        v = vals.astype(jnp.uint8) if isbool else vals
        out = smapped(v, mask.astype(jnp.bool_))
        return out.astype(jnp.bool_) if isbool else out

    return run


@lru_cache(maxsize=512)
def _jit_mask_select(mesh, axis_name, split, ndim, n_valid, per_out, flatten):
    # NB: the program depends on n_sel only through per_out = ceil(n_sel/S),
    # so per_out (not n_sel) is the cache key — masks whose popcounts share a
    # chunk size share one compiled executable
    return jax.jit(
        _build_mask_select(mesh, axis_name, split, ndim, n_valid, per_out, flatten)
    )


def distributed_mask_select(
    phys_vals: jax.Array,
    phys_mask: jax.Array,
    mesh,
    axis_name: str,
    split: int,
    n_valid: int,
    n_sel: int,
    flatten: bool = False,
):
    """Select ``phys_vals``'s elements where ``phys_mask`` holds, along the
    sharded axis ``split`` (both in canonical physical layout).  Returns the
    physical output: canonical even-chunk layout of extent ``n_sel`` along
    the selection axis (``flatten=True``: a 1-D split-0 result).
    ``n_sel`` must equal the mask's true count (host-known; see module doc).
    """
    S = int(mesh.shape[axis_name])
    per_out = -(-int(n_sel) // S)
    fn = _jit_mask_select(
        mesh, axis_name, int(split), phys_vals.ndim, int(n_valid), per_out,
        bool(flatten),
    )
    return fn(phys_vals, phys_mask)


def _build_int_gather(mesh, axis_name, split, ndim, per_out,
                      tile_per=None, n_tiles=1):
    """Distributed integer-array gather along the split axis (round 5;
    VERDICT r4 weak #3 / next #5): output row ``t`` is input row
    ``rows[t]``.  Since round 6 this is the tiled transport engine
    (:mod:`heat_tpu.parallel.transport`): per output tile, each shard
    contributes the requested rows it owns and ONE ``psum_scatter``
    (reduce-scatter) delivers the tile — wire volume is the OUTPUT size,
    staging is ``S*tile`` rows (never the global output the round-5
    monolith staged), and the input is never gathered (the reference
    keeps these distributed too, dndarray.py:779-1035).  ``rows`` rides
    replicated in destination-grid layout: it is index metadata (n_out
    ints), not data.  ``tile_per=None`` means one tile of ``per_out``
    rows (the monolithic special case)."""
    from .transport import _build_tiled_gather

    if tile_per is None:
        tile_per = per_out
    return _build_tiled_gather(
        mesh, axis_name, split, ndim, per_out, tile_per, n_tiles
    )


@lru_cache(maxsize=512)
def _jit_int_gather(mesh, axis_name, split, ndim, per_out,
                    tile_per=None, n_tiles=1):
    return jax.jit(
        _build_int_gather(mesh, axis_name, split, ndim, per_out, tile_per, n_tiles)
    )


def distributed_take(
    phys_vals: jax.Array,
    rows,
    mesh,
    axis_name: str,
    split: int,
):
    """Gather ``phys_vals``'s rows ``rows`` along the sharded axis
    ``split`` (canonical physical layout).  ``rows`` is 1-D int — host-
    (``np.ndarray``) or device-resident (``jax.Array``) — already
    normalized to the valid non-negative range by the caller
    (out-of-range rows would silently read padding).  Returns the physical
    output: canonical even-chunk layout with extent ``len(rows)`` on the
    split axis.  No device sync: the output extent is ``rows.shape[0]``,
    static either way.  Routed through the tiled transport engine: peak
    staging per device is ``O(tile)``, not the global output."""
    from .transport import tiled_take

    return tiled_take(phys_vals, rows, mesh, axis_name, split)


def _build_pair_take(mesh, axis_name, t_ax, p2, ndim):
    """Local pairing step for mixed advanced keys (x[rows, cols]-class):
    input ``y`` is the already-transported array (t-axis = ``t_ax``, sharded
    there); output element t takes ``y[..., t, ..., cols[t], ...]`` —
    dimension ``p2`` is consumed.  Purely local: ``cols`` rides replicated
    (host-known metadata) and each shard slices its own span.  No
    collectives at all."""

    p2_m = p2 + 1 if p2 < t_ax else p2          # p2 after t moves to front
    t_after = t_ax - (1 if p2 < t_ax else 0)    # t position after squeeze

    def local(yv, cols):
        r = lax.axis_index(axis_name)
        per = yv.shape[t_ax]
        lc = lax.dynamic_slice_in_dim(cols, r * per, per)
        ym = jnp.moveaxis(yv, t_ax, 0)          # (per, ...)
        idx_shape = [1] * ym.ndim
        idx_shape[0] = per
        idx = lc.reshape(idx_shape)
        out = jnp.take_along_axis(ym, idx, axis=p2_m)
        out = jnp.squeeze(out, axis=p2_m)
        return jnp.moveaxis(out, 0, t_after)

    in_spec = P(*[axis_name if d == t_ax else None for d in range(ndim)])
    out_spec = P(*[axis_name if d == t_after else None for d in range(ndim - 1)])
    return shard_map_unchecked(
        local, mesh, in_specs=(in_spec, P()), out_specs=out_spec
    )


@lru_cache(maxsize=512)
def _jit_pair_take(mesh, axis_name, t_ax, p2, ndim):
    return jax.jit(_build_pair_take(mesh, axis_name, t_ax, p2, ndim))


def distributed_pair_take(
    phys_y: jax.Array,
    cols: np.ndarray,
    mesh,
    axis_name: str,
    t_ax: int,
    p2: int,
):
    """Apply the local pairing step (see :func:`_build_pair_take`); ``cols``
    must be host-known, 1-D, length = the t-axis logical extent, already
    normalized to [0, dim_p2).  Returns the physical output (t-axis keeps
    its canonical sharding at the adjusted position)."""
    S = int(mesh.shape[axis_name])
    per = phys_y.shape[t_ax] // S
    pad = S * per - int(cols.shape[0])
    cols_pad = np.concatenate(
        [np.asarray(cols, np.int32), np.zeros((pad,), np.int32)]
    )
    fn = _jit_pair_take(mesh, axis_name, int(t_ax), int(p2), phys_y.ndim)
    return fn(phys_y, jnp.asarray(cols_pad))
