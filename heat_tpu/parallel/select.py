"""Distributed boolean-mask selection along the split axis.

The reference keeps ``x[mask]`` distributed with unbalanced output: each
rank selects from its local shard and the result's lshape map is whatever
the mask left behind (heat/core/dndarray.py:779-1035).  GSPMD arrays hold
the canonical even-chunk layout instead, so the TPU-native design is a
*compact-and-rebalance* program (round 4, closing the last indexing path
that replicated):

1. **count** — one tiny host readback of ``mask.sum()`` fixes the output
   extent ``n_sel`` (XLA needs static shapes; the reference pays the same
   sync in its Allgather of local counts).
2. **shard-local compact** — each shard keeps its selected elements,
   front-compacted by a stable argsort (the ``unique_compact_sorted``
   pattern, parallel/sort.py:445).
3. **count exchange** — an ``all_gather`` of ONE int32 per shard gives
   every shard its exclusive prefix, hence each selected element's global
   destination position.
4. **rebalance** — each shard scatters its survivors into a zero buffer at
   their global destinations and ONE ``psum_scatter`` (reduce-scatter over
   ICI) hands every shard exactly its canonical output slab.

The input is never gathered: per-shard peak memory is one output-sized
send buffer (``n_sel``-sized — the thing being *produced*), never the
input-sized replicated intermediate the eager path materialized.  Wire
traffic is one reduce-scatter of the output volume plus S scalars.

``flatten=True`` serves the full-``ndim`` mask form ``x[m]`` with
``m.shape == x.shape`` (row-major flattened output): with split=0 the
global row-major flatten is shard-contiguous, so the same program runs on
the per-shard flattened slabs.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import shard_map_unchecked

__all__ = ["distributed_mask_select"]


def _build_mask_select(mesh, axis_name, split, ndim, n_valid, per_out, flatten):
    S = int(mesh.shape[axis_name])

    def local(vals, mask):
        r = lax.axis_index(axis_name)
        v = jnp.moveaxis(vals, split, 0)
        if flatten:
            v = v.reshape(-1)
            m = jnp.moveaxis(mask, split, 0).reshape(-1)
        else:
            m = mask
        per = v.shape[0]
        pos = r * per + jnp.arange(per)
        keep = m & (pos < n_valid)
        c = keep.sum(dtype=jnp.int32)
        counts = lax.all_gather(c, axis_name)  # (S,) int32 — the count exchange
        prefix = jnp.sum(jnp.where(jnp.arange(S) < r, counts, 0))
        order = jnp.argsort(~keep, stable=True)  # survivors to the slab front
        sel = jnp.take(v, order, axis=0)
        i = jnp.arange(per)
        # destination global position of the i-th survivor; past-count rows
        # get an out-of-range sentinel and are dropped by the scatter
        dest = jnp.where(i < c, prefix + i, S * per_out)
        buf = jnp.zeros((S * per_out,) + sel.shape[1:], sel.dtype)
        buf = buf.at[dest].set(sel, mode="drop")
        out = lax.psum_scatter(buf, axis_name, scatter_dimension=0, tiled=True)
        if not flatten:
            out = jnp.moveaxis(out, 0, split)
        return out

    dim_spec = lambda nd, sdim: P(*[axis_name if d == sdim else None for d in range(nd)])
    vals_spec = dim_spec(ndim, split)
    mask_spec = vals_spec if flatten else P(axis_name)
    out_spec = P(axis_name) if flatten else vals_spec
    smapped = shard_map_unchecked(
        local, mesh, in_specs=(vals_spec, mask_spec), out_specs=out_spec
    )

    def run(vals, mask):
        # psum_scatter has no bool reduction: route bool payloads via uint8
        isbool = vals.dtype == jnp.bool_
        v = vals.astype(jnp.uint8) if isbool else vals
        out = smapped(v, mask.astype(jnp.bool_))
        return out.astype(jnp.bool_) if isbool else out

    return run


@lru_cache(maxsize=512)
def _jit_mask_select(mesh, axis_name, split, ndim, n_valid, per_out, flatten):
    # NB: the program depends on n_sel only through per_out = ceil(n_sel/S),
    # so per_out (not n_sel) is the cache key — masks whose popcounts share a
    # chunk size share one compiled executable
    return jax.jit(
        _build_mask_select(mesh, axis_name, split, ndim, n_valid, per_out, flatten)
    )


def distributed_mask_select(
    phys_vals: jax.Array,
    phys_mask: jax.Array,
    mesh,
    axis_name: str,
    split: int,
    n_valid: int,
    n_sel: int,
    flatten: bool = False,
):
    """Select ``phys_vals``'s elements where ``phys_mask`` holds, along the
    sharded axis ``split`` (both in canonical physical layout).  Returns the
    physical output: canonical even-chunk layout of extent ``n_sel`` along
    the selection axis (``flatten=True``: a 1-D split-0 result).
    ``n_sel`` must equal the mask's true count (host-known; see module doc).
    """
    S = int(mesh.shape[axis_name])
    per_out = -(-int(n_sel) // S)
    fn = _jit_mask_select(
        mesh, axis_name, int(split), phys_vals.ndim, int(n_valid), per_out,
        bool(flatten),
    )
    return fn(phys_vals, phys_mask)
