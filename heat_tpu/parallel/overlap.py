"""Overlap-scheduled collective matmul: ring-decomposed GEMM with fused epilogues.

The reference's ``matmul`` (heat/core/linalg/basics.py:424) is a ~700-line
hand-scheduled block ring because overlapping tile communication with the
local GEMM is where distributed matmul performance lives.  The rebuild's
default is the opposite extreme — one einsum under GSPMD
(core/linalg/basics.py) — which serializes the collective against the
compute and, when the convention out-split disagrees with XLA's chosen
layout, pays a second full-array resplit (``_ensure_split``).

This module is the middle path (Wang et al., ASPLOS 2023: decompose the
collective matmul so each transferred tile overlaps the previous tile's
dot).  The three canonical sharded 2-D GEMM cases lower to per-step
shard_map programs whose ring transfers (``ring_shift`` — one
collective-permute riding the ICI torus links) are issued *before* the
step's local dot, so XLA's async collectives run the wire and the MXU
concurrently:

``ag``   A row-split  ×  B row-split  →  out row-split.
         Stationary A row-block; B's k-blocks rotate.  The all-gather of B
         that GSPMD would materialize is unrolled into the ring and the
         replicated copy never exists.
``rs``   A col-split  ×  B row-split  (inner-dim split)  →  out row-split,
         col-split or replicated — the caller's choice.  The *accumulator*
         travels: each hop carries a partial out-block one neighbor further
         while the next partial dot computes, a reduce-scatter unrolled
         into the ring that lands directly in the requested out-split (no
         ``_ensure_split`` second pass, no full-size psum buffer).
``col``  A col-split  ×  B col-split  →  out col-split.
         Stationary B col-block; A's k-blocks rotate (symmetric to ``ag``).

Every program carries an optional fused epilogue — ``scale``/``bias``/
``activation``/``cast`` via :class:`Epilogue` for eager calls, or an
arbitrary elementwise tail captured from the fusion DAG (``core/fusion.py``
chains ending in matmul lower here through the registered chain
terminator) — applied to the final local block inside the same executable.
Epilogue constants enter as runtime operands, so new values never retrace.

Dispatch: ``HEAT_TPU_MATMUL=auto|gspmd|ring`` (auto picks the ring above
``HEAT_TPU_MATMUL_RING_MIN_BYTES`` moved per ring step, GSPMD for
tiny/replicated operands).  With the tuning plane live
(``HEAT_TPU_AUTOTUNE=on``, the default — see ``core/autotune.py``) the
byte threshold is only a *prior*: in ``auto`` mode the first K eager
calls per GEMM geometry run BOTH arms under measurement (the ring
program and the GSPMD reference einsum), the winner by steady-state
``min_s`` sticks, and lazy chains consume resolved winners at lowering
time.  A plan-time staging check against measured free HBM
(``memtrack.suggest_budget``) declines the ring before it can OOM.
Eager programs are cached via ``jit_shard_map_cached``; lazy chains live
in the fusion compile cache (one entry per chain × dispatch mode ×
autotune generation).  :func:`stats` reports the schedule decisions,
steps, bytes/step and cache hits.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import autotune, memtrack, telemetry
from ..core import wire as _wire
from ..analysis import program_audit, sanitize
from .collectives import (
    all_gather,
    jit_shard_map_cached,
    ring_shift,
    shard_map_unchecked,
)

__all__ = [
    "Epilogue",
    "matmul",
    "matmul_raw",
    "ring_sweep",
    "stats",
    "reset_stats",
    "set_mode",
]


# ------------------------------------------------------------------ dispatch

_VALID_MODES = ("auto", "gspmd", "ring")
_RING_MIN_BYTES_DEFAULT = 1 << 20  # 1 MiB moved over the ring
# plan-time staging admission: the ring's per-device residency (both
# padded operands + the accumulator) may spend at most this fraction of
# measured free HBM; beyond it the dispatcher declines to GSPMD, whose
# fused collective degrades more gracefully under memory pressure
_STAGING_FRACTION = 0.5
_MODE_OVERRIDE: Optional[str] = None

# static-decision reasons that mean the ring schedule is IMPOSSIBLE for
# this layout/mesh (vs merely dispreferred) — the tuning plane never
# second-guesses these
_RING_IMPOSSIBLE = ("layout", "mesh1", "out-split")


def set_mode(mode: Optional[str]) -> Optional[str]:
    """Process-wide override of ``HEAT_TPU_MATMUL`` (``None`` restores the
    environment variable).  Returns the previous override."""
    global _MODE_OVERRIDE
    if mode is not None and mode not in _VALID_MODES:
        raise ValueError(f"mode must be one of {_VALID_MODES}, got {mode!r}")
    prev = _MODE_OVERRIDE
    _MODE_OVERRIDE = mode
    return prev


def _mode() -> str:
    if _MODE_OVERRIDE is not None:
        return _MODE_OVERRIDE
    raw = os.environ.get("HEAT_TPU_MATMUL", "auto").strip().lower()
    return raw if raw in _VALID_MODES else "auto"


def _ring_min_bytes() -> int:
    # one parser with HEAT_TPU_TILE_BYTES (autotune.env_bytes): a
    # malformed value raises instead of silently running the default —
    # an operator's typo'd threshold must not become an invisible perf bug
    return autotune.env_bytes(
        "HEAT_TPU_MATMUL_RING_MIN_BYTES", _RING_MIN_BYTES_DEFAULT
    )


def _dispatch_salt() -> tuple:
    # participates in the fusion compile-cache key: flipping the mode or
    # threshold must build a distinct entry, not reuse the other mode's.
    # The wire knobs join for the same reason — a forced HEAT_TPU_WIRE
    # flip changes the chain's compiled collectives without any autotune
    # generation bump (winner flips ride autotune.salt instead).
    return (
        "overlap", _mode(), _ring_min_bytes(), _wire.mode(),
        _wire.min_bytes(),
    )


def _ceil_mult(n: int, s: int) -> int:
    return -(-n // s) * s


def _classify(a_split: Optional[int], b_split: Optional[int]) -> Optional[str]:
    if a_split == 0 and b_split == 0:
        return "ag"
    if a_split == 1 and b_split == 0:
        return "rs"
    if a_split == 1 and b_split == 1:
        return "col"
    return None


def _decide(case, out_split, m, k, n, S, comp_isz, acc_isz):
    """Schedule decision: ``(use_ring, reason, bytes_per_step)``.

    bytes/step is the per-device ICI traffic of one ring hop — the moving
    operand block (``ag``/``col``) or the traveling accumulator (``rs``).
    ``auto`` rings only when total wire traffic clears the threshold: below
    it the per-step dispatch overhead beats any overlap win and GSPMD's
    single fused collective is faster."""
    if case is None:
        return False, "layout", 0
    if S <= 1:
        return False, "mesh1", 0
    if case == "ag" and out_split != 0:
        return False, "out-split", 0
    if case == "col" and out_split != 1:
        return False, "out-split", 0
    if case == "ag":
        bps = (_ceil_mult(k, S) // S) * n * comp_isz
    elif case == "col":
        bps = m * (_ceil_mult(k, S) // S) * comp_isz
    elif out_split == 1:
        bps = m * (_ceil_mult(n, S) // S) * acc_isz
    else:
        bps = (_ceil_mult(m, S) // S) * n * acc_isz
    mode = _mode()
    if mode == "gspmd":
        return False, "mode=gspmd", bps
    if mode == "ring":
        return True, "mode=ring", bps
    if bps * (S - 1) < _ring_min_bytes():
        return False, "below-threshold", bps
    return True, "auto", bps


# --------------------------------------------------------------------- stats

_SEEN: set = set()

# Registered as the "overlap" telemetry group; on_reset clears the
# build-dedup set alongside the counters (registry-managed, one site).
_STATS = telemetry.register_group(
    "overlap",
    {
        "calls": 0,
        "ring_calls": 0,
        "gspmd_calls": 0,
        "ring_builds": 0,
        "cache_hits": 0,
        "by_schedule": {"ring_ag": 0, "ring_rs": 0, "ring_col": 0, "gspmd": 0},
        "last": None,
    },
    on_reset=_SEEN.clear,
)


def stats() -> dict:
    """Dispatcher counters: ``calls`` (decisions), ``ring_calls`` /
    ``gspmd_calls``, ``ring_builds`` (programs built), ``cache_hits``
    (eager ring calls served by an already-built program; lazy-chain reuse
    is counted by ``fusion.cache_stats()`` instead), ``by_schedule``, and
    ``last`` — the most recent decision's schedule, steps, bytes/step,
    out-split and reason.

    Thin shim over ``telemetry.snapshot_group("overlap")`` — the same
    counters appear in ``ht.telemetry.snapshot()``."""
    return telemetry.snapshot_group("overlap")


def reset_stats() -> None:
    """Zero the dispatcher counters and the build-dedup set
    (registry-managed via ``telemetry.reset_group``)."""
    telemetry.reset_group("overlap")


def _record(schedule, *, steps=0, bps=0, out_split=None, reason="",
            cache_hit=False):
    _STATS["calls"] += 1
    if schedule == "gspmd":
        _STATS["gspmd_calls"] += 1
    else:
        _STATS["ring_calls"] += 1
        if cache_hit:
            _STATS["cache_hits"] += 1
        else:
            _STATS["ring_builds"] += 1
    _STATS["by_schedule"][schedule] += 1
    _STATS["last"] = {
        "schedule": schedule, "steps": steps, "bytes_per_step": bps,
        "out_split": out_split, "reason": reason,
    }
    # the flight recorder keeps the decision WITH its cost-model inputs —
    # the ring-vs-GSPMD trail the counters alone cannot reconstruct
    telemetry.record_event(
        "matmul_dispatch", schedule=schedule, steps=steps,
        bytes_per_step=bps, out_split=out_split, reason=reason,
        cache_hit=cache_hit,
    )


# ---------------------------------------------------------------- ring sweep

def ring_sweep(axis: str, n_steps: int, moving, state, step: Callable):
    """Unrolled ring schedule: ``state = step(t, moving_t, state)`` for each
    of ``n_steps`` ring positions, with the next hop's ``ring_shift`` issued
    *before* the step's compute so XLA overlaps the transfer of block t+1
    with the local work on block t.  Unrolling (python range, not
    fori_loop) is what makes the overlap possible — a loop iteration is a
    scheduling barrier, an unrolled chain is not.  The final useless shift
    is elided.

    ``moving`` may be any pytree — every leaf hops together, which is how
    a quantized block and its scale table ride the same ring position
    (the wire arms of :func:`_build_ring`)."""
    for t in range(n_steps):
        nxt = (
            jax.tree_util.tree_map(
                lambda v: ring_shift(v, axis, shift=1), moving
            )
            if t + 1 < n_steps
            else None
        )
        state = step(t, moving, state)
        moving = nxt
    return state


# ----------------------------------------------------------------- epilogue

def _cast(x, dtype):
    return x.astype(dtype)


def _apply_steps(blk, steps, extras):
    for fn, kw, pat in steps:
        blk = fn(*[blk if p < 0 else extras[p] for p in pat], **dict(kw))
    return blk


@dataclasses.dataclass(frozen=True)
class Epilogue:
    """Fused matmul tail for eager calls, applied to each final local block
    inside the ring program: ``out = cast(act(scale * (a @ b) + bias))``
    (``None`` fields are skipped).  ``bias`` broadcasts against the 2-D
    result; ``activation`` must be a traceable elementwise callable (e.g.
    ``jax.nn.relu``; use a module-level function — a fresh lambda per call
    defeats the program cache).  ``scale``/``bias`` enter the program as
    runtime operands: new constants never retrace."""

    scale: Any = None
    bias: Any = None
    activation: Optional[Callable] = None
    dtype: Any = None

    def __post_init__(self):
        # fail at construction, not steps deep inside a ring program: a
        # bad operand here would otherwise surface as a shard_map shape
        # mismatch with no mention of the epilogue at all
        for name in ("scale", "bias"):
            value = getattr(self, name)
            if value is None or isinstance(value, jax.core.Tracer):
                continue
            try:
                arr = jnp.asarray(value)
            except (TypeError, ValueError) as exc:
                raise TypeError(
                    f"Epilogue.{name} must be numeric/array-like "
                    f"(got {type(value).__name__}): {exc}"
                ) from None
            if not jnp.issubdtype(arr.dtype, jnp.number):
                raise TypeError(
                    f"Epilogue.{name} must be numeric, got dtype {arr.dtype}"
                )
            if arr.ndim > 2:
                raise ValueError(
                    f"Epilogue.{name} must be scalar, 1-D, or 2-D — it "
                    f"broadcasts against the 2-D matmul result; got "
                    f"ndim={arr.ndim} (shape {tuple(arr.shape)})"
                )
        if self.activation is not None and not callable(self.activation):
            raise TypeError(
                "Epilogue.activation must be a traceable callable, got "
                f"{type(self.activation).__name__}"
            )
        if self.dtype is not None:
            try:
                jnp.dtype(self.dtype)
            except TypeError:
                raise TypeError(
                    f"Epilogue.dtype is not a dtype: {self.dtype!r}"
                ) from None

    def lower(self):
        """→ ``(steps, extras)`` in the engine's internal encoding: each
        step is ``(fn, static_kwargs_items, arg_pattern)`` with ``-1`` in
        the pattern marking the flowing block and ``i ≥ 0`` an extras
        operand."""
        steps, extras = [], []
        if self.scale is not None:
            extras.append(jnp.asarray(self.scale))
            steps.append((jnp.multiply, (), (-1, len(extras) - 1)))
        if self.bias is not None:
            extras.append(jnp.asarray(self.bias))
            steps.append((jnp.add, (), (-1, len(extras) - 1)))
        if self.activation is not None:
            steps.append((self.activation, (), (-1,)))
        if self.dtype is not None:
            steps.append((_cast, (("dtype", jnp.dtype(self.dtype)),), (-1,)))
        return tuple(steps), tuple(extras)


def _check_extras(extras, gshape, out_split) -> None:
    """Validate epilogue extras against the GLOBAL result shape before a
    ring program is built.  Each extra must broadcast against the 2-D
    result; along the out-split axis the only legal extents are 1
    (broadcast) or the full global extent (the kernel slices it per ring
    block — see :func:`_extra_axes`).  A partial extent used to die deep
    inside shard_map as an unrelated shape-mismatch error."""
    for i, value in enumerate(extras):
        es = tuple(value.shape)
        if len(es) > 2:
            raise ValueError(
                f"epilogue extra {i} (shape {es}) cannot broadcast "
                f"against the 2-D matmul result {tuple(gshape)}"
            )
        for off in range(1, len(es) + 1):
            ext, full = es[-off], gshape[-off]
            if ext in (1, full):
                continue
            res_ax = len(gshape) - off
            sliced = out_split is not None and res_ax == out_split
            raise ValueError(
                f"epilogue extra {i} has shape {es}: axis {len(es) - off} "
                f"has length {ext}, expected 1 or the full result extent "
                f"{full} (result axis {res_ax} of {tuple(gshape)}"
                + (", sliced per ring block along the out-split)"
                   if sliced else ")")
            )


def _extra_axes(extra_shapes, gshape, out_split) -> tuple:
    """Per-extra axis that tracks the out-split (kernel slices it per
    block), or None when the extra broadcasts along the split dim."""
    axes = []
    for es in extra_shapes:
        eax = None
        if out_split is not None and es:
            ax = out_split - (len(gshape) - len(es))
            if 0 <= ax < len(es) and es[ax] == gshape[out_split] and es[ax] > 1:
                eax = ax
        axes.append(eax)
    return tuple(axes)


# ------------------------------------------------------------- ring kernels

class _Spec(NamedTuple):
    """Hashable program identity for ``jit_shard_map_cached`` / the fusion
    compile cache.  Epilogue ``steps`` carry function objects (hashable);
    extra *values* stay out — they are runtime operands."""

    case: str
    out_split: Optional[int]
    axis: str
    S: int
    m: int
    k: int
    n: int
    comp_dt: str     # dtype both operands are cast to (the promoted dtype)
    acc_dt: str      # dot accumulator (f32 for half inputs)
    steps: tuple
    extra_axes: tuple
    prec: Any
    fold: bool       # return (block, allfinite) for the folded guard
    wire: str = ""   # on-wire format of the moving block ("" | int8 | fp8):
    #                  the ring ships absmax-quantized hops with one f32
    #                  scale per contraction slice (core/wire.py)


def _build_ring(mesh, spec: _Spec):
    """One shard_map program for one :class:`_Spec` (un-jitted; callers jit
    it — directly for eager entries, traced into the fused chain program
    for the terminator path)."""
    case, out_split, axis, S = spec.case, spec.out_split, spec.axis, spec.S
    m, k, n = spec.m, spec.k, spec.n
    comp = jnp.dtype(spec.comp_dt)
    acc_dt = jnp.dtype(spec.acc_dt)
    kp, mp, np_ = _ceil_mult(k, S), _ceil_mult(m, S), _ceil_mult(n, S)
    kb, mb, nb = kp // S, mp // S, np_ // S

    def _dot(x, y):
        return lax.dot_general(
            x, y, (((1,), (0,)), ((), ())),
            precision=spec.prec, preferred_element_type=acc_dt,
        )

    # the k-pad region of a physical operand is not guaranteed zero (a
    # donated or transported buffer may carry garbage, even NaN — and
    # NaN·0 would still poison the dot), so both operands' k-pads are
    # masked to exact zeros before any block enters the ring
    def _mask_k(v, me, axis_in_v):
        gidx = me * kb + jnp.arange(kb, dtype=jnp.int32)
        keep = gidx < k
        keep = keep[:, None] if axis_in_v == 0 else keep[None, :]
        return jnp.where(keep, v, jnp.zeros((), v.dtype))

    def _finish(blk, extras, me):
        blk = blk.astype(comp)
        blk_sz = mb if out_split == 0 else nb
        ex = []
        for v, eax in zip(extras, spec.extra_axes):
            if eax is not None:
                ext = v.shape[eax]
                pad = blk_sz * S - ext
                if pad:
                    v = jnp.pad(
                        v, [(0, pad) if i == eax else (0, 0) for i in range(v.ndim)]
                    )
                v = lax.dynamic_slice_in_dim(v, me * blk_sz, blk_sz, axis=eax)
            ex.append(v)
        blk = _apply_steps(blk, spec.steps, ex)
        # re-zero the out-split pad rows/cols: they hold garbage from the
        # operand pads (and the epilogue's bias would otherwise leak into
        # them), and the physical-layout contract is zero pad
        if out_split == 0 and mp != m:
            rows = me * mb + jnp.arange(mb, dtype=jnp.int32)
            blk = jnp.where((rows < m)[:, None], blk, jnp.zeros((), blk.dtype))
        elif out_split == 1 and np_ != n:
            cols = me * nb + jnp.arange(nb, dtype=jnp.int32)
            blk = jnp.where((cols < n)[None, :], blk, jnp.zeros((), blk.dtype))
        if not spec.fold:
            return blk
        ok = (
            jnp.all(jnp.isfinite(blk))
            if jnp.issubdtype(blk.dtype, jnp.inexact)
            else jnp.asarray(True)
        )
        return blk, lax.pmin(ok.astype(jnp.int32), axis)

    if case == "ag":
        # stationary A row-block needs every k-block of B: rotate them.
        # wire arm: the moving (kb, n) block hops as (int8/fp8 grid,
        # per-k-row f32 scales) — the masked k-pad rows are exact zeros
        # with scale 1, so padding survives the lossy wire bitwise
        def kernel(a_loc, b_loc, *extras):
            me = lax.axis_index(axis)
            av = a_loc.astype(comp)                      # (mb, k)
            bv = b_loc.astype(comp)                      # (kb, n)
            if kp != k:
                bv = _mask_k(bv, me, 0)
                av = jnp.pad(av, ((0, 0), (0, kp - k)))
            moving0 = _wire.absmax_encode(bv, spec.wire, (0,)) if spec.wire else bv

            def step(t, moving, acc):
                src = (me - t) % S
                a_blk = lax.dynamic_slice_in_dim(av, src * kb, kb, axis=1)
                if spec.wire:
                    blk = _wire.absmax_decode(moving[0], moving[1], (0,), comp)
                else:
                    blk = moving
                return acc + _dot(a_blk, blk)

            acc = ring_sweep(axis, S, moving0, jnp.zeros((mb, n), acc_dt), step)
            return _finish(acc, extras, me)

        in_op = (P(axis, None), P(axis, None))
        out_spec = P(axis, None)

    elif case == "col":
        # stationary B col-block needs every k-block of A: rotate them.
        # wire arm: the moving (m, kb) block hops quantized with one f32
        # scale per k-column (the contraction slice, mirroring ag)
        def kernel(a_loc, b_loc, *extras):
            me = lax.axis_index(axis)
            av = a_loc.astype(comp)                      # (m, kb)
            bv = b_loc.astype(comp)                      # (k, nb)
            if kp != k:
                av = _mask_k(av, me, 1)
                bv = jnp.pad(bv, ((0, kp - k), (0, 0)))
            moving0 = _wire.absmax_encode(av, spec.wire, (1,)) if spec.wire else av

            def step(t, moving, acc):
                src = (me - t) % S
                b_blk = lax.dynamic_slice_in_dim(bv, src * kb, kb, axis=0)
                if spec.wire:
                    blk = _wire.absmax_decode(moving[0], moving[1], (1,), comp)
                else:
                    blk = moving
                return acc + _dot(blk, b_blk)

            acc = ring_sweep(axis, S, moving0, jnp.zeros((m, nb), acc_dt), step)
            return _finish(acc, extras, me)

        in_op = (P(None, axis), P(None, axis))
        out_spec = P(None, axis)

    else:  # rs: inner-dim split, traveling accumulator
        eff = 1 if out_split == 1 else 0

        def kernel(a_loc, b_loc, *extras):
            me = lax.axis_index(axis)
            av = a_loc.astype(comp)                      # (m, kb)
            bv = b_loc.astype(comp)                      # (kb, n)
            if kp != k:
                av = _mask_k(av, me, 1)
                bv = _mask_k(bv, me, 0)
            if eff == 0:
                ap = jnp.pad(av, ((0, mp - m), (0, 0))) if mp != m else av

                def partial_(d):
                    blk = lax.dynamic_slice_in_dim(ap, d * mb, mb, axis=0)
                    return _dot(blk, bv)
            else:
                bp = jnp.pad(bv, ((0, 0), (0, np_ - n))) if np_ != n else bv

                def partial_(d):
                    blk = lax.dynamic_slice_in_dim(bp, d * nb, nb, axis=1)
                    return _dot(av, blk)

            # the partial sum itself rides the ring: shard r starts the
            # accumulator destined for r-1 and hops it one neighbor up per
            # step while the next local partial dot — independent of the
            # in-flight transfer — computes.  After S-1 hops every
            # accumulator reaches its destination with all S contributions:
            # a reduce-scatter unrolled into the ring.  The wire plane
            # never quantizes this case: re-snapping the PARTIAL SUM to a
            # fresh absmax grid every hop compounds the rounding error S
            # times over (dispatchers decline it statically).
            acc = partial_((me - 1) % S)
            for t in range(1, S):
                sent = ring_shift(acc, axis, shift=1)
                acc = sent + partial_((me - t - 1) % S)
            if out_split is None:
                full = all_gather(acc, axis, concat_axis=0, tiled=True)
                return _finish(full[:m], extras, me)
            return _finish(acc, extras, me)

        in_op = (P(None, axis), P(axis, None))
        out_spec = (
            P() if out_split is None
            else P(axis, None) if out_split == 0
            else P(None, axis)
        )

    in_specs = in_op + (P(),) * len(spec.extra_axes)
    out_specs = (out_spec, P()) if spec.fold else out_spec
    return shard_map_unchecked(kernel, mesh, in_specs, out_specs)


# --------------------------------------------------------------- eager entry

def _pad_physical(v, lshape, split, S):
    """Ensure ``v`` carries the even-chunk physical layout along ``split``
    (zero-padding a logical array; rejecting unexpected layouts)."""
    want = _ceil_mult(lshape[split], S)
    have = v.shape[split]
    if have == want:
        return v
    if have != lshape[split]:
        raise ValueError(
            f"operand dim {split} is {have}, neither logical "
            f"{lshape[split]} nor physical {want}"
        )
    pad = [(0, 0)] * v.ndim
    pad[split] = (0, want - have)
    return jnp.pad(v, pad)


def _spec_for(comm, case, out_split, m, k, n, comp, steps, extra_axes,
              precision, fold, wire=""):
    comp = jnp.dtype(comp)
    half = jnp.issubdtype(comp, jnp.inexact) and comp.itemsize < 4
    acc = jnp.dtype(jnp.float32) if half else comp
    return _Spec(
        case, out_split, comm.split_axis, comm.size, m, k, n,
        str(comp), str(acc), steps, extra_axes, precision, fold, wire,
    )


@functools.lru_cache(maxsize=256)
def _gspmd_reference(mesh, spec: _Spec):
    """The competing arm as one jitted program: the einsum XLA/GSPMD
    would run had the dispatcher declined, with the same epilogue tail —
    what the explore phase times the ring program against.  Takes the
    ring's PHYSICAL (padded) operands and slices back to logical, so both
    arms are driven by identical inputs, and pins the ring's out-split
    via ``out_shardings`` so GSPMD pays the same layout obligation
    (``_ensure_split``'s resplit cost is part of what the ring wins)."""
    m, k, n = spec.m, spec.k, spec.n
    comp = jnp.dtype(spec.comp_dt)
    out_spec = (
        P() if spec.out_split is None
        else P(spec.axis, None) if spec.out_split == 0
        else P(None, spec.axis)
    )

    def ref(a, b, *extras):
        out = jnp.matmul(
            a[:m, :k].astype(comp), b[:k, :n].astype(comp),
            precision=spec.prec,
        )
        return _apply_steps(out, spec.steps, extras)

    return jax.jit(ref, out_shardings=NamedSharding(mesh, out_spec))


def matmul_raw(comm, a, b, lshape_a, lshape_b, a_split, b_split,
               out_split=None, *, comp_dtype=None, epilogue: Optional[Epilogue] = None,
               precision=None, exact: bool = False):
    """Raw-array eager entry (the DNDarray-free engine core, for callers
    like ``linalg.qr`` and ``cluster.kmeans`` that hold jax arrays):
    dispatches one 2-D sharded GEMM, returning the physical result array —
    or ``None`` when the dispatcher picks GSPMD and the caller should run
    its own einsum.  ``a``/``b`` may be logical (zero-padded here) or
    already physical.

    Wire plane (round 17): the ``ag``/``col`` rings may ship their moving
    block absmax-quantized (int8/fp8 grid + f32 scales per contraction
    slice) — a second tuning axis over :data:`autotune.WIRE_ARMS`,
    consulted only once the ring-vs-GSPMD entry has stopped exploring.
    ``exact=True`` pins the f32 wire (linalg callers whose residuals are
    measured in ulps); the ``rs`` case always declines (the traveling
    partial sum cannot be re-quantized per hop)."""
    sanitize.check_use(a, "overlap.matmul_raw")
    sanitize.check_use(b, "overlap.matmul_raw")
    m, k = lshape_a
    k2, n = lshape_b
    if k != k2:
        raise ValueError(f"inner dims disagree: {lshape_a} @ {lshape_b}")
    case = _classify(a_split, b_split)
    comp = jnp.dtype(comp_dtype) if comp_dtype is not None else jnp.promote_types(
        a.dtype, b.dtype
    )
    steps, extras = epilogue.lower() if epilogue is not None else ((), ())
    if extras:
        _check_extras(extras, (m, n), out_split)
    acc_isz = 4 if (jnp.issubdtype(comp, jnp.inexact) and comp.itemsize < 4) else comp.itemsize
    use, reason, bps = _decide(
        case, out_split, m, k, n, comm.size, comp.itemsize, acc_isz
    )
    # explore/exploit consult (core/autotune.py): in auto mode with the
    # tuning plane live, the byte threshold above is only a prior — the
    # first K calls per geometry run BOTH arms under measurement (below),
    # then the measured winner overrides the threshold.  This eager entry
    # is where exploration happens; lazy chains only consume winners.
    tune = None
    if (
        reason not in _RING_IMPOSSIBLE
        and _mode() == "auto"
        and autotune.enabled()
    ):
        tune_key = autotune.matmul_key(
            case, out_split, m, k, n, comm.size, str(comp)
        )
        # plan-time staging admission from measured free HBM — refuse the
        # ring BEFORE it can RESOURCE_EXHAUST (statsless backends: None,
        # keep the static path)
        per_dev = (
            (m * k + k * n) * comp.itemsize + m * n * acc_isz
        ) // comm.size
        granted = memtrack.suggest_budget(per_dev, fraction=_STAGING_FRACTION)
        if granted is not None and granted < per_dev:
            autotune.note_staging_decline(tune_key, per_dev, granted)
            _record(
                "gspmd", steps=0, bps=bps, out_split=out_split,
                reason="hbm-budget",
            )
            return None
        tune = autotune.decide(
            tune_key, "ring" if use else "gspmd",
            desc=f"{case} {m}x{k}x{n} {comp} S={comm.size}",
        )
        if tune.explore:
            use, reason = True, "autotune:explore"
        else:
            use = tune.arm == "ring"
            reason = "autotune:" + tune.source
    if not use:
        _record("gspmd", steps=0, bps=bps, out_split=out_split, reason=reason)
        return None

    # wire-arm consult (core/wire.py): a SECOND tuning axis, deliberately
    # sequenced after the ring-vs-GSPMD axis — while the ring entry still
    # explores, the wire stays f32 so each explore measures one variable.
    # kb-slice scale counts make the byte model exact: per hop the moving
    # block ships 1-byte elements plus kb f32 scales, (S-1) hops total.
    S_ = comm.size
    kb_ = _ceil_mult(k, S_) // S_
    wire_arm, wire_d, wm = "wire_f32", None, ""
    if case == "rs":
        _wire.decline("ring_rs")
    elif not (tune is not None and tune.explore) and _wire.eligible(
        comp, bps * (S_ - 1), exact=exact
    ):
        wire_arm, wire_d = _wire.choose(
            "ring_" + case, (m, k, n, S_, str(comp)),
            desc=f"ring_{case} {m}x{k}x{n} {comp} S={S_}",
        )
        if wire_d is None or not wire_d.explore:
            wm = "" if wire_arm == "wire_f32" else wire_arm[len("wire_"):]
    wire_elems = (kb_ * n if case == "ag" else m * kb_) * (S_ - 1)
    wire_total = lambda w: _wire.payload_nbytes(wire_elems, kb_ * (S_ - 1), w)

    extra_axes = _extra_axes([tuple(v.shape) for v in extras], (m, n), out_split)
    spec = _spec_for(
        comm, case, out_split, m, k, n, comp, steps, extra_axes, precision,
        fold=False, wire=wm,
    )
    a = _pad_physical(a, lshape_a, 0 if case == "ag" else 1, comm.size)
    b = _pad_physical(b, lshape_b, 1 if case == "col" else 0, comm.size)
    # ledger the ring operands: a padded copy is transient staging; an
    # unpadded passthrough dedupes to its existing (leaf) entry
    memtrack.register_buffer(a, tag="staging")
    memtrack.register_buffer(b, tag="staging")
    seen_key = (id(comm.mesh), spec)
    hit = seen_key in _SEEN
    _SEEN.add(seen_key)
    # a wire-armed dispatch gets its own ledger row ("ring_wire" prefix):
    # the roofline must see the compressed hop volume against the same
    # logical bytes instead of averaging arms into one row
    fp_parts = ("ring", case, out_split, m, k, n, str(comp), len(steps))
    if wm:
        fp_parts = ("ring_wire",) + fp_parts[1:] + (wm,)
    ring_fp = (
        telemetry.fingerprint(fp_parts)
        if telemetry.ledger_enabled()
        else None
    )
    with telemetry.span("overlap.ring_" + case, m=m, k=k, n=n):
        fn = jit_shard_map_cached(_build_ring, comm.mesh, spec)
        if program_audit.enabled():
            program_audit.audit_program(
                "ring_" + case, ring_fp, fn, (a, b) + tuple(extras),
                expect="any",
            )
        if tune is not None and tune.explore:
            # explore: measure BOTH arms — the ring program and the GSPMD
            # reference einsum it competes with — and return the ring
            # result (the arms are numerically interchangeable; the law
            # tests hold them together).  One extra einsum per explore
            # call, K calls per geometry, then the winner runs alone.
            if hit:
                telemetry.program_hit(ring_fp)
            out, ring_s = autotune.timed(fn, a, b, *extras)
            if hit:
                # keep the roofline ledger's convention: the build call's
                # wall (trace+compile) stays out of min/p50
                telemetry.record_timing(ring_fp, ring_s)
            autotune.observe(tune.key, "ring", ring_s)
            try:
                gfn = _gspmd_reference(comm.mesh, spec)
                _, gspmd_s = autotune.timed(gfn, a, b, *extras)
            except Exception:
                # a reference arm that cannot build loses by forfeit
                # (inf keeps the explore phase bounded)
                gspmd_s = float("inf")
            autotune.observe(tune.key, "gspmd", gspmd_s)
        elif wire_d is not None and wire_d.explore:
            # wire explore round: the f32 ring (this `fn` — wm is "")
            # and both quantized rings run under measurement; the f32
            # result is returned, so numerics never depend on tuning
            # state.  First-sample compile walls are absorbed by the
            # per-arm min over explore_k samples.
            if hit:
                telemetry.program_hit(ring_fp)

            def run_for(wmx):
                if not wmx:
                    return fn(a, b, *extras)
                fnx = jit_shard_map_cached(
                    _build_ring, comm.mesh, spec._replace(wire=wmx)
                )
                return fnx(a, b, *extras)

            out = _wire.explore(wire_d, run_for)
        elif hit:
            # steady state: count the ledger hit and (sampled) wall-clock
            # the executable; the first call below traces+compiles, so
            # its wall would pollute min/p50 and is left unmeasured.
            # A tuned winner keeps being watched through the sampled
            # observer — the degradation guard that re-explores a ring
            # gone >2x slower than its recorded best.  A wire-armed
            # dispatch feeds BOTH watches: the ring entry and the wire
            # entry each see the measured wall.
            telemetry.program_hit(ring_fp)
            obs_list = []
            if tune is not None:
                obs_list.append(
                    functools.partial(autotune.observe, tune.key, "ring")
                )
            if wm and wire_d is not None:
                obs_list.append(
                    functools.partial(autotune.observe, wire_d.key, wire_arm)
                )
            observer = (
                (lambda dur_s: [o(dur_s) for o in obs_list])
                if obs_list else None
            )
            out = telemetry.timed_call(
                ring_fp, fn, a, b, *extras, observer=observer
            )
        else:
            out = fn(a, b, *extras)
    if wm:
        _wire.account(
            "ring_" + case, wire_arm, bps * (S_ - 1), wire_total(wm)
        )
    memtrack.register_buffer(out, tag="output", split=out_split)
    sanitize.collective_event(
        "ring_" + case, axis=str(comm.split_axis), site="overlap.matmul_raw"
    )
    _record(
        "ring_" + case, steps=comm.size, bps=bps, out_split=out_split,
        reason=reason, cache_hit=hit,
    )
    # ledger the ring program with the overlap cost model's own numbers:
    # GEMM FLOPs plus the mandatory HBM traffic (operands + result once —
    # the per-step wire bytes are ICI, not HBM)
    if not hit and ring_fp is not None:
        extra_kw = {}
        if wm:
            extra_kw = dict(
                wire=wm,
                logical_bytes=float(bps * (S_ - 1)),
                wire_bytes=float(wire_total(wm)),
            )
        telemetry.record_program(
            ring_fp,
            kind="ring_matmul",
            ops=1 + len(steps),
            flops=2.0 * m * k * n,
            hbm_bytes=float(
                (m * k + k * n) * comp.itemsize + m * n * acc_isz
            ),
            mesh={"devices": comm.size},
            schedule="ring_" + case,
            bytes_per_step=bps,
            dtype=str(comp),
            **extra_kw,
        )
    return out


def matmul(a, b, out_split="auto", *, epilogue: Optional[Epilogue] = None,
           precision=None, exact: bool = False):
    """Eager DNDarray entry: ring-dispatch ``a @ b`` (2-D), returning the
    result DNDarray — or ``None`` when the dispatcher picks GSPMD (the
    caller falls back to the einsum path, keeping this function decline-
    safe).  ``out_split="auto"`` follows the reference convention
    (row-split a → 0, col-split b → 1, inner split → replicated); the
    ``rs`` case honors any explicit request directly."""
    from ..core import types as _types
    from ..core.dndarray import DNDarray

    if a.ndim != 2 or b.ndim != 2 or a.comm.mesh != b.comm.mesh:
        _record("gspmd", reason="layout")
        return None
    if out_split == "auto":
        out_split = 0 if a.split == 0 else (1 if b.split == 1 else None)
    promoted = _types.promote_types(a.dtype, b.dtype)
    comp = jnp.dtype(promoted.jax_type())
    steps, extras = epilogue.lower() if epilogue is not None else ((), ())
    m, k = a.shape
    n = b.shape[1]
    if steps:
        _check_extras(extras, (m, n), out_split)
        out_aval = jax.eval_shape(
            lambda a_, b_, *ex: _apply_steps(
                jnp.matmul(a_.astype(comp), b_.astype(comp)), steps, ex
            ),
            jax.ShapeDtypeStruct((m, k), a.parray.dtype),
            jax.ShapeDtypeStruct((k, n), b.parray.dtype),
            *extras,
        )
        if tuple(out_aval.shape) != (m, n):
            raise ValueError(
                f"epilogue changes the result shape to {out_aval.shape}"
            )
        out_dt = out_aval.dtype
    else:
        out_dt = comp
    out = matmul_raw(
        a.comm, a.parray, b.parray, (m, k), (k, n), a.split, b.split,
        out_split, comp_dtype=comp, epilogue=epilogue, precision=precision,
        exact=exact,
    )
    if out is None:
        return None
    return DNDarray(
        out, (m, n), _types.canonical_heat_type(out_dt), out_split,
        a.device, a.comm,
    )


# ------------------------------------------------- fusion chain terminator

def _mm(a, b):
    """The matmul node of the fusion DAG.  The eager body is authoritative:
    when the ring terminator declines (or fails), the generic fused program
    evaluates this under GSPMD and correctness never depends on the
    pattern match."""
    return jnp.matmul(a, b)


# chain ops that may ride the ring as epilogue steps: shape-preserving,
# value-wise — reductions/scans/composites force the generic program
_CHAIN_KINDS = {"elementwise", "cast", "comparison", "predicate"}

_REGISTERED = False


def ensure_registered() -> None:
    """Idempotently register ``_mm`` and the chain terminator with the
    fusion engine (lazy: parallel.overlap must stay importable before
    heat_tpu.core finishes initializing)."""
    global _REGISTERED
    if _REGISTERED:
        return
    from ..core import fusion

    fusion.register_op(_mm, "matmul", kind="matmul")
    fusion.register_terminator(_lower_chain, salt=_dispatch_salt)
    # tuned-mode flips (a winner resolving, a cache load, an enable
    # toggle) must build distinct fused programs — the autotune
    # generation joins every compile-cache key
    fusion.register_cache_salt(autotune.salt)
    _REGISTERED = True


def _split_of(value, mesh, axis) -> Optional[int]:
    sh = getattr(value, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh == mesh:
        for i, names in enumerate(sh.spec):
            if names == axis or (isinstance(names, tuple) and axis in names):
                return i
    return None


def _chain_operand(instrs, c):
    """A ``_mm`` operand slot: a leaf, optionally through one fused
    operand cast.  → ``(leaf_index, cast_dtype_or_None)`` or None."""
    from ..core import fusion

    ins = instrs[c]
    if ins[0] == "L":
        return ins[1], None
    _, fn, kw, ch = ins
    if fn is fusion._astype and len(ch) == 1 and instrs[ch[0]][0] == "L":
        return instrs[ch[0]][1], jnp.dtype(dict(kw)["dtype"])
    return None


def _lower_chain(instrs, leaves, out_slot, lshapes, gshape, split, comm,
                 target, with_guard):
    """Fusion-cache lowerer: recognize ``epilogue(...(_mm(a, b)))`` chains
    and return a replacement program running the ring engine, with the
    whole elementwise tail fused into the ring step.  Returns ``None`` to
    decline (generic GSPMD program takes over)."""
    from ..core import fusion

    if len(gshape) != 2:
        return None
    if not any(ins[0] == "O" and ins[1] is _mm for ins in instrs):
        return None
    # walk root → _mm, collecting the elementwise tail
    tail = []
    slot = out_slot
    while True:
        ins = instrs[slot]
        if ins[0] != "O":
            return None
        _, fn, kw, ch = ins
        if fn is _mm:
            mm_ch = ch
            break
        meta = fusion._OP_TABLE.get(fn)
        if meta is None or meta[1] not in _CHAIN_KINDS:
            return None
        nxt = {c for c in ch if instrs[c][0] == "O"}
        if len(nxt) != 1:
            return None
        tail.append((fn, kw, ch, slot))
        slot = nxt.pop()
    if len(mm_ch) != 2:
        return None
    opa = _chain_operand(instrs, mm_ch[0])
    opb = _chain_operand(instrs, mm_ch[1])
    if opa is None or opb is None:
        return None
    ia, cast_a = opa
    ib, cast_b = opb
    la, lb = lshapes[ia], lshapes[ib]
    if len(la) != 2 or len(lb) != 2 or la[1] != lb[0]:
        return None
    m, k = la
    n = lb[1]
    if tuple(gshape) != (m, n):
        return None
    mesh, axis, S = comm.mesh, comm.split_axis, comm.size
    a_val, b_val = leaves[ia].value, leaves[ib].value
    a_split = _split_of(a_val, mesh, axis)
    b_split = _split_of(b_val, mesh, axis)
    case = _classify(a_split, b_split)
    if case is None:
        _record("gspmd", out_split=split, reason="layout")
        return None
    # physical layout sanity: the kernel's block algebra needs the
    # even-chunk pad on the split dim
    for v, ls, sp in ((a_val, la, a_split), (b_val, lb, b_split)):
        if v.shape[sp] != _ceil_mult(ls[sp], S) or v.shape[1 - sp] != ls[1 - sp]:
            return None
    comp = jnp.promote_types(cast_a or a_val.dtype, cast_b or b_val.dtype)
    acc_isz = 4 if (jnp.issubdtype(comp, jnp.inexact) and comp.itemsize < 4) else comp.itemsize
    use, reason, bps = _decide(case, split, m, k, n, S, comp.itemsize, acc_isz)
    # the chain path CONSUMES tuning state, it never explores: running
    # both arms inside a fused program would double-execute the whole
    # chain.  An eager explore on the same GEMM geometry warms this
    # lookup (the key deliberately excludes the epilogue); until then the
    # static threshold verdict stands, recorded as the prior.  The
    # autotune generation rides the fusion compile-cache key
    # (register_cache_salt in ensure_registered), so a winner resolving
    # later rebuilds this chain instead of reusing the stale executable.
    if (
        reason not in _RING_IMPOSSIBLE
        and _mode() == "auto"
        and autotune.enabled()
    ):
        key = autotune.matmul_key(case, split, m, k, n, S, str(comp))
        w = autotune.winner(key)
        if w is not None:
            use, reason = w == "ring", "autotune:cached"
        else:
            autotune.note_prior(key, "ring" if use else "gspmd")
    if not use:
        _record("gspmd", bps=bps, out_split=split, reason=reason)
        return None
    # bottom-up epilogue: each tail op becomes a ring step; its leaf
    # operands become runtime extras (dim checks: ≤2-D, broadcast extents)
    steps = []
    extra_of = {}   # leaf index -> extras position
    extra_shapes = []
    chain_slot = slot  # the _mm slot
    for fn, kw, ch, op_slot in reversed(tail):
        pat = []
        for c in ch:
            if c == chain_slot:
                pat.append(-1)
                continue
            ins_c = instrs[c]
            if ins_c[0] != "L":
                return None
            li = ins_c[1]
            es = lshapes[li]
            if len(es) > 2:
                return None
            off = 2 - len(es)
            if any(es[i] not in (1, gshape[i + off]) for i in range(len(es))):
                return None
            if li not in extra_of:
                extra_of[li] = len(extra_shapes)
                extra_shapes.append(es)
            pat.append(extra_of[li])
        steps.append((fn, kw or (), tuple(pat)))
        chain_slot = op_slot
    steps = tuple(steps)
    extra_axes = _extra_axes(extra_shapes, gshape, split)
    # wire consult (consume-only, like the ring-vs-GSPMD one above): a
    # chain only serves forced modes or winners the eager entry already
    # resolved on the SAME ("ring_<case>", geometry) key.  Guard-folded
    # chains decline statically — the fold's finiteness verdict must
    # describe the caller's numbers, not the quantized hops.
    wire_m = ""
    if case in ("ag", "col"):
        if with_guard:
            _wire.decline("ring_fold")
        else:
            kb_ = _ceil_mult(k, S) // S
            bps_w = (kb_ * n if case == "ag" else m * kb_) * comp.itemsize
            if _wire.eligible(comp, bps_w * (S - 1)):
                wire_m = _wire.consume(
                    "ring_" + case, (m, k, n, S, str(comp))
                )
    elif case == "rs":
        _wire.decline("ring_rs")
    spec = _spec_for(
        comm, case, split, m, k, n, comp, steps, extra_axes, None,
        fold=with_guard, wire=wire_m,
    )
    kern = _build_ring(mesh, spec)
    if wire_m:
        kb_ = _ceil_mult(k, S) // S
        elems = (kb_ * n if case == "ag" else m * kb_) * (S - 1)
        _wire.account(
            "ring_" + case, "wire_" + wire_m,
            (kb_ * n if case == "ag" else m * kb_) * comp.itemsize * (S - 1),
            _wire.payload_nbytes(elems, kb_ * (S - 1), wire_m),
        )
    extra_leaf_idx = tuple(extra_of)
    _record(
        "ring_" + case, steps=S, bps=bps, out_split=split, reason=reason,
    )

    def program(*vals):
        ex = []
        for li in extra_leaf_idx:
            v = vals[li]
            ls = lshapes[li]
            if tuple(v.shape) != ls:
                v = v[tuple(slice(0, d) for d in ls)]
            ex.append(v)
        return kern(vals[ia], vals[ib], *ex)

    return program
