"""K-Medoids clustering (reference: heat/cluster/kmedoids.py, 150 LoC).

Reference semantics (kmedoids.py:56): the new center of cluster i is the data
point closest to the median of the points assigned to i; iteration stops when
the centers stop moving (tol = 0)."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import types
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMedoids"]


class KMedoids(_KCluster):
    """K-Medoids: centers snap to actual data points (reference: kmedoids.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedoids++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.cdist(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Median per cluster, then snap to the nearest sample (reference:
        kmedoids.py:56-110)."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        old = self._cluster_centers.larray.astype(arr.dtype)
        mask = labels[:, None] == jnp.arange(self.n_clusters)[None, :]
        masked = jnp.where(mask[:, :, None], arr[:, None, :], jnp.nan)
        med = jnp.nanmedian(masked, axis=0)  # (k, f)
        counts = jnp.sum(mask, axis=0)
        med = jnp.where(counts[:, None] > 0, med, old)
        # snap each median to the closest actual data point (the medoid)
        x2 = jnp.sum(arr * arr, axis=1)[:, None]
        m2 = jnp.sum(med * med, axis=1)[None, :]
        d2 = x2 + m2 - 2.0 * jnp.matmul(arr, med.T)  # (n, k)
        idx = jnp.argmin(d2, axis=0)  # (k,)
        new = arr[idx]
        new = jnp.where(counts[:, None] > 0, new, old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    def fit(self, x: DNDarray) -> "KMedoids":
        """Iterate until the medoids stop changing (reference: kmedoids.py fit)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        self._initialize_cluster_centers(x)
        self._n_iter = 0
        for _ in range(self.max_iter):
            labels = self._assign_to_cluster(x)
            new_centers = self._update_centroids(x, labels)
            unchanged = bool(jnp.all(new_centers.larray == self._cluster_centers.larray))
            self._cluster_centers = new_centers
            self._n_iter += 1
            if unchanged:
                break
        self._labels = self._assign_to_cluster(x)
        return self
