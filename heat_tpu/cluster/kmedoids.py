"""K-Medoids clustering (reference: heat/cluster/kmedoids.py, 150 LoC).

Reference semantics (kmedoids.py:56): the new center of cluster i is the data
point closest to the median of the points assigned to i; iteration stops when
the centers stop moving (tol = 0)."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import types
from ..spatial import distance
from . import _kcluster
from ._kcluster import _KCluster

__all__ = ["KMedoids"]


class KMedoids(_KCluster):
    """K-Medoids: centers snap to actual data points (reference: kmedoids.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedoids++":
            init = "probability_based"
        super().__init__(
            # the reference's KMedoids assigns by Manhattan distance
            # (kmedoids.py:48), matching the L1 assignment in _median_loop
            metric=lambda x, y: distance.manhattan(x, y, expand=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Median per cluster, then snap to the nearest sample (reference:
        kmedoids.py:56-110)."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        old = self._cluster_centers.larray.astype(arr.dtype)
        med = _kcluster._masked_medians(arr, labels, self.n_clusters, old)
        counts = jnp.sum(
            labels[:, None] == jnp.arange(self.n_clusters)[None, :], axis=0
        )
        # snap each median to the closest actual data point (the medoid)
        d2 = _kcluster.ops_cdist(arr, med, sqrt=False)  # (n, k)
        idx = jnp.argmin(d2, axis=0)  # (k,)
        new = jnp.where(counts[:, None] > 0, arr[idx], old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    def fit(self, x: DNDarray) -> "KMedoids":
        """Iterate until the medoids stop changing, in one on-device XLA loop
        (reference: kmedoids.py fit)."""
        return self._fit_median_loop(x, snap_to_sample=True)
