"""K-Medians clustering (reference: heat/cluster/kmedians.py, 137 LoC).

Same skeleton as KMeans with an L1 metric and per-cluster median updates
(reference: kmedians.py:57 masks assigned points and medians them; here the
mask becomes a NaN-select + nanmedian, one XLA program)."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import types
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMedians"]


class KMedians(_KCluster):
    """K-Medians (Manhattan metric, median update; reference: kmedians.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedians++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.manhattan(x, y, expand=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Per-cluster masked median (reference: kmedians.py:57)."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        old = self._cluster_centers.larray.astype(arr.dtype)
        # (n, k, f) NaN-masked view; nanmedian reduces the sample axis
        mask = labels[:, None] == jnp.arange(self.n_clusters)[None, :]
        masked = jnp.where(mask[:, :, None], arr[:, None, :], jnp.nan)
        med = jnp.nanmedian(masked, axis=0)
        counts = jnp.sum(mask, axis=0)
        new = jnp.where(counts[:, None] > 0, med, old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    def fit(self, x: DNDarray) -> "KMedians":
        """Iterate assignment + median update until the centroid shift is
        below tol (reference: kmedians.py fit)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        self._initialize_cluster_centers(x)
        self._n_iter = 0
        for _ in range(self.max_iter):
            labels = self._assign_to_cluster(x)
            new_centers = self._update_centroids(x, labels)
            shift = float(jnp.sum((new_centers.larray - self._cluster_centers.larray) ** 2))
            self._cluster_centers = new_centers
            self._n_iter += 1
            if shift <= self.tol:
                break
        self._labels = self._assign_to_cluster(x)
        return self
