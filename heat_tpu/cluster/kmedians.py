"""K-Medians clustering (reference: heat/cluster/kmedians.py, 137 LoC).

Same skeleton as KMeans with an L1 metric and per-cluster median updates
(reference: kmedians.py:57 masks assigned points and medians them; here the
mask becomes a NaN-select + nanmedian, one XLA program)."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import types
from ..spatial import distance
from . import _kcluster
from ._kcluster import _KCluster

__all__ = ["KMedians"]


class KMedians(_KCluster):
    """K-Medians (Manhattan metric, median update; reference: kmedians.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmedians++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.manhattan(x, y, expand=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Per-cluster masked median (reference: kmedians.py:57). Exposed for
        API parity; ``fit`` uses the fused on-device loop."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        old = self._cluster_centers.larray.astype(arr.dtype)
        new = _kcluster._masked_medians(arr, labels, self.n_clusters, old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    def fit(self, x: DNDarray) -> "KMedians":
        """Iterate assignment + median update until the centroid shift is
        below tol, in one on-device XLA loop (reference: kmedians.py fit)."""
        return self._fit_median_loop(x, snap_to_sample=False)
