"""K-Means clustering (reference: heat/cluster/kmeans.py, 139 LoC).

The reference's Lloyd iteration issues one Allreduce per cluster per step for
the masked sums (kmeans.py:73-100).  Here the whole iteration — distance
matrix (quadratic expansion on the MXU), argmin, one-hot count/sum matmuls —
is a single jitted XLA program with one fused cross-device reduction
(SURVEY.md §3.4), the benchmark north-star workload.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import types
from ..ops.cdist import cdist as ops_cdist
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMeans"]


@partial(jax.jit, static_argnames=("k",))
def _lloyd_loop(x, centers, k: int, max_iter, tol):
    """Run Lloyd iterations until ``shift² <= tol`` or ``max_iter``, entirely
    on-device (``lax.while_loop``).  The reference reads the convergence
    scalar back to the host every iteration (kmeans.py:102-139, ``.item()``
    broadcast); through a remote TPU tunnel one readback costs ~100× an
    iteration's compute, so the whole loop is a single XLA program and the
    host sees only the final (centers, shift, inertia, n_iter)."""

    def cond(state):
        _, shift, _, it = state
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(state):
        centers, _, _, it = state
        new_centers, shift, inertia = _lloyd_step(x, centers, k)
        return new_centers, shift, inertia, it + 1

    # convergence scalars stay f32 whatever the data dtype: shift/inertia
    # come out of f32 distance accumulation, and a bf16 carry would both
    # mismatch the while_loop types and quantize the tol comparison
    init = (centers, jnp.array(jnp.inf, jnp.float32), jnp.array(0.0, jnp.float32), 0)
    return jax.lax.while_loop(cond, body, init)


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(x, centers, k: int):
    """One fused Lloyd iteration: returns (new_centers, shift², inertia).

    With ``x`` row-sharded and ``centers`` replicated, XLA compiles this to
    local MXU matmuls plus a single psum of the (k, f) sums and (k,) counts.
    """
    d2 = ops_cdist(x, centers, sqrt=False)
    labels = jnp.argmin(d2, axis=1)
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(x.dtype)
    # counts/sums accumulate in f32 whatever the data dtype: a bf16
    # accumulator drops counts by ~0.2% at 4e5 members and skews centroids
    # (the 0/1 products are exact, only the accumulator needs width)
    counts = jnp.sum(onehot, axis=0, dtype=jnp.float32)
    sums = jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None], centers.astype(jnp.float32)
    ).astype(centers.dtype)
    shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
    # distance to the assigned (= nearest) centroid is the row minimum; a
    # take_along_axis gather here costs ~20x the rest of the step on TPU
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return new_centers, shift, inertia


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference: kmeans.py:13).

    Parameters mirror the reference: ``n_clusters``, ``init`` ("random",
    "kmeans++"/"probability_based", or explicit centroids), ``max_iter``,
    ``tol`` (convergence on squared centroid shift), ``random_state``.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmeans++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.cdist(x, y, quadratic_expansion=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Masked-mean centroid update (reference: kmeans.py:73). Exposed for
        API parity; ``fit`` uses the fused step."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        onehot = (labels[:, None] == jnp.arange(self.n_clusters)[None, :]).astype(arr.dtype)
        counts = jnp.sum(onehot, axis=0)
        sums = jnp.matmul(onehot.T, arr)
        old = self._cluster_centers.larray
        new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None], old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    def fit(self, x: DNDarray) -> "KMeans":
        """Lloyd iterations until centroid shift < tol (reference:
        kmeans.py:102-139)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        self._initialize_cluster_centers(x)

        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        centers = self._cluster_centers.larray.astype(arr.dtype)

        centers, _, inertia, n_iter = _lloyd_loop(
            arr, centers, self.n_clusters, self.max_iter, self.tol
        )
        self._n_iter = int(n_iter)

        self._cluster_centers = DNDarray(
            centers, tuple(centers.shape), types.canonical_heat_type(centers.dtype),
            None, x.device, x.comm,
        )
        self._labels = self._assign_to_cluster(x)
        self._inertia = float(inertia)
        return self
