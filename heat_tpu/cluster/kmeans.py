"""K-Means clustering (reference: heat/cluster/kmeans.py, 139 LoC).

The reference's Lloyd iteration issues one Allreduce per cluster per step for
the masked sums (kmeans.py:73-100).  Here the whole iteration — distance
matrix (quadratic expansion on the MXU), argmin, one-hot count/sum matmuls —
is a single jitted XLA program with one fused cross-device reduction
(SURVEY.md §3.4), the benchmark north-star workload.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import memtrack, telemetry, types
from ..ops.cdist import cdist as ops_cdist
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMeans"]


# --- layout-API drift shims (jax>=0.6 renamed Layout→Format: arrays carry
# --- `.format`, executables `.input_formats`; 0.4/0.5 say `.layout` and
# --- `.input_layouts`, and the AUTO sentinel lives on Layout/DeviceLocalLayout)

def _fmt_of(x):
    """The array's device layout object (hashable on both API surfaces —
    the AOT caches key on it)."""
    fmt = getattr(x, "format", None)
    return fmt if fmt is not None else x.layout


def _auto_fmt():
    """An ``in_shardings`` entry meaning 'let the layout solver choose'."""
    try:
        from jax.experimental.layout import Format, Layout

        return Format(Layout.AUTO)
    except ImportError:
        from jax.experimental.layout import DeviceLocalLayout, Layout

        return Layout(DeviceLocalLayout.AUTO)


def _input_fmts(comp):
    """Per-argument formats of a compiled executable."""
    fmts = getattr(comp, "input_formats", None)
    return fmts if fmts is not None else comp.input_layouts


def _lloyd_while(step, centers, max_iter, tol):
    """Shared convergence driver: iterate ``step`` until ``shift² <= tol``
    or ``max_iter``, entirely on-device (``lax.while_loop``).  The
    reference reads the convergence scalar back to the host every iteration
    (kmeans.py:102-139, ``.item()`` broadcast); through a remote TPU tunnel
    one readback costs ~100× an iteration's compute, so the whole loop is a
    single XLA program and the host sees only the final
    (centers, shift, inertia, n_iter)."""

    def cond(state):
        _, shift, _, it = state
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(state):
        centers, _, _, it = state
        new_centers, shift, inertia = step(centers)
        return new_centers, shift, inertia, it + 1

    # convergence scalars stay f32 whatever the data dtype: shift/inertia
    # come out of f32 distance accumulation, and a bf16 carry would both
    # mismatch the while_loop types and quantize the tol comparison
    init = (centers, jnp.array(jnp.inf, jnp.float32), jnp.array(0.0, jnp.float32), 0)
    return jax.lax.while_loop(cond, body, init)


@partial(jax.jit, static_argnames=("k",))
def _lloyd_loop(x, centers, k: int, max_iter, tol):
    """Lloyd iterations over unpacked data (see :func:`_lloyd_while`)."""
    return _lloyd_while(
        lambda c: _lloyd_step(x, c, k), centers, max_iter, tol
    )


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(x, centers, k: int):
    """One fused Lloyd iteration: returns (new_centers, shift², inertia).

    With ``x`` row-sharded and ``centers`` replicated, XLA compiles this to
    local MXU matmuls plus a single psum of the (k, f) sums and (k,) counts.
    """
    d2 = ops_cdist(x, centers, sqrt=False)
    labels = jnp.argmin(d2, axis=1)
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(x.dtype)
    # counts/sums accumulate in f32 whatever the data dtype: a bf16
    # accumulator drops counts by ~0.2% at 4e5 members and skews centroids
    # (the 0/1 products are exact, only the accumulator needs width)
    counts = jnp.sum(onehot, axis=0, dtype=jnp.float32)
    sums = jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None], centers.astype(jnp.float32)
    ).astype(centers.dtype)
    shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
    # distance to the assigned (= nearest) centroid is the row minimum; a
    # take_along_axis gather here costs ~20x the rest of the step on TPU
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return new_centers, shift, inertia


@partial(jax.jit, static_argnames=("k",))
def _stream_lloyd_stats(x, valid, centers, k: int):
    """Per-slab Lloyd sufficient statistics for the out-of-core path:
    (masked counts, masked sums, masked inertia) against FIXED centers.

    Same math as :func:`_lloyd_step` — f32 count/sum accumulation, row-min
    inertia — restricted to rows ``[0, valid)`` (the streaming engine
    zero-pads slab tails to keep one compiled bucket per pass; ``valid``
    arrives as a Python int and traces as a weak scalar, so tail slabs hit
    the same executable).  The center UPDATE happens host-side in
    ``fit_stream`` after all slabs of a pass are folded together."""
    x = x.astype(centers.dtype)
    d2 = ops_cdist(x, centers, sqrt=False)
    labels = jnp.argmin(d2, axis=1)
    mask = jnp.arange(x.shape[0]) < valid
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(x.dtype)
    onehot = onehot * mask[:, None].astype(x.dtype)
    counts = jnp.sum(onehot, axis=0, dtype=jnp.float32)
    sums = jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    inertia = jnp.sum(jnp.where(mask, jnp.min(d2, axis=1), 0.0))
    return counts, sums, inertia


@partial(jax.jit, static_argnames=("k", "p", "with_inertia"))
def _lloyd_loop_packed(x2, sq, valid, centers, k: int, p: int, max_iter, tol,
                       with_inertia: bool = True):
    """Lloyd loop over lane-packed data.

    Sub-128-lane bf16 rows read f32-sized HBM on this chip (layout
    ``T(8,128)(2,1)`` pads the minor dim to 128 lanes — see
    docs/PERFORMANCE.md).  Packing ``p = 128//f`` samples per 128-lane row
    (``x2``: (n/p, 128)) makes every pass over the data read the packed
    bytes: the cross term is one matmul against a block-diagonal centroid
    matrix (slot s's columns see only feature block s), and the masked
    centroid sums slice slot s's feature block out of ``one_hot_sᵀ @ x2``.
    FLOPs grow p-fold on the cross term but the step is memory-bound at
    small k, so halved traffic wins.  ``sq`` carries per-slot ``|x|²``
    (n/p, p) f32; ``valid`` masks the zero-padded tail slots.
    """

    f = x2.shape[1] // p

    def step(centers):
        cT = centers.astype(x2.dtype).T  # (f, k)
        w = jnp.zeros((p * f, p * k), x2.dtype)
        for s in range(p):
            w = jax.lax.dynamic_update_slice(w, cT, (s * f, s * k))
        # (n/p, p*k): slot s's distances live in columns [s*k, (s+1)*k)
        cross = jax.lax.dot_general(
            x2, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        cn2 = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)
        # all slots at once: (n/p, p, k) distances, slot-major one-hots.
        # |x|^2 shifts every cluster equally, so the argmin only needs
        # m2 = |c|^2 - 2<x,c>; the full d2 (clamped at 0 like ops_cdist —
        # f32 rounding near centroids can dip negative) is built only
        # when the caller wants the per-iteration inertia
        m2 = cn2[None, None, :] - 2.0 * cross.reshape(-1, p, k)
        labels = jnp.argmin(m2, axis=2)  # (n/p, p)
        vf = valid[..., None].astype(x2.dtype)
        oh = (labels[..., None] == jnp.arange(k)[None, None, :]).astype(x2.dtype) * vf
        counts = jnp.sum(oh, axis=(0, 1), dtype=jnp.float32)
        if with_inertia:
            d2min = jnp.maximum(sq + jnp.min(m2, axis=2), 0.0)
            inertia = jnp.sum(d2min * valid)
        else:
            inertia = jnp.array(0.0, jnp.float32)
        # ONE masked-sum matmul for every slot: a per-slot dot would read
        # x2 p times and hand the traffic win straight back
        all_sums = jax.lax.dot_general(
            oh.reshape(-1, p * k), x2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (p*k, p*f); slot s's contribution is its diagonal block
        sums = jnp.zeros((k, f), jnp.float32)
        for s in range(p):
            sums = sums + jax.lax.dynamic_slice(all_sums, (s * k, s * f), (k, f))
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1)[:, None],
            centers.astype(jnp.float32),
        ).astype(centers.dtype)
        shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
        return new_centers, shift, inertia

    return _lloyd_while(step, centers, max_iter, tol)


def _lloyd_loop_packed_blocked_impl(x2, centers, k: int, p: int, n: int, blk: int, max_iter, tol):
    """Packed Lloyd loop with ROW-BLOCKED accumulation, for data near the
    HBM ceiling (the 1e8x64 bf16 north-star: the payload alone is 12.8 GB
    of a 16 GB chip, so whole-array f32 temporaries — cross (rows, p*k),
    d2 (rows, p, k), even the (rows, p) |x|² — cannot exist).  Each Lloyd
    iteration runs a ``fori_loop`` over row blocks carrying only the
    (k, f) sums, (k,) counts and scalar inertia; per-slot |x|² and the
    validity mask are computed per block and never materialize globally.
    One extra read of each block (the |x|² pass fuses into the same
    sweep), temporaries capped at ~blk * p * k floats.

    Compile through :func:`_lloyd_loop_packed_blocked` (AOT with AUTO
    layouts): under jit's default pinned layouts XLA's layout assignment
    relayouts the ENTIRE x2 parameter into a column-major while-state
    copy — an 11.9 GB HLO temp at n=1e8, reproducibly gone when the
    layout solver is free (probed both ways on the v5e; temps drop
    27 GB → 1.6 GB and the chosen x2 layout is the default row-major)."""
    rows, pf = x2.shape
    f = pf // p
    nb = -(-rows // blk)

    def step(centers):
        cT = centers.astype(x2.dtype).T
        w = jnp.zeros((p * f, p * k), x2.dtype)
        for s in range(p):
            w = jax.lax.dynamic_update_slice(w, cT, (s * f, s * k))
        cn2 = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)

        def body(i, carry):
            sums, counts = carry
            # dynamic_slice clamps the start: the last block re-reads
            # earlier rows, so mask rows below this block's true start
            start = jnp.minimum(i * blk, rows - blk)
            xb = jax.lax.dynamic_slice_in_dim(x2, start, blk, 0)
            # NO optimization barrier here: with the slimmed body the
            # layout solver keeps the payload's natural orientation and
            # fuses the slice into its consumers (compile-reported temps
            # 0.02 GB); the earlier fuller body needed a barrier to stop
            # a transpose-hoist of the whole payload — re-probe if ops
            # are added back
            gsl = (start * p) + jnp.arange(blk * p)
            vb = ((gsl < n) & (gsl >= i * blk * p)).astype(jnp.float32)
            vb = vb.reshape(blk, p)
            # m2[j] = |c_j|^2 - 2<x, c_j> has the same argmin as d^2: the
            # per-sample |x|^2 shifts every cluster equally, so neither
            # the labels nor the convergence check need it — the profiled
            # per-iteration |x|^2 pass (convert+square+reduce, ~59 ms of
            # a 169 ms iteration at n=1e8) is gone; fit computes the
            # final inertia once in the labels pass
            cross = jax.lax.dot_general(
                xb, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(blk, p, k)
            m2 = cn2[None, None, :] - 2.0 * cross
            labels = jnp.argmin(m2, axis=2)
            oh = (labels[..., None] == jnp.arange(k)[None, None, :]).astype(
                x2.dtype
            ) * vb[..., None].astype(x2.dtype)
            counts = counts + jnp.sum(
                oh.astype(jnp.float32), axis=(0, 1), dtype=jnp.float32
            )
            # transpose the BLOCK explicitly: contracting the row dim of
            # the slice directly makes layout assignment want the whole
            # x2 payload transposed — a wish that penetrates optimization
            # barriers and lands as an 11.9 GB relayout copy (verified
            # both ways); a per-block transposed temp satisfies the GEMM
            # locally
            xbT = jnp.swapaxes(xb, 0, 1)
            all_sums = jax.lax.dot_general(
                oh.reshape(blk, p * k), xbT, (((0,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            for s in range(p):
                sums = sums + jax.lax.dynamic_slice(
                    all_sums, (s * k, s * f), (k, f)
                )
            return sums, counts

        sums, counts = jax.lax.fori_loop(
            0,
            nb,
            body,
            (
                jnp.zeros((k, f), jnp.float32),
                jnp.zeros((k,), jnp.float32),
            ),
        )
        # the loop reports inertia 0: its true value is only needed once,
        # after convergence — _fit_packed computes it in the labels pass
        inertia = jnp.array(0.0, jnp.float32)
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1)[:, None],
            centers.astype(jnp.float32),
        ).astype(centers.dtype)
        shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
        return new_centers, shift, inertia

    return _lloyd_while(step, centers, max_iter, tol)


@lru_cache(maxsize=None)
def _blocked_loop_compiled(rows, pf, dtype_str, k, p, n, blk, x2_format):
    """AOT-compile the blocked loop, baking in the payload's ACTUAL
    format (see the impl docstring for why the default pinned layouts
    OOM).  The slim loop body's layout solve prefers the payload's
    natural (generation-time) orientation, so no relayout copy appears;
    any layout the payload does not already have — whether jit's default
    or a free AUTO choice that happens to differ — costs a full-array
    relayout: 12.8 GB and the OOM at the north-star size.  Re-probe
    memory_analysis() both ways whenever the body changes."""
    dt = jnp.dtype(dtype_str)
    x2_s = jax.ShapeDtypeStruct((rows, pf), dt)
    c_s = jax.ShapeDtypeStruct((k, pf // p), dt)
    mi_s = jax.ShapeDtypeStruct((), jnp.int32)
    tol_s = jax.ShapeDtypeStruct((), jnp.float32)

    def fn(x2, centers, max_iter, tol):
        return _lloyd_loop_packed_blocked_impl(
            x2, centers, k, p, n, blk, max_iter, tol
        )

    jitted = jax.jit(
        fn,
        in_shardings=(
            x2_format,
            _auto_fmt(),
            _auto_fmt(),
            _auto_fmt(),
        ),
    )
    return jitted.lower(x2_s, c_s, mi_s, tol_s).compile()


def _lloyd_loop_packed_blocked(x2, centers, k, p, n, blk, max_iter, tol):
    """Run the blocked Lloyd loop through its AUTO-layout AOT executable;
    small inputs are device_put into the compiled formats (x2 is passed
    as-is: the executable is compiled for its exact sharding, and the
    probed AUTO layout choice for it is the default row-major)."""
    comp = _blocked_loop_compiled(
        x2.shape[0], x2.shape[1], str(x2.dtype), int(k), int(p), int(n),
        int(blk), _fmt_of(x2),
    )
    fmts = _input_fmts(comp)[0]
    small = [
        jnp.asarray(centers),
        jnp.asarray(max_iter, jnp.int32),
        jnp.asarray(tol, jnp.float32),
    ]
    args = [x2] + [jax.device_put(a, f) for a, f in zip(small, fmts[1:])]
    return comp(*args)


@partial(jax.jit, static_argnames=("p",))
def _pack_relayout(arr, p: int):
    """Pad + pack into (n/p, p*f).  Jitted so intermediates fuse (eagerly
    each op materializes and OOMs the exact large-n case packing exists
    for).  Kept separate from the |x|² reduce below: one program emitting
    both the relayout copy and the row reduce sends the TPU compiler into
    a multi-minute layout-assignment spiral (observed hang at n=1e7)."""
    n, f = arr.shape
    n2 = -(-n // p) * p
    if n2 != n:
        arr = jnp.pad(arr, ((0, n2 - n), (0, 0)))
    return arr.reshape(n2 // p, p * f)


@partial(jax.jit, static_argnames=("p",))
def _pack_rownorms(arr, p: int):
    """Per-slot |x|² (n/p, p) f32 and the validity mask, from the unpacked
    array (the convert+square fuses into the reduce — no f32 copy)."""
    n = arr.shape[0]
    n2 = -(-n // p) * p
    sq = jnp.sum(arr.astype(jnp.float32) ** 2, axis=1)
    if n2 != n:
        sq = jnp.pad(sq, (0, n2 - n))
    valid = (jnp.arange(n2).reshape(n2 // p, p) < n).astype(jnp.float32)
    return sq.reshape(n2 // p, p), valid


def _pack_kernel(arr, p: int):
    x2 = _pack_relayout(arr, p)
    sq, valid = _pack_rownorms(arr, p)
    return x2, sq, valid


def _pack_lanes(arr):
    """Pack ``p = 128//f`` samples per 128-lane row when profitable:
    returns ``(x2, sq, valid, f, p)`` or None when not applicable."""
    n, f = arr.shape
    if arr.dtype != jnp.bfloat16 or f >= 128 or 128 % f != 0:
        return None
    # the conversion holds the lane-padded source (2x logical bytes for
    # f=64) AND the packed copy; without headroom for both, fall back to
    # the unpacked loop rather than OOM — packing at ingest (loader level)
    # is the path for arrays near the HBM ceiling
    dev = next(iter(arr.devices()))
    # the array is sharded over the mesh: memory budgets are per device;
    # the unified reader reports the TIGHTEST device (None where the
    # backend has no stats — e.g. through remote TPU tunnels)
    n_dev = max(1, len(arr.devices()))
    need = arr.size * 2 // n_dev
    # THE budget formula (memtrack.suggest_budget, shared with transport's
    # informed retry and autotune's plan-time seeding): the packed copy
    # must fit free HBM minus a 1 GiB working-set reservation
    granted = memtrack.suggest_budget(need, fraction=1.0, headroom=1 << 30)
    if granted is not None:
        if granted < need:
            return None
    elif dev.platform == "tpu":
        # no stats: estimate — lane-padded source (n*128*2B) + packed copy
        # + loop temporaries must stay well under a 16 GB chip
        n_ = arr.shape[0]
        if n_ * (256 + 2 * arr.shape[1]) * 1.3 / n_dev > 12e9:
            return None
    p = 128 // f
    x2, sq, valid = _pack_kernel(arr, p)
    return x2, sq, valid, f, p


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference: kmeans.py:13).

    Parameters mirror the reference: ``n_clusters``, ``init`` ("random",
    "kmeans++"/"probability_based", or explicit centroids), ``max_iter``,
    ``tol`` (convergence on squared centroid shift), ``random_state``.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmeans++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.cdist(x, y, quadratic_expansion=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Masked-mean centroid update (reference: kmeans.py:73). Exposed for
        API parity; ``fit`` uses the fused step."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        onehot = (labels[:, None] == jnp.arange(self.n_clusters)[None, :]).astype(arr.dtype)
        counts = jnp.sum(onehot, axis=0)
        sums = None
        if x.split == 0 and x.comm.size > 1:
            # inner-split GEMM: the sample axis is the contraction — the ring
            # reduce-scatter schedule lands the (k, f) sums replicated without
            # the all-gather-then-dot GSPMD would emit (decline-safe)
            from ..parallel import overlap
            k_ = self.n_clusters
            sums = overlap.matmul_raw(
                x.comm, onehot.T, arr,
                (k_, x.shape[0]), (x.shape[0], x.shape[1]), 1, 0, None,
            )
        if sums is None:
            sums = jnp.matmul(onehot.T, arr)
        old = self._cluster_centers.larray
        new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None], old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    @telemetry.span("kmeans.fit")
    def fit(self, x) -> "KMeans":
        """Lloyd iterations until centroid shift < tol (reference:
        kmeans.py:102-139).  Also accepts :class:`packing.PackedSamples`
        (lane-packed ingest — the 1e8x64 bf16 north-star path)."""
        from ..core import sanitation
        from .packing import PackedSamples

        if isinstance(x, PackedSamples):
            return self._fit_packed(x)
        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        self._initialize_cluster_centers(x)

        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        centers = self._cluster_centers.larray.astype(arr.dtype)

        packed = _pack_lanes(arr)
        if packed is not None:
            x2, sq, valid, f, p = packed
            centers, _, inertia, n_iter = _lloyd_loop_packed(
                x2, sq, valid, centers, self.n_clusters, p,
                self.max_iter, self.tol,
            )
        else:
            centers, _, inertia, n_iter = _lloyd_loop(
                arr, centers, self.n_clusters, self.max_iter, self.tol
            )
        self._n_iter = int(n_iter)  # ht: HT002 ok — end-of-fit n_iter readback, one scalar per fit

        self._cluster_centers = DNDarray(
            centers, tuple(centers.shape), types.canonical_heat_type(centers.dtype),
            None, x.device, x.comm,
        )
        self._labels = self._assign_to_cluster(x)
        self._inertia = float(inertia)  # ht: HT002 ok — end-of-fit inertia readback, one scalar per fit
        return self

    # ------------------------------------------------------ packed-ingest path
    def _init_centers_packed(self, packed) -> jax.Array:
        """Initial centroids from lane-packed data (see packing.py).

        "random" mirrors the stratified draw of
        ``_KCluster._initialize_cluster_centers``; "kmeans++" seeds on a
        bounded sample prefix (2^18 samples) — at north-star scale an
        exact kmeans++ scan would read the full array k times for a
        seeding whose quality a large subsample matches statistically."""
        from ..core import random as ht_random

        if self.random_state is not None:
            ht_random.seed(self.random_state)
        k = self.n_clusters
        n, f, p = packed.n, packed.f, packed.p
        if n < k:
            raise ValueError(
                f"n_samples={n} should be >= n_clusters={k}"
            )
        x2 = packed.x2.parray

        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, f):
                raise ValueError("passed centroids do not match cluster count or data shape")
            return self.init.resplit(None).larray
        us = ht_random.rand(k, comm=packed.comm).larray.astype(jnp.float32)
        if isinstance(self.init, str) and self.init == "random":
            lo = jnp.arange(k) * (n // k)
            width = jnp.maximum(jnp.asarray(n // k), 1)
            idx = jnp.minimum(lo + (us * width).astype(jnp.int32), n - 1)
            return _gather_packed_samples(x2, idx, p, f, packed.comm)
        if isinstance(self.init, str) and self.init in ("probability_based", "kmeans++", "kmedians++"):
            from ._kcluster import _kmeanspp_init

            m_rows = min(x2.shape[0], (1 << 18) // p)
            sub = x2[:m_rows].reshape(-1, f)[: min(n, m_rows * p)]
            return _kmeanspp_init(sub, us, k)
        raise ValueError(f"unsupported init for packed data: {self.init!r}")

    def _fit_packed(self, packed) -> "KMeans":
        # the PHYSICAL payload: even row chunks over the mesh (trailing
        # pad rows' slots are >= n, so the validity masks drop them)
        x2 = packed.x2.parray
        centers = self._init_centers_packed(packed).astype(x2.dtype)
        if _use_blocked(x2):
            blk = min(x2.shape[0], _BLOCK_ROWS)
            centers, _, inertia, n_iter = _lloyd_loop_packed_blocked(
                x2, centers, self.n_clusters, packed.p, packed.n, blk,
                self.max_iter, self.tol,
            )
        else:
            # validity mask only — the per-slot |x|^2 pass would be dead
            # work here (with_inertia=False; inertia comes from the final
            # labels pass)
            rows = x2.shape[0]
            valid = (
                jnp.arange(rows * packed.p).reshape(rows, packed.p)
                < packed.n
            ).astype(jnp.float32)
            centers, _, inertia, n_iter = _lloyd_loop_packed(
                x2, jnp.zeros((1, 1), jnp.float32), valid, centers,
                self.n_clusters, packed.p, self.max_iter, self.tol,
                with_inertia=False,
            )
        self._n_iter = int(n_iter)  # ht: HT002 ok — end-of-fit n_iter readback, one scalar per fit
        self._cluster_centers = DNDarray(
            centers, tuple(centers.shape),
            types.canonical_heat_type(centers.dtype), None, packed.device,
            packed.comm,
        )
        # BOTH packed branches take inertia from the final labels pass —
        # distance to the FINAL centers (sklearn's inertia_ definition),
        # identical on either side of the blocked-path size threshold.
        # (The dense path keeps the reference's definition: the last
        # iteration's assignment distances, pre-update centers.)
        del inertia
        self._labels, inertia = self._predict_packed(packed, with_inertia=True)
        self._inertia = float(inertia)  # ht: HT002 ok — end-of-fit inertia readback, one scalar per fit
        return self

    def _predict_packed(self, packed, with_inertia: bool = False):
        """Labels (and optionally inertia) from packed data.  The blocked
        single-chip path engages only under the same _use_blocked guard
        as the fit loop; mesh-sharded payloads keep the GSPMD-friendly
        whole-array matmul."""
        x2 = packed.x2.parray
        if _use_blocked(x2):
            # half-size blocks when the inertia sweep rides along: it
            # adds per-block |x|^2 temps, and full _BLOCK_ROWS puts the
            # compile-reported peak within ~300 MB of the ceiling
            blk = _BLOCK_ROWS // 2 if with_inertia else _BLOCK_ROWS
            labels, inertia = _packed_labels_blocked(
                x2, self._cluster_centers.larray, packed.p, packed.n,
                min(x2.shape[0], blk), with_inertia=with_inertia,
            )
        else:
            labels, inertia = _packed_labels(
                x2, self._cluster_centers.larray, packed.p, packed.n,
                with_inertia=with_inertia,
            )
        out = DNDarray(
            labels, tuple(labels.shape),
            types.canonical_heat_type(labels.dtype), packed.split,
            packed.device, packed.comm,
        )
        return (out, inertia) if with_inertia else out

    def predict(self, x) -> DNDarray:
        from .packing import PackedSamples

        if isinstance(x, PackedSamples):
            return self._predict_packed(x)
        return super().predict(x)

    # ------------------------------------------------------ streaming path
    def _init_centers_stream(self, src, comm) -> jax.Array:
        """Initial centroids off a chunk source (bounded host reads only).

        Mirrors :meth:`_init_centers_packed`'s strategies: explicit
        centroids pass through; "random" is the stratified per-cluster
        draw, each chosen row host-read individually; "kmeans++" seeds on
        a bounded sample prefix (2^18 rows) — an exact scan would stream
        the whole array k times for a seeding a large subsample matches
        statistically."""
        import numpy as np

        from ..core import random as ht_random

        k = self.n_clusters
        n, f = src.shape
        if n < k:
            raise ValueError(f"n_samples={n} should be >= n_clusters={k}")
        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, f):
                raise ValueError(
                    "passed centroids do not match cluster count or data shape"
                )
            return self.init.resplit(None).larray.astype(jnp.float32)
        if self.random_state is not None:
            ht_random.seed(self.random_state)
        us = ht_random.rand(k, comm=comm).larray.astype(jnp.float32)
        if isinstance(self.init, str) and self.init == "random":
            width = max(n // k, 1)
            lo = np.arange(k) * (n // k)
            off = (np.asarray(us) * width).astype(np.int64)  # ht: HT002 ok — k uniforms read once at init
            idx = np.minimum(lo + off, n - 1)
            rows = np.concatenate([src.read(int(i), int(i) + 1) for i in idx])
            return jnp.asarray(rows, jnp.float32)
        if isinstance(self.init, str) and self.init in (
            "probability_based", "kmeans++", "kmedians++",
        ):
            from ._kcluster import _kmeanspp_init

            sub = jnp.asarray(src.read(0, min(n, 1 << 18)), jnp.float32)
            return _kmeanspp_init(sub, us, k)
        raise ValueError(f"unsupported init for streamed data: {self.init!r}")

    @telemetry.span("kmeans.fit_stream")
    def fit_stream(self, source, dataset: Optional[str] = None, *,
                   comm=None, budget: Optional[int] = None) -> "KMeans":
        """Exact multi-pass Lloyd over data that does not fit in HBM.

        Each Lloyd iteration is ONE streaming pass (core/stream.py):
        slabs arrive double-buffered under the residency budget, the
        jitted :func:`_stream_lloyd_stats` folds each into running
        (counts, sums, inertia) — compiled once per pass, the slab shape
        is fixed — and the center update + one scalar shift readback
        happen between passes.  The result is the same Lloyd fixed point
        as :meth:`fit` on the in-memory array (f32 accumulation; only
        the slab-wise summation order differs, so centroids agree to
        accumulation roundoff).  ``self.labels_`` stays ``None`` — a
        labels pass over out-of-core data is a separate full read the
        caller can run via chunked ``predict`` when actually wanted.

        ``source`` is anything :func:`heat_tpu.core.stream.open_source`
        accepts (HDF5/NetCDF path + ``dataset``, ``.npy``, ndarray, open
        ``ChunkSource``); ``budget`` overrides the measured residency
        budget in bytes."""
        import numpy as np

        from ..core import stream

        from ..parallel.mesh import sanitize_comm

        comm = sanitize_comm(comm)
        src = stream.open_source(source, dataset=dataset,
                                 np_dtype=np.float32)
        own = src is not source  # passthrough ChunkSource stays caller-owned
        try:
            if len(src.shape) != 2:
                raise ValueError(
                    f"input needs to be 2-D, but was {len(src.shape)}-D"
                )
            n, f = src.shape
            k = self.n_clusters
            centers = self._init_centers_stream(src, comm)
            inertia = 0.0
            self._n_iter = 0
            self.last_stream_report = None
            for _ in range(self.max_iter):
                pl = stream.plan_pass(src, comm=comm, site="kmeans_fit",
                                      budget=budget)
                sp = stream.StreamPass(src, comm=comm, plan=pl)
                counts = jnp.zeros((k,), jnp.float32)
                sums = jnp.zeros((k, f), jnp.float32)
                pass_inertia = jnp.zeros((), jnp.float32)
                for slab in sp:
                    c, s, i = _stream_lloyd_stats(
                        slab.x.larray, slab.valid, centers, k
                    )
                    counts = counts + c
                    sums = sums + s
                    pass_inertia = pass_inertia + i
                    del slab  # drop the loop reference: 3-slab residency cap
                rep = stream.finish_pass(sp)
                self.last_stream_report = dict(rep, arm=pl.arm,
                                               budget=pl.budget)
                fp = telemetry.fingerprint(
                    ("stream_kmeans", pl.slab_rows, f, k, comm.size)
                )
                telemetry.ensure_program(
                    fp, kind="stream_kmeans", dtype="float32",
                    flops=4.0 * n * f * k, hbm_bytes=float(n) * f * 4,
                )
                telemetry.record_timing(fp, rep["wall_s"])
                telemetry.annotate_program(
                    fp,
                    io_stall_frac=round(1.0 - rep["overlap_frac"], 4),
                    io_bytes=rep["bytes_read"],
                )
                new_centers = jnp.where(
                    counts[:, None] > 0,
                    sums / jnp.maximum(counts, 1)[:, None],
                    centers.astype(jnp.float32),
                ).astype(centers.dtype)
                shift = float(  # ht: HT002 ok — one convergence scalar per full-data pass
                    jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
                )
                centers = new_centers
                inertia = float(pass_inertia)  # ht: HT002 ok — rides the shift sync, last pass's value is inertia_
                self._n_iter += 1
                if shift <= self.tol:
                    break
        finally:
            if own:
                src.close()
        from ..core.devices import sanitize_device

        self._cluster_centers = DNDarray(
            centers, tuple(centers.shape),
            types.canonical_heat_type(centers.dtype), None,
            sanitize_device(None), comm,
        )
        # dense-path definition: last iteration's assignment distances
        # against pre-update centers (see fit); labels stay out-of-core
        self._inertia = inertia
        self._labels = None
        return self


# row-block size for the near-HBM-ceiling paths: temporaries per block
# stay in the hundreds of MB (2^23 rows already OOMs the compile at the
# north-star size); and the threshold above which whole-array f32
# temporaries (cross/d2 at rows*p*k floats) stop fitting next to the
# payload on a 16 GB chip
_BLOCK_ROWS = 1 << 21
_BLOCKED_BYTES = 4 << 30


def _use_blocked(x2) -> bool:
    """Blocked accumulation is the SINGLE-CHIP near-HBM-ceiling path; on a
    mesh, GSPMD already divides the whole-array loop's temporaries per
    device."""
    try:
        single = len(x2.devices()) == 1
    except Exception:
        single = True
    return single and x2.size * x2.dtype.itemsize > _BLOCKED_BYTES


def _packed_labels_blocked_impl(x2, centers, p: int, n: int, blk: int, with_inertia: bool = True):
    """Blocked nearest-centroid labels AND the total inertia (see
    _lloyd_loop_packed_blocked — the whole-array cross term cannot exist
    next to the payload; and inertia is only needed once, after
    convergence, so the per-sample |x|^2 lives here rather than in every
    Lloyd iteration).

    The label buffer is FLAT (rows*p,): a (rows, p) int32 array lane-pads
    p -> 128 under the TPU's T(8,128) tiling — 64x, a 25.6 GB buffer for
    400 MB of labels at the north-star size."""
    rows, pf = x2.shape
    f = pf // p
    k = centers.shape[0]
    nb = -(-rows // blk)
    cT = centers.astype(x2.dtype).T
    w = jnp.zeros((p * f, p * k), x2.dtype)
    for s in range(p):
        w = jax.lax.dynamic_update_slice(w, cT, (s * f, s * k))
    cn2 = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)

    def body(i, carry):
        out, inertia = carry
        start = jnp.minimum(i * blk, rows - blk)
        xb = jax.lax.dynamic_slice_in_dim(x2, start, blk, 0)
        gsl = (start * p) + jnp.arange(blk * p)
        vbf = ((gsl < n) & (gsl >= i * blk * p)).astype(jnp.float32)
        cross = jax.lax.dot_general(
            xb, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).reshape(blk, p, k)
        m2 = cn2[None, None, :] - 2.0 * cross
        lb = jnp.argmin(m2, axis=2).astype(jnp.int32)
        if with_inertia:
            sqb = jnp.sum(
                xb.reshape(blk * p, f).astype(jnp.float32) ** 2, axis=1
            )
            # d2 = |x|^2 + min m2, clamped at 0 per sample (f32 rounding
            # near centroids can dip negative)
            d2min = jnp.maximum(sqb + jnp.min(m2, axis=2).reshape(-1), 0.0)
            inertia = inertia + jnp.sum(d2min * vbf)
        # overlap from the clamped tail start rewrites identical values
        out = jax.lax.dynamic_update_slice(out, lb.reshape(-1), (start * p,))
        return out, inertia

    labels, inertia = jax.lax.fori_loop(
        0, nb, body,
        (jnp.zeros((rows * p,), jnp.int32), jnp.array(0.0, jnp.float32)),
    )
    return labels[:n], inertia


@lru_cache(maxsize=None)
def _labels_blocked_compiled(rows, pf, dtype_str, k, p, n, blk, x2_format, with_inertia):
    """AOT labels pass baking in the payload's actual format (same
    relayout-copy avoidance as :func:`_blocked_loop_compiled`).  The
    inertia sweep (an extra per-block |x|^2 pass) compiles in only when
    asked — predict wants labels alone."""
    dt = jnp.dtype(dtype_str)

    def fn(x2, centers):
        return _packed_labels_blocked_impl(
            x2, centers, p, n, blk, with_inertia
        )

    jitted = jax.jit(fn, in_shardings=(x2_format, _auto_fmt()))
    return jitted.lower(
        jax.ShapeDtypeStruct((rows, pf), dt),
        jax.ShapeDtypeStruct((k, pf // p), dt),
    ).compile()


def _packed_labels_blocked(x2, centers, p, n, blk, with_inertia=True):
    """Returns ``(labels (n,), inertia scalar)`` — inertia is 0 when
    ``with_inertia`` is off (labels-only predict path)."""
    comp = _labels_blocked_compiled(
        x2.shape[0], x2.shape[1], str(x2.dtype), int(centers.shape[0]),
        int(p), int(n), int(blk), _fmt_of(x2), bool(with_inertia),
    )
    fmts = _input_fmts(comp)[0]
    centers = jax.device_put(jnp.asarray(centers, x2.dtype), fmts[1])
    return comp(x2, centers)


@lru_cache(maxsize=None)
def _gather_rows_compiled(rows_phys, pf, dtype_str, kcount, blk, x2_format):
    """AOT blocked row gather over the packed payload.

    A direct ``jnp.take`` on the big payload relayouts/reshards the WHOLE
    operand (observed both as an sdy reshard copy and as a gather-layout
    copy — 11.9 GB either way at the north-star size).  The blocked
    pattern sidesteps every preference: ``fori`` over dynamic-sliced row
    blocks, a small per-block take, masked accumulate — the same
    structure as the blocked Lloyd loop, compiled with the payload's
    actual format baked in."""
    dt = jnp.dtype(dtype_str)
    nb = -(-rows_phys // blk)

    def fn(x2, ridx):
        def body(i, acc):
            start = jnp.minimum(i * blk, rows_phys - blk)
            xb = jax.lax.dynamic_slice_in_dim(x2, start, blk, 0)
            lpos = ridx - start
            # the clamped tail block re-reads earlier rows: only own rows
            # at/after this block's true start count
            owned = (lpos >= 0) & (lpos < blk) & (ridx >= i * blk)
            take = jnp.clip(lpos, 0, blk - 1)
            got = jnp.take(xb, take, axis=0) * owned[:, None].astype(dt)
            return acc + got

        return jax.lax.fori_loop(
            0, nb, body, jnp.zeros((kcount, pf), dt)
        )

    jitted = jax.jit(fn, in_shardings=(x2_format, _auto_fmt()))
    return jitted.lower(
        jax.ShapeDtypeStruct((rows_phys, pf), dt),
        jax.ShapeDtypeStruct((kcount,), jnp.int32),
    ).compile()


def _gather_packed_samples(x2, idx, p: int, f: int, comm):
    """Samples by global id from the packed layout: sample i is lanes
    [(i%p)*f, (i%p+1)*f) of row i//p (see :func:`_gather_rows_compiled`)."""
    blk = min(x2.shape[0], _BLOCK_ROWS)
    comp = _gather_rows_compiled(
        x2.shape[0], x2.shape[1], str(x2.dtype), int(idx.shape[0]), blk,
        _fmt_of(x2),
    )
    fmts = _input_fmts(comp)[0]
    ridx = jax.device_put((idx // p).astype(jnp.int32), fmts[1])
    rows = comp(x2, ridx).reshape(idx.shape[0], p, f)
    return jnp.take_along_axis(
        rows, (idx % p)[:, None, None].astype(jnp.int32), axis=1
    )[:, 0, :]


@partial(jax.jit, static_argnames=("p", "n"))
def _packed_stats(x2, p: int, n: int):
    """Per-slot |x|² (rows, p) f32 and validity mask, computed FROM the
    packed layout (the ingest path: the lane-padded source never exists)."""
    rows, pf = x2.shape
    f = pf // p
    x3 = x2.reshape(rows, p, f)
    sq = jnp.sum(x3.astype(jnp.float32) ** 2, axis=2)
    valid = (jnp.arange(rows * p).reshape(rows, p) < n).astype(jnp.float32)
    return sq, valid


@partial(jax.jit, static_argnames=("p", "n", "with_inertia"))
def _packed_labels(x2, centers, p: int, n: int, with_inertia: bool = False):
    """Nearest-centroid labels (flat (n,)) from packed data — one
    block-diagonal cross matmul, GSPMD-friendly for mesh-sharded
    payloads — plus the total inertia when asked (distance to these
    centers, sklearn's inertia_ definition)."""
    rows, pf = x2.shape
    f = pf // p
    k = centers.shape[0]
    cT = centers.astype(x2.dtype).T
    w = jnp.zeros((p * f, p * k), x2.dtype)
    for s in range(p):
        w = jax.lax.dynamic_update_slice(w, cT, (s * f, s * k))
    cross = jax.lax.dot_general(
        x2, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).reshape(rows, p, k)
    cn2 = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)
    m2 = cn2[None, None, :] - 2.0 * cross
    labels = jnp.argmin(m2, axis=2)
    if with_inertia:
        f = pf // p
        sq = jnp.sum(
            x2.reshape(rows * p, f).astype(jnp.float32) ** 2, axis=1
        )
        valid = (jnp.arange(rows * p) < n).astype(jnp.float32)
        d2min = jnp.maximum(sq + jnp.min(m2, axis=2).reshape(-1), 0.0)
        inertia = jnp.sum(d2min * valid)
    else:
        inertia = jnp.array(0.0, jnp.float32)
    # flat (n,) labels: a trailing length-1/length-p dim lane-pads to 128
    # under TPU tiling (see _packed_labels_blocked_impl)
    return labels.reshape(-1)[:n].astype(jnp.int32), inertia
