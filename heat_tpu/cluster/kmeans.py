"""K-Means clustering (reference: heat/cluster/kmeans.py, 139 LoC).

The reference's Lloyd iteration issues one Allreduce per cluster per step for
the masked sums (kmeans.py:73-100).  Here the whole iteration — distance
matrix (quadratic expansion on the MXU), argmin, one-hot count/sum matmuls —
is a single jitted XLA program with one fused cross-device reduction
(SURVEY.md §3.4), the benchmark north-star workload.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..core import types
from ..ops.cdist import cdist as ops_cdist
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMeans"]


def _lloyd_while(step, centers, max_iter, tol):
    """Shared convergence driver: iterate ``step`` until ``shift² <= tol``
    or ``max_iter``, entirely on-device (``lax.while_loop``).  The
    reference reads the convergence scalar back to the host every iteration
    (kmeans.py:102-139, ``.item()`` broadcast); through a remote TPU tunnel
    one readback costs ~100× an iteration's compute, so the whole loop is a
    single XLA program and the host sees only the final
    (centers, shift, inertia, n_iter)."""

    def cond(state):
        _, shift, _, it = state
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(state):
        centers, _, _, it = state
        new_centers, shift, inertia = step(centers)
        return new_centers, shift, inertia, it + 1

    # convergence scalars stay f32 whatever the data dtype: shift/inertia
    # come out of f32 distance accumulation, and a bf16 carry would both
    # mismatch the while_loop types and quantize the tol comparison
    init = (centers, jnp.array(jnp.inf, jnp.float32), jnp.array(0.0, jnp.float32), 0)
    return jax.lax.while_loop(cond, body, init)


@partial(jax.jit, static_argnames=("k",))
def _lloyd_loop(x, centers, k: int, max_iter, tol):
    """Lloyd iterations over unpacked data (see :func:`_lloyd_while`)."""
    return _lloyd_while(
        lambda c: _lloyd_step(x, c, k), centers, max_iter, tol
    )


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(x, centers, k: int):
    """One fused Lloyd iteration: returns (new_centers, shift², inertia).

    With ``x`` row-sharded and ``centers`` replicated, XLA compiles this to
    local MXU matmuls plus a single psum of the (k, f) sums and (k,) counts.
    """
    d2 = ops_cdist(x, centers, sqrt=False)
    labels = jnp.argmin(d2, axis=1)
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(x.dtype)
    # counts/sums accumulate in f32 whatever the data dtype: a bf16
    # accumulator drops counts by ~0.2% at 4e5 members and skews centroids
    # (the 0/1 products are exact, only the accumulator needs width)
    counts = jnp.sum(onehot, axis=0, dtype=jnp.float32)
    sums = jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None], centers.astype(jnp.float32)
    ).astype(centers.dtype)
    shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
    # distance to the assigned (= nearest) centroid is the row minimum; a
    # take_along_axis gather here costs ~20x the rest of the step on TPU
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return new_centers, shift, inertia


@partial(jax.jit, static_argnames=("k", "p"))
def _lloyd_loop_packed(x2, sq, valid, centers, k: int, p: int, max_iter, tol):
    """Lloyd loop over lane-packed data.

    Sub-128-lane bf16 rows read f32-sized HBM on this chip (layout
    ``T(8,128)(2,1)`` pads the minor dim to 128 lanes — see
    docs/PERFORMANCE.md).  Packing ``p = 128//f`` samples per 128-lane row
    (``x2``: (n/p, 128)) makes every pass over the data read the packed
    bytes: the cross term is one matmul against a block-diagonal centroid
    matrix (slot s's columns see only feature block s), and the masked
    centroid sums slice slot s's feature block out of ``one_hot_sᵀ @ x2``.
    FLOPs grow p-fold on the cross term but the step is memory-bound at
    small k, so halved traffic wins.  ``sq`` carries per-slot ``|x|²``
    (n/p, p) f32; ``valid`` masks the zero-padded tail slots.
    """

    f = x2.shape[1] // p

    def step(centers):
        cT = centers.astype(x2.dtype).T  # (f, k)
        w = jnp.zeros((p * f, p * k), x2.dtype)
        for s in range(p):
            w = jax.lax.dynamic_update_slice(w, cT, (s * f, s * k))
        # (n/p, p*k): slot s's distances live in columns [s*k, (s+1)*k)
        cross = jax.lax.dot_general(
            x2, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        cn2 = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)
        # all slots at once: (n/p, p, k) distances, slot-major one-hots;
        # clamp like ops_cdist does — f32 rounding across the three terms
        # can go slightly negative at/near centroids, and a negative
        # minimum would leak into the reported inertia
        d2 = jnp.maximum(
            sq[:, :, None] + cn2[None, None, :] - 2.0 * cross.reshape(-1, p, k),
            0.0,
        )
        labels = jnp.argmin(d2, axis=2)  # (n/p, p)
        vf = valid[..., None].astype(x2.dtype)
        oh = (labels[..., None] == jnp.arange(k)[None, None, :]).astype(x2.dtype) * vf
        counts = jnp.sum(oh, axis=(0, 1), dtype=jnp.float32)
        inertia = jnp.sum(jnp.min(d2, axis=2) * valid)
        # ONE masked-sum matmul for every slot: a per-slot dot would read
        # x2 p times and hand the traffic win straight back
        all_sums = jax.lax.dot_general(
            oh.reshape(-1, p * k), x2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (p*k, p*f); slot s's contribution is its diagonal block
        sums = jnp.zeros((k, f), jnp.float32)
        for s in range(p):
            sums = sums + jax.lax.dynamic_slice(all_sums, (s * k, s * f), (k, f))
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1)[:, None],
            centers.astype(jnp.float32),
        ).astype(centers.dtype)
        shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
        return new_centers, shift, inertia

    return _lloyd_while(step, centers, max_iter, tol)


@partial(jax.jit, static_argnames=("p",))
def _pack_relayout(arr, p: int):
    """Pad + pack into (n/p, p*f).  Jitted so intermediates fuse (eagerly
    each op materializes and OOMs the exact large-n case packing exists
    for).  Kept separate from the |x|² reduce below: one program emitting
    both the relayout copy and the row reduce sends the TPU compiler into
    a multi-minute layout-assignment spiral (observed hang at n=1e7)."""
    n, f = arr.shape
    n2 = -(-n // p) * p
    if n2 != n:
        arr = jnp.pad(arr, ((0, n2 - n), (0, 0)))
    return arr.reshape(n2 // p, p * f)


@partial(jax.jit, static_argnames=("p",))
def _pack_rownorms(arr, p: int):
    """Per-slot |x|² (n/p, p) f32 and the validity mask, from the unpacked
    array (the convert+square fuses into the reduce — no f32 copy)."""
    n = arr.shape[0]
    n2 = -(-n // p) * p
    sq = jnp.sum(arr.astype(jnp.float32) ** 2, axis=1)
    if n2 != n:
        sq = jnp.pad(sq, (0, n2 - n))
    valid = (jnp.arange(n2).reshape(n2 // p, p) < n).astype(jnp.float32)
    return sq.reshape(n2 // p, p), valid


def _pack_kernel(arr, p: int):
    x2 = _pack_relayout(arr, p)
    sq, valid = _pack_rownorms(arr, p)
    return x2, sq, valid


def _pack_lanes(arr):
    """Pack ``p = 128//f`` samples per 128-lane row when profitable:
    returns ``(x2, sq, valid, f, p)`` or None when not applicable."""
    n, f = arr.shape
    if arr.dtype != jnp.bfloat16 or f >= 128 or 128 % f != 0:
        return None
    # the conversion holds the lane-padded source (2x logical bytes for
    # f=64) AND the packed copy; without headroom for both, fall back to
    # the unpacked loop rather than OOM — packing at ingest (loader level)
    # is the path for arrays near the HBM ceiling
    dev = next(iter(arr.devices()))
    # the array is sharded over the mesh: memory budgets are per device
    n_dev = max(1, len(arr.devices()))
    stats = None
    try:
        stats = dev.memory_stats()  # None through remote TPU tunnels
    except Exception:
        pass
    free = None
    if stats:
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use")
        if limit is not None and in_use is not None:
            free = limit - in_use
    if free is not None:
        if free < arr.size * 2 // n_dev + (1 << 30):
            return None
    elif dev.platform == "tpu":
        # no stats: estimate — lane-padded source (n*128*2B) + packed copy
        # + loop temporaries must stay well under a 16 GB chip
        n_ = arr.shape[0]
        if n_ * (256 + 2 * arr.shape[1]) * 1.3 / n_dev > 12e9:
            return None
    p = 128 // f
    x2, sq, valid = _pack_kernel(arr, p)
    return x2, sq, valid, f, p


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference: kmeans.py:13).

    Parameters mirror the reference: ``n_clusters``, ``init`` ("random",
    "kmeans++"/"probability_based", or explicit centroids), ``max_iter``,
    ``tol`` (convergence on squared centroid shift), ``random_state``.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init == "kmeans++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.cdist(x, y, quadratic_expansion=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Masked-mean centroid update (reference: kmeans.py:73). Exposed for
        API parity; ``fit`` uses the fused step."""
        labels = matching_centroids.larray.reshape(-1)
        arr = x.larray
        onehot = (labels[:, None] == jnp.arange(self.n_clusters)[None, :]).astype(arr.dtype)
        counts = jnp.sum(onehot, axis=0)
        sums = jnp.matmul(onehot.T, arr)
        old = self._cluster_centers.larray
        new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1)[:, None], old)
        return DNDarray(
            new, tuple(new.shape), types.canonical_heat_type(new.dtype),
            None, x.device, x.comm,
        )

    def fit(self, x: DNDarray) -> "KMeans":
        """Lloyd iterations until centroid shift < tol (reference:
        kmeans.py:102-139)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        self._initialize_cluster_centers(x)

        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        centers = self._cluster_centers.larray.astype(arr.dtype)

        packed = _pack_lanes(arr)
        if packed is not None:
            x2, sq, valid, f, p = packed
            centers, _, inertia, n_iter = _lloyd_loop_packed(
                x2, sq, valid, centers, self.n_clusters, p,
                self.max_iter, self.tol,
            )
        else:
            centers, _, inertia, n_iter = _lloyd_loop(
                arr, centers, self.n_clusters, self.max_iter, self.tol
            )
        self._n_iter = int(n_iter)

        self._cluster_centers = DNDarray(
            centers, tuple(centers.shape), types.canonical_heat_type(centers.dtype),
            None, x.device, x.comm,
        )
        self._labels = self._assign_to_cluster(x)
        self._inertia = float(inertia)
        return self
