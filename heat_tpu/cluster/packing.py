"""Lane-packed sample ingest for sub-128-lane bf16 data.

On this TPU a bf16 array with minor dim f < 128 is laid out ``T(8,128)``:
the lane dim pads to 128, so bf16[n, 64] occupies f32-sized HBM and the
capacity win over f32 never materializes (docs/PERFORMANCE.md).  The
KMeans Lloyd loop has a packed variant (`kmeans._lloyd_loop_packed`) that
reads ``p = 128//f`` samples per 128-lane row; round 2 built that packed
layout *post hoc*, which needs the padded source AND the packed copy
resident at once — the exact reason the 1e8x64 north-star config could
not fit one chip (VERDICT round 2, weak #2).

This module builds the packed layout AT INGEST, so the lane-padded form
never exists.  The packed layout is nothing but the row-major bytes of
the logical (n, f) array viewed as (ceil(n/p), p*f) — sample ``i`` is
lanes ``[(i%p)*f, (i%p+1)*f)`` of row ``i//p`` — so a generator or
loader only has to *shape* its output differently:

- :func:`randn_packed` / :func:`rand_packed` sample the packed shape
  directly through the chunked block sampler (no f32 full-size
  intermediate, no lane padding ever),
- :func:`load_hdf5_packed` reshapes each host slab before it lands on
  device (core/io.py's slab-per-shard path, reference: io.py:57),
- :func:`pack` converts an existing DNDarray (the post-hoc path, still
  memory-gated).

``KMeans.fit``/``predict`` accept a :class:`PackedSamples` and drive the
packed Lloyd loop on it directly.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import random as ht_random
from ..core import types
from ..core.dndarray import DNDarray

__all__ = [
    "PackedSamples",
    "pack",
    "packable",
    "rand_packed",
    "randn_packed",
    "load_hdf5_packed",
]


def packable(f: int, dtype) -> bool:
    """Lane packing applies iff the dtype is bf16 and f divides 128."""
    return (
        types.canonical_heat_type(dtype) is types.bfloat16
        and f < 128
        and 128 % f == 0
    )


class PackedSamples:
    """A logical (n, f) sample matrix stored lane-packed as a
    ``(ceil(n/p), p*f)`` DNDarray (``p = 128 // f``); trailing slots of
    the last row are zero and masked out by consumers."""

    def __init__(self, x2: DNDarray, n: int, f: int):
        p = 128 // f
        expect_rows = -(-n // p)
        if x2.shape != (expect_rows, p * f):
            raise ValueError(
                f"packed payload shape {x2.shape} does not match "
                f"n={n}, f={f} (expected {(expect_rows, p * f)})"
            )
        self.x2 = x2
        self.n = int(n)
        self.f = int(f)
        self.p = p

    # mirror the DNDarray surface consumers touch
    @property
    def shape(self) -> Tuple[int, int]:
        return (self.n, self.f)

    @property
    def ndim(self) -> int:
        return 2

    @property
    def dtype(self):
        return self.x2.dtype

    @property
    def split(self):
        return self.x2.split

    @property
    def comm(self):
        return self.x2.comm

    @property
    def device(self):
        return self.x2.device

    def unpack(self) -> DNDarray:
        """The logical (n, f) array — materializes the lane-PADDED layout;
        for inspection and small data only."""
        rows = self.x2.larray.reshape(-1, self.f)[: self.n]
        return DNDarray(
            rows, (self.n, self.f), self.x2.dtype, None, self.device,
            self.comm,
        )

    def __repr__(self) -> str:
        return (
            f"PackedSamples(n={self.n}, f={self.f}, p={self.p}, "
            f"dtype=ht.{self.dtype.__name__})"
        )


@partial(jax.jit, static_argnames=("n", "p"))
def _zero_tail(x2, n: int, p: int):
    """Zero the trailing slots of the last row (slot s of row r is sample
    r*p + s; samples >= n are pad)."""
    rows, pf = x2.shape
    f = pf // p
    slot_sample = (
        (rows - 1) * p + jnp.arange(pf) // f
    )  # sample id of each lane in the LAST row
    mask = (slot_sample < n).astype(x2.dtype)
    return x2.at[rows - 1].multiply(mask)


def _packed_factory(sampler, n: int, f: int, dtype, split, device, comm):
    if not packable(f, dtype):
        raise ValueError(
            f"lane packing needs bf16 and f | 128, got f={f}, "
            f"dtype={types.canonical_heat_type(dtype).__name__}"
        )
    p = 128 // f
    rows = -(-n // p)
    x2 = sampler(rows, p * f, dtype=dtype, split=split, device=device, comm=comm)
    if n % p:
        x2 = DNDarray(
            _zero_tail(x2.larray, n, p), x2.shape, x2.dtype, x2.split,
            x2.device, x2.comm,
        )
    return PackedSamples(x2, n, f)


def randn_packed(
    n: int, f: int, dtype=types.bfloat16, split: Optional[int] = 0,
    device=None, comm=None,
) -> PackedSamples:
    """Standard-normal samples generated directly in packed form: the
    (rows, p*f) draw goes through random.randn's chunked block sampler, so
    neither a full-size f32 intermediate nor the lane-padded (n, f) layout
    ever exists (the ingest path for the 1e8x64 bf16 north-star)."""
    return _packed_factory(ht_random.randn, n, f, dtype, split, device, comm)


def rand_packed(
    n: int, f: int, dtype=types.bfloat16, split: Optional[int] = 0,
    device=None, comm=None,
) -> PackedSamples:
    """Uniform [0, 1) samples in packed form (see :func:`randn_packed`)."""
    return _packed_factory(ht_random.rand, n, f, dtype, split, device, comm)


def pack(x: DNDarray) -> PackedSamples:
    """Post-hoc packing of an existing (n, f) DNDarray.  Holds source and
    packed copy at once — near the HBM ceiling prefer the *_packed
    generators or load_hdf5_packed."""
    from ..core.dndarray import _to_physical
    from .kmeans import _pack_relayout

    n, f = x.shape
    if not packable(f, x.dtype):
        raise ValueError(f"cannot lane-pack f={f}, dtype={x.dtype.__name__}")
    p = 128 // f
    x2 = _pack_relayout(x.larray, p)
    shape = tuple(x2.shape)
    # canonical even-chunk physical layout over the mesh (trailing pad
    # rows' slots index past n, so consumers' validity masks drop them)
    phys = _to_physical(x2, shape, x.split, x.comm)
    wrapped = DNDarray(phys, shape, x.dtype, x.split, x.device, x.comm)
    return PackedSamples(wrapped, n, f)


def load_hdf5_packed(
    path: str, dataset: str, dtype=types.bfloat16, device=None, comm=None,
    split: Optional[int] = 0,
) -> PackedSamples:
    """Sharded HDF5 load straight into the packed layout: each host slab
    (a block of whole packed rows) is reshaped (rows_blk, p*f) before it
    lands on its device — the lane-padded (n, f) form never exists
    (reference loader: io.py:57; sharded slab path: core/io.py:86)."""
    from ..core import io as ht_io
    from ..core import stream
    import numpy as np

    if split != 0:
        raise ValueError("packed loads are row-sharded: split must be 0")
    ht = types.canonical_heat_type(dtype)
    np_dtype = types._np_equivalent(ht)
    # shared chunk reader (core/stream.py): one open handle for the whole
    # load instead of the old reopen-per-slab, one copy of the slab math
    with stream.open_source(path, dataset=dataset, np_dtype=np_dtype) as src:
        n, f = src.shape
        if not packable(f, ht):
            raise ValueError(f"cannot lane-pack f={f}, dtype={ht.__name__}")
        p = 128 // f
        rows = -(-n // p)

        def read_packed_slab(lo: int, hi: int) -> "np.ndarray":
            # packed rows [lo, hi) = samples [lo*p, min(hi*p, n))
            chunk = src.read(lo * p, min(hi * p, n))
            if chunk.shape[0] < (hi - lo) * p:  # zero-pad tail slots
                padr = (hi - lo) * p - chunk.shape[0]
                chunk = np.concatenate([chunk, np.zeros((padr, f), np_dtype)])
            return chunk.reshape(hi - lo, p * f)

        from ..core.devices import sanitize_device
        from ..parallel.mesh import sanitize_comm

        comm = sanitize_comm(comm)
        device = sanitize_device(device)
        x2 = ht_io._assemble_sharded(
            read_packed_slab, (rows, p * f), np_dtype, 0, device, comm
        )
    if x2.dtype is not ht:
        x2 = x2.astype(ht)
    return PackedSamples(x2, n, f)
