"""Clustering estimators (reference: heat/cluster/)."""

from . import packing
from .kmeans import KMeans
from .kmedians import KMedians
from .kmedoids import KMedoids
from .packing import (
    PackedSamples,
    load_hdf5_packed,
    pack,
    rand_packed,
    randn_packed,
)
from .spectral import Spectral

__all__ = [
    "KMeans",
    "KMedians",
    "KMedoids",
    "PackedSamples",
    "Spectral",
    "load_hdf5_packed",
    "pack",
    "packing",
    "rand_packed",
    "randn_packed",
]
