"""Spectral clustering (reference: heat/cluster/spectral.py, 217 LoC).

Pipeline matches the reference (:103-189): RBF similarity → graph Laplacian →
Lanczos low-rank eigendecomposition (distributed matmuls) → eigensolve of the
small tridiagonal T → KMeans on the spectral embedding."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray, _ensure_split
from ..core import types
from ..core.linalg import solver
from ..graph.laplacian import Laplacian
from ..spatial import distance
from .kmeans import KMeans

__all__ = ["Spectral"]


class Spectral(ClusteringMixin, BaseEstimator):
    """Spectral clustering on a similarity graph (reference: spectral.py:12)."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels

        if metric != "rbf":
            raise NotImplementedError(f"only the rbf metric is supported, got {metric!r}")
        sigma = (1.0 / (2.0 * gamma)) ** 0.5
        self._laplacian = Laplacian(
            lambda x: distance.rbf(x, sigma=sigma, quadratic_expansion=True),
            definition="norm_sym",
            mode=laplacian,
            threshold_key=boundary,
            threshold_value=threshold,
        )
        if assign_labels == "kmeans":
            kmeans_params = params.get("params", {"n_clusters": n_clusters, "init": "kmeans++"})
            if n_clusters is not None:
                kmeans_params["n_clusters"] = n_clusters
            self._cluster = KMeans(**kmeans_params)
        else:
            raise NotImplementedError(
                f"only kmeans label assignment is supported, got {assign_labels!r}"
            )
        self._labels = None

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        """Eigenvectors of the Laplacian via Lanczos (reference:
        spectral.py:103-149)."""
        L = self._laplacian.construct(x)
        m = min(self.n_lanczos, L.shape[0])
        V, T = solver.lanczos(L, m)
        # eigensolve the small tridiagonal T; approximate eigenpairs of L
        evals, evecs = jnp.linalg.eigh(T.larray)
        eigenvectors = jnp.matmul(V.larray, evecs)
        return evals, eigenvectors, x

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (reference: spectral.py:150-189)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        evals, evecs, _ = self._spectral_embedding(x)

        if self.n_clusters is None:
            # largest eigen-gap heuristic (reference: spectral.py:166)
            gaps = jnp.diff(evals)
            self.n_clusters = int(jnp.argmax(gaps)) + 1  # ht: HT002 ok — eigen-gap model selection needs the host-side cluster count
            self._cluster.n_clusters = self.n_clusters

        components = evecs[:, : self.n_clusters]
        emb = DNDarray(
            components, tuple(components.shape),
            types.canonical_heat_type(components.dtype), x.split, x.device, x.comm,
        )
        emb = _ensure_split(emb, x.split)
        self._cluster.fit(emb)
        self._labels = self._cluster.labels_
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Embed ``x`` and assign to the fitted KMeans centroids (reference:
        spectral.py:190-230 recomputes the eigenspectrum of ``x`` and calls
        the fitted clusterer's predict)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if self._labels is None:
            raise RuntimeError("fit the model first")
        if x.split is not None and x.split != 0:
            raise NotImplementedError("Not implemented for other splitting-axes")
        _, evecs, _ = self._spectral_embedding(x)
        components = evecs[:, : self.n_clusters]
        emb = DNDarray(
            components, tuple(components.shape),
            types.canonical_heat_type(components.dtype), x.split, x.device, x.comm,
        )
        return self._cluster.predict(_ensure_split(emb, x.split))
