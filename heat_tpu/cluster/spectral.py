"""Spectral clustering (reference: heat/cluster/spectral.py, 217 LoC).

Pipeline matches the reference (:103-189): RBF similarity → graph Laplacian →
Lanczos low-rank eigendecomposition (distributed matmuls) → eigensolve of the
small tridiagonal T → KMeans on the spectral embedding.

Round 19: ``affinity="knn"`` swaps the dense RBF similarity for a sparse
k-NN graph (``sparse.knn_graph``) and keeps the WHOLE pipeline sparse —
DCSR Laplacian (``graph.laplacian_sparse``), Lanczos over the tuned SpMV
program, zero densifications of the affinity matrix.  The dense
(n, n) similarity never exists; HBM residency is O(nnz)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray, _ensure_split
from ..core import types
from ..core.linalg import solver
from ..graph.laplacian import Laplacian
from ..sparse.dcsr_matrix import DCSR_matrix
from ..sparse.knn import knn_graph
from ..spatial import distance
from .kmeans import KMeans

__all__ = ["Spectral"]


class Spectral(ClusteringMixin, BaseEstimator):
    """Spectral clustering on a similarity graph (reference: spectral.py:12)."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        affinity: str = "rbf",
        n_neighbors: int = 10,
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels
        self.affinity = affinity
        self.n_neighbors = n_neighbors

        if metric != "rbf":
            raise NotImplementedError(f"only the rbf metric is supported, got {metric!r}")
        if affinity not in ("rbf", "knn"):
            raise NotImplementedError(
                f'affinity must be "rbf" (dense) or "knn" (sparse), got {affinity!r}'
            )
        sigma = (1.0 / (2.0 * gamma)) ** 0.5
        if affinity == "knn":
            # sparse path: k-NN graph with RBF edge weights; bucketed
            # slab capacity so serving requests share compiled programs
            similarity = lambda x: knn_graph(
                x, n_neighbors, weights="rbf", sigma=sigma,
                bucket_cap=True, split=x.split if x.split == 0 else None,
            )
        else:
            similarity = lambda x: distance.rbf(
                x, sigma=sigma, quadratic_expansion=True
            )
        self._laplacian = Laplacian(
            similarity,
            definition="norm_sym",
            mode=laplacian,
            threshold_key=boundary,
            threshold_value=threshold,
        )
        if assign_labels == "kmeans":
            kmeans_params = params.get("params", {"n_clusters": n_clusters, "init": "kmeans++"})
            if n_clusters is not None:
                kmeans_params["n_clusters"] = n_clusters
            self._cluster = KMeans(**kmeans_params)
        else:
            raise NotImplementedError(
                f"only kmeans label assignment is supported, got {assign_labels!r}"
            )
        self._labels = None

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        """Eigenvectors of the Laplacian via Lanczos (reference:
        spectral.py:103-149).  The sparse (knn) path runs the recurrence
        over the tuned SpMV program with a DETERMINISTIC start vector —
        a serving endpoint must embed identical batches identically."""
        L = self._laplacian.construct(x)
        n = L.shape[0]
        m = min(self.n_lanczos, n)
        if isinstance(L, DCSR_matrix):
            # deterministic, structureless v0 (sin ramp): generic w.r.t.
            # the Laplacian eigenbasis, unlike the all-ones vector which
            # is D^1/2-close to the trivial eigenvector
            raw = jnp.sin(jnp.arange(1, n + 1, dtype=jnp.float32))
            v0 = DNDarray(
                raw, (n,), types.float32, None, x.device, x.comm,
            )
            V, T = solver.lanczos(L, m, v0=v0)
        else:
            V, T = solver.lanczos(L, m)
        # eigensolve the small tridiagonal T; approximate eigenpairs of L
        evals, evecs = jnp.linalg.eigh(T.larray)
        eigenvectors = jnp.matmul(V.larray, evecs)
        return evals, eigenvectors, x

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (reference: spectral.py:150-189)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        evals, evecs, _ = self._spectral_embedding(x)

        if self.n_clusters is None:
            # largest eigen-gap heuristic (reference: spectral.py:166)
            gaps = jnp.diff(evals)
            self.n_clusters = int(jnp.argmax(gaps)) + 1  # ht: HT002 ok — eigen-gap model selection needs the host-side cluster count
            self._cluster.n_clusters = self.n_clusters

        components = evecs[:, : self.n_clusters]
        emb = DNDarray(
            components, tuple(components.shape),
            types.canonical_heat_type(components.dtype), x.split, x.device, x.comm,
        )
        emb = _ensure_split(emb, x.split)
        self._cluster.fit(emb)
        self._labels = self._cluster.labels_
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Embed ``x`` and assign to the fitted KMeans centroids (reference:
        spectral.py:190-230 recomputes the eigenspectrum of ``x`` and calls
        the fitted clusterer's predict)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if self._labels is None:
            raise RuntimeError("fit the model first")
        if x.split is not None and x.split != 0:
            raise NotImplementedError("Not implemented for other splitting-axes")
        _, evecs, _ = self._spectral_embedding(x)
        components = evecs[:, : self.n_clusters]
        emb = DNDarray(
            components, tuple(components.shape),
            types.canonical_heat_type(components.dtype), x.split, x.device, x.comm,
        )
        return self._cluster.predict(_ensure_split(emb, x.split))
