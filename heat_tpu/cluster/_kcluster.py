"""Shared k-clustering base (reference: heat/cluster/_kcluster.py, 254 LoC).

Init strategies match the reference (:87-194): ``"random"`` stratified point
sampling, ``"probability_based"`` (kmeans++) distance-weighted sampling, or
directly passed centroids.  Where the reference walks displacement tables and
Bcasts the chosen rows rank by rank, here a gather from the global array is
one XLA op (the sampled rows end up replicated, exactly like the Bcast)."""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..core import random as ht_random
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray, _ensure_split
from ..core import types
from ..ops.cdist import cdist as ops_cdist

__all__ = ["_KCluster"]


def _masked_medians(x, labels, k: int, fallback):
    """Per-cluster, per-feature median of the rows assigned to each cluster.

    The naive masked formulation (reference: kmedians.py:57 builds a
    per-cluster selection) would materialize an ``(n, k, f)`` tensor for a
    NaN-median — 20 GB at 1e7x64x8.  Instead: one ``(n, f)`` sort per cluster
    (non-members pushed to +inf sort to the end), then the two middle rows of
    the member prefix are picked by dynamic index.  Empty clusters fall back
    to ``fallback[j]``."""

    def body(j, meds):
        mask = labels == j
        cnt = jnp.sum(mask)
        svals = jnp.sort(jnp.where(mask[:, None], x, jnp.inf), axis=0)
        lo = jnp.maximum((cnt - 1) // 2, 0)
        hi = cnt // 2
        med = (
            jax.lax.dynamic_index_in_dim(svals, lo, 0, keepdims=False)
            + jax.lax.dynamic_index_in_dim(svals, hi, 0, keepdims=False)
        ) * 0.5
        return meds.at[j].set(jnp.where(cnt > 0, med, fallback[j]))

    return jax.lax.fori_loop(0, k, body, jnp.zeros((k, x.shape[1]), x.dtype))


def _l1_dist(x, centers):
    """(n, k) Manhattan distances; the broadcast |x-c| fuses into the
    reduction (no (n, k, f) buffer)."""
    return jnp.sum(jnp.abs(x[:, None, :] - centers[None, :, :]), axis=-1)


def _l1_assign(x, centers):
    """Labels by Manhattan distance."""
    return jnp.argmin(_l1_dist(x, centers), axis=1)


@partial(jax.jit, static_argnames=("k",))
def _kmeanspp_init(arr, us, k: int):
    """Distance-weighted (kmeans++) seeding, fused on-device (reference:
    _kcluster.py:141 draws one sample per round with a Bcast; through a
    remote TPU tunnel each round's ``.item()`` readback costs ~100x the
    distance computation, so all k rounds run in one XLA program fed by a
    single batch of uniforms).

    Matches the reference's weighting — Euclidean distance to the nearest
    chosen center, for every estimator (the reference's probability_based
    branch always uses ``spatial.cdist``, _kcluster.py:161) — carried as a
    running min so each round costs one (n, 1) distance column rather than
    an (n, k) recomputation.  Divergence from the reference, on purpose: the
    reference mins over all k centroid slots including the still-zero
    placeholders, so distance-to-origin leaks into its weights; here
    unchosen slots do not participate."""
    n, _ = arr.shape
    first = jnp.minimum((us[0] * n).astype(jnp.int32), n - 1)
    c0 = jax.lax.dynamic_index_in_dim(arr, first, 0, keepdims=False)
    centers = jnp.zeros((k, arr.shape[1]), arr.dtype).at[0].set(c0)
    d = ops_cdist(arr, c0[None, :], sqrt=True)[:, 0]

    def body(j, carry):
        centers, d = carry
        cum = jnp.cumsum(d / jnp.sum(d))
        nxt = jnp.minimum(jnp.searchsorted(cum, us[j]), n - 1)
        cj = jax.lax.dynamic_index_in_dim(arr, nxt, 0, keepdims=False)
        d = jnp.minimum(d, ops_cdist(arr, cj[None, :], sqrt=True)[:, 0])
        return centers.at[j].set(cj), d

    centers, _ = jax.lax.fori_loop(1, k, body, (centers, d))
    return centers


@partial(jax.jit, static_argnames=("k", "snap_to_sample"))
def _median_loop(x, centers, k: int, max_iter, tol, snap_to_sample: bool):
    """On-device KMedians/KMedoids iteration loop (one XLA program; see
    kmeans._lloyd_loop for why host round-trips per iteration are fatal
    through a remote TPU tunnel).

    ``snap_to_sample=False``: KMedians — centers move to per-cluster medians.
    ``snap_to_sample=True``: KMedoids — the median is snapped to the nearest
    actual sample (reference: kmedoids.py:56 "closest sample to the median").
    """

    def cond(state):
        _, shift, it = state
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(state):
        centers, _, it = state
        labels = _l1_assign(x, centers)
        new = _masked_medians(x, labels, k, centers)
        if snap_to_sample:
            counts = jnp.sum(labels[:, None] == jnp.arange(k)[None, :], axis=0)
            d2 = ops_cdist(x, new, sqrt=False)
            idx = jnp.argmin(d2, axis=0)
            new = jnp.where(counts[:, None] > 0, x[idx], centers)
        shift = jnp.sum((new - centers) ** 2)
        return new, shift, it + 1

    init = (centers, jnp.array(jnp.inf, x.dtype), 0)
    return jax.lax.while_loop(cond, body, init)


class _KCluster(ClusteringMixin, BaseEstimator):
    """Base class for k-statistics clustering (KMeans/KMedians/KMedoids)."""

    def __init__(
        self,
        metric: Callable,
        n_clusters: int,
        init: Union[str, DNDarray],
        max_iter: int,
        tol: float,
        random_state: Optional[int],
    ):
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state

        self._metric = metric
        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None

    @property
    def cluster_centers_(self) -> DNDarray:
        """Coordinates of the cluster centers (replicated)."""
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    @property
    def inertia_(self) -> float:
        return self._inertia

    @property
    def n_iter_(self) -> int:
        return self._n_iter

    def _initialize_cluster_centers(self, x: DNDarray):
        """Pick initial centroids (reference: _kcluster.py:87)."""
        if self.random_state is not None:
            ht_random.seed(self.random_state)
        k = self.n_clusters
        n = x.shape[0]
        if n < k:
            raise ValueError(
                f"n_samples={n} should be >= n_clusters={k}"
            )
        arr = x.larray

        if isinstance(self.init, DNDarray):
            if self.init.ndim != 2:
                raise ValueError("passed centroids need to be two-dimensional")
            if self.init.shape[0] != k or self.init.shape[1] != x.shape[1]:
                raise ValueError("passed centroids do not match cluster count or data shape")
            self._cluster_centers = self.init.resplit(None)
            return

        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        if isinstance(self.init, str) and self.init == "random":
            # one sample per stratum [i*n/k, (i+1)*n/k) — the reference's
            # equal-distribution draw (_kcluster.py:101-123); one batched
            # uniform draw, indices never leave the device
            # uniforms stay float32: cast to a half-precision data dtype
            # would quantize the sampled indices to ~1.7k distinct rows
            # scope the draw to x's communicator: a sub-mesh fit must not mix
            # world-mesh arrays into the jitted init (comm.Split consumers)
            us = ht_random.rand(k, comm=x.comm).larray.astype(jnp.float32)
            lo = jnp.arange(k) * (n // k)
            width = jnp.maximum(jnp.asarray(n // k), 1)
            idx = jnp.minimum(lo + (us * width).astype(jnp.int32), n - 1)
            centroids = arr[idx]
        elif isinstance(self.init, str) and self.init in ("probability_based", "kmeans++"):
            # scope the draw to x's communicator: a sub-mesh fit must not mix
            # world-mesh arrays into the jitted init (comm.Split consumers)
            us = ht_random.rand(k, comm=x.comm).larray.astype(jnp.float32)
            centroids = _kmeanspp_init(arr, us, k)
        else:
            raise ValueError(
                f'init needs to be "random", "kmeans++"/"probability_based" or a '
                f"DNDarray, but was {self.init!r}"
            )

        self._cluster_centers = DNDarray(
            centroids, tuple(centroids.shape),
            types.canonical_heat_type(centroids.dtype), None, x.device, x.comm,
        )

    def _assign_to_cluster(self, x: DNDarray, return_inertia: bool = False):
        """Assign each sample to its closest centroid (reference:
        _kcluster.py:196).  With ``return_inertia`` the min-distance sum
        rides along as a second root of the SAME fused program — the
        cdist subtree is shared through the scheduler's CSE, so labels and
        inertia cost one compile and one dispatch, not two cdists."""
        from ..core import fusion, statistics

        # the distance update rides the fusion engine: a GSPMD cdist defers a
        # lazy DAG and this argmin extends it, so distances + labels lower as
        # one cached executable per (shape, sharding) key
        distances = self._metric(x, self._cluster_centers)
        labels = statistics.argmin(distances, axis=1, keepdims=True)
        if return_inertia:
            inertia = statistics.min(distances, axis=1).sum()
            fusion.materialize(labels, inertia)
            inertia_val = float(jnp.asarray(inertia.larray).reshape(()))  # ht: HT002 ok — end-of-fit inertia readback, one scalar per fit
        if labels.split != x.split:
            out = DNDarray(
                labels.larray, labels.gshape, labels.dtype, x.split, x.device, x.comm
            )
            labels = _ensure_split(out, x.split)
        if return_inertia:
            return labels, inertia_val
        return labels

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray):
        raise NotImplementedError()

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    def _fit_median_loop(self, x: DNDarray, snap_to_sample: bool):
        """Shared KMedians/KMedoids fit body: initialize, run the on-device
        :func:`_median_loop`, rebuild center/label metadata."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2-D, but was {x.ndim}-D")
        self._initialize_cluster_centers(x)
        arr = x.larray
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(jnp.float32)
        centers = self._cluster_centers.larray.astype(arr.dtype)
        centers, _, n_iter = _median_loop(
            arr, centers, self.n_clusters, self.max_iter, self.tol,
            snap_to_sample=snap_to_sample,
        )
        self._n_iter = int(n_iter)  # ht: HT002 ok — end-of-fit n_iter readback, one scalar per fit
        self._cluster_centers = DNDarray(
            centers, tuple(centers.shape),
            types.canonical_heat_type(centers.dtype), None, x.device, x.comm,
        )
        self._labels, self._inertia = self._assign_to_cluster(x, return_inertia=True)
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Closest-cluster index for each sample (reference: _kcluster.py)."""
        from ..core import sanitation

        sanitation.sanitize_in(x)
        if self._cluster_centers is None:
            raise RuntimeError(
                f"{type(self).__name__} is not fitted yet; call fit() before predict()"
            )
        return self._assign_to_cluster(x)
