"""Elastic batch-serving front door (ISSUE 14).

Turns the fitted estimator/nn surface into a concurrent request path:

>>> import heat_tpu as ht
>>> from heat_tpu import serving
>>> eng = serving.ServingEngine()
>>> eng.register("kmeans", model, feature_dim=32, warm=True)
>>> labels = eng.predict("kmeans", one_row)          # blocking
>>> fut = eng.submit("kmeans", four_rows)            # async Future

Three layers, one module each:

* :mod:`~heat_tpu.serving.batcher` — shape-agnostic request coalescing
  (flush on full bucket / latency deadline / drain);
* :mod:`~heat_tpu.serving.engine` — endpoint registry, power-of-two
  bucket ladders, compile-once step cache, telemetry;
* :mod:`~heat_tpu.serving.admission` — bounded queue depth, HBM-,
  stall- and SLO-class-aware load shedding (:class:`RequestRejected`),
  graceful drain;
* :mod:`~heat_tpu.serving.router` — the fleet layer (ISSUE 18): N
  health-checked replicas behind a consistent-hash ring, circuit
  breaker with half-open probes, bounded retry/failover, and
  zero-downtime rolling weight swaps:

>>> fleet = serving.ServingFleet(replicas=4)
>>> fleet.register("kmeans", models=replica_models, feature_dim=32)
>>> fleet.rolling_swap("kmeans", {"w": new_w}, canary=1)

Importing the package registers the ``serving`` and ``router``
telemetry groups; see ``docs/quick_start.md`` §13/§16 for the
end-to-end walkthroughs.
"""

from .admission import AdmissionController, RequestRejected
from .batcher import DynamicBatcher, Request
from .engine import Endpoint, ServingEngine
from .router import Replica, ServingFleet

__all__ = [
    "AdmissionController",
    "DynamicBatcher",
    "Endpoint",
    "Replica",
    "Request",
    "RequestRejected",
    "ServingEngine",
    "ServingFleet",
]
