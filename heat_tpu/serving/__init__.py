"""Elastic batch-serving front door (ISSUE 14).

Turns the fitted estimator/nn surface into a concurrent request path:

>>> import heat_tpu as ht
>>> from heat_tpu import serving
>>> eng = serving.ServingEngine()
>>> eng.register("kmeans", model, feature_dim=32, warm=True)
>>> labels = eng.predict("kmeans", one_row)          # blocking
>>> fut = eng.submit("kmeans", four_rows)            # async Future

Three layers, one module each:

* :mod:`~heat_tpu.serving.batcher` — shape-agnostic request coalescing
  (flush on full bucket / latency deadline / drain);
* :mod:`~heat_tpu.serving.engine` — endpoint registry, power-of-two
  bucket ladders, compile-once step cache, telemetry;
* :mod:`~heat_tpu.serving.admission` — bounded queue depth, HBM- and
  stall-aware load shedding (:class:`RequestRejected`), graceful drain.

Importing the package registers the ``serving`` telemetry group; see
``docs/quick_start.md`` §13 for the end-to-end walkthrough.
"""

from .admission import AdmissionController, RequestRejected
from .batcher import DynamicBatcher, Request
from .engine import Endpoint, ServingEngine

__all__ = [
    "AdmissionController",
    "DynamicBatcher",
    "Endpoint",
    "Request",
    "RequestRejected",
    "ServingEngine",
]
