"""Admission control for the serving front door: bounded queues,
load-shedding, and stall-aware fast-fail.

The controller is the *decision* layer only — it never touches the mesh
and never blocks.  :meth:`AdmissionController.admit` either returns (the
request may be queued) or raises :class:`RequestRejected` with a
machine-readable reason and a ``retry_after_s`` hint.  Three pressure
signals feed the decision:

* **queue depth** — accepted-but-unfinished rows are capped at
  ``max_queue_rows``; beyond that the queue is only adding latency, so
  new work is shed (``queue_full``) instead of piling up.
* **HBM headroom** — :func:`heat_tpu.core.memtrack.would_fit` projects
  the request's staging bytes against the measured free-memory budget
  (``hbm_pressure``).  Statsless backends (CPU CI) return ``None`` and
  the gate admits — never shed on fake numbers.
* **mesh liveness** — a :class:`heat_tpu.utils.fault.StallDetector`
  subscription (satellite of ISSUE 14) latches ``stalled`` on the
  detector's ``"stall"`` notification and clears it on ``"recover"`` /
  ``"resume"``, so a wedged mesh fails requests in microseconds instead
  of letting them hang behind a dead queue.  Push, not poll.

Round 20 adds **SLO classes**: every request carries a ``priority``
(``"high"`` / ``"normal"`` / ``"low"`` by default) and each class rides a
fraction of the queue bound (:data:`DEFAULT_CLASS_THRESHOLDS`).  Under
pressure the low class hits its smaller bound first — low-priority work
sheds before paying traffic feels anything — while ``high``/``normal``
keep the full bound, so the single-class behaviour is unchanged.

Shutdown is two-phase: :meth:`begin_drain` sheds *new* work
(``draining``) while queued work finishes; :meth:`close` sheds
everything (``closed``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..core import memtrack, telemetry

__all__ = ["AdmissionController", "DEFAULT_CLASS_THRESHOLDS", "RequestRejected"]

#: SLO classes and the fraction of ``max_queue_rows`` each may fill.
#: ``high`` and ``normal`` ride the full bound (so a fleet of one class
#: behaves exactly like the pre-SLO gate); ``low`` is shed once the
#: queue passes half — under pressure, low-priority work goes first.
DEFAULT_CLASS_THRESHOLDS = {"high": 1.0, "normal": 1.0, "low": 0.5}


class RequestRejected(RuntimeError):
    """The front door refused to queue a request (load shedding).

    This is the *documented* serving error: callers must catch it and
    back off rather than treat it as an infrastructure failure.  Fields:

    ``reason``
        One of ``"queue_full"``, ``"hbm_pressure"``, ``"stalled"``,
        ``"draining"``, ``"closed"``, ``"too_large"``.
    ``retry_after_s``
        Suggested client backoff in seconds, or ``None`` when retrying
        the same process cannot help (``closed``, ``too_large``).

    The message always reads ``serving request rejected (<reason>):
    <detail>`` with the retry hint appended when one exists, so log
    scrapers and tests can match on the reason token.
    """

    def __init__(self, reason: str, retry_after_s: Optional[float], detail: str):
        self.reason = str(reason)
        self.retry_after_s = retry_after_s
        msg = f"serving request rejected ({self.reason}): {detail}"
        if retry_after_s is not None:
            msg += f"; retry after {retry_after_s:g}s"
        super().__init__(msg)


class AdmissionController:
    """Bounded-queue + pressure-aware admission decisions.

    One controller fronts one :class:`~heat_tpu.serving.engine.ServingEngine`;
    the engine calls :meth:`admit` before enqueueing and :meth:`release`
    when a request's rows leave the system (served or failed).  All state
    transitions are guarded by one lock; callbacks from the stall
    detector arrive on the watcher thread and only flip latches.
    """

    def __init__(
        self,
        *,
        max_queue_rows: int = 1024,
        retry_after_s: float = 0.05,
        memory_fraction: float = 0.5,
        memory_headroom: int = 0,
        class_thresholds: Optional[Dict[str, float]] = None,
    ):
        if max_queue_rows < 1:
            raise ValueError(f"max_queue_rows must be >= 1, got {max_queue_rows}")
        self.max_queue_rows = int(max_queue_rows)
        self.retry_after_s = float(retry_after_s)
        self.memory_fraction = float(memory_fraction)
        self.memory_headroom = int(memory_headroom)
        thresholds = dict(DEFAULT_CLASS_THRESHOLDS)
        if class_thresholds:
            thresholds.update(class_thresholds)
        for cls, fraction in thresholds.items():
            if not 0.0 < float(fraction) <= 1.0:
                raise ValueError(
                    f"class threshold for {cls!r} must be in (0, 1], got {fraction}"
                )
        self.class_thresholds = {c: float(f) for c, f in thresholds.items()}
        self._lock = threading.Lock()
        self._queued_rows = 0
        self._stalled = False
        self._draining = False
        self._closed = False
        self._detector = None

    # -- stall-detector subscription (push, not poll) -------------------

    def attach_stall_detector(self, detector) -> "AdmissionController":
        """Subscribe to ``detector`` so stall/pause/resume flip the
        ``stalled`` latch without any polling thread."""
        with self._lock:
            if self._detector is not None:
                raise RuntimeError("a StallDetector is already attached")
            self._detector = detector
        detector.subscribe(self._on_stall_event)
        return self

    def detach_stall_detector(self) -> None:
        with self._lock:
            detector, self._detector = self._detector, None
        if detector is not None:
            detector.unsubscribe(self._on_stall_event)

    def _on_stall_event(self, kind: str, info: Dict[str, Any]) -> None:
        # Watcher-thread context: latch flips only, no mesh work.
        if kind == "stall":
            with self._lock:
                self._stalled = True
            telemetry.record_event("serving_stall", **info)
        elif kind in ("recover", "resume"):
            with self._lock:
                self._stalled = False

    # -- lifecycle ------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting new work; queued work keeps flowing."""
        with self._lock:
            self._draining = True

    def close(self) -> None:
        """Terminal: everything is shed from here on."""
        with self._lock:
            self._draining = True
            self._closed = True
        self.detach_stall_detector()

    # -- the decision ---------------------------------------------------

    def admit(
        self, endpoint: str, rows: int, nbytes: int, *, priority: str = "normal"
    ) -> None:
        """Admit ``rows`` request rows (``nbytes`` of staging) for
        ``endpoint`` or raise :class:`RequestRejected`.

        ``priority`` selects the SLO class: the queue bound scales by
        the class's threshold, so under pressure classes below 1.0 shed
        first.  An unknown class is a programming error (``ValueError``),
        not load shedding."""
        rows = int(rows)
        threshold = self.class_thresholds.get(priority)
        if threshold is None:
            raise ValueError(
                f"unknown SLO class {priority!r}; known: {sorted(self.class_thresholds)}"
            )
        with self._lock:
            if self._closed:
                raise RequestRejected("closed", None, "serving engine is closed")
            if self._draining:
                raise RequestRejected(
                    "draining", self.retry_after_s, "engine is draining for shutdown"
                )
            if self._stalled:
                raise RequestRejected(
                    "stalled",
                    self.retry_after_s,
                    "mesh stall detected — failing fast instead of queueing behind it",
                )
            bound = int(self.max_queue_rows * threshold)
            if self._queued_rows + rows > bound:
                detail = (
                    f"{self._queued_rows} rows queued + {rows} requested "
                    f"> bound {bound}"
                )
                if threshold < 1.0:
                    detail += (
                        f" (class {priority!r} rides {threshold:g} of "
                        f"{self.max_queue_rows} — lower classes shed first)"
                    )
                raise RequestRejected("queue_full", self.retry_after_s, detail)
            fits = memtrack.would_fit(
                int(nbytes),
                fraction=self.memory_fraction,
                headroom=self.memory_headroom,
            )
            if fits is False:
                raise RequestRejected(
                    "hbm_pressure",
                    self.retry_after_s,
                    f"{int(nbytes)} staging bytes exceed the measured HBM budget",
                )
            self._queued_rows += rows

    def release(self, rows: int) -> None:
        """Rows left the system (served or failed) — free queue budget."""
        with self._lock:
            self._queued_rows = max(0, self._queued_rows - int(rows))

    def note_progress(self) -> None:
        """A batch completed on the mesh: any stale stall latch clears.

        Belt-and-braces next to the detector's ``"recover"`` push — an
        engine without an attached detector still self-heals."""
        with self._lock:
            self._stalled = False

    # -- introspection --------------------------------------------------

    @property
    def stalled(self) -> bool:
        with self._lock:
            return self._stalled

    @property
    def queued_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "queued_rows": self._queued_rows,
                "max_queue_rows": self.max_queue_rows,
                "stalled": self._stalled,
                "draining": self._draining,
                "closed": self._closed,
            }
