"""Dynamic batcher: coalesce in-flight requests into bucketed batches.

One daemon worker drains per-endpoint FIFO queues.  A queue flushes when
one of three causes fires, and the cause is reported to the executor so
the telemetry plane can count *why* batches formed:

``"max_batch"``
    Enough rows are queued to fill the endpoint's largest bucket —
    flush immediately, latency timer not consulted.
``"timer"``
    The oldest queued request hit its ``max_delay_s`` deadline — ship a
    partial batch rather than holding a caller hostage for stragglers.
``"drain"``
    Shutdown: everything queued is flushed regardless of deadlines.

The batcher never splits a request across batches — per-request
unpadding in the engine stays a contiguous row slice — and it knows
nothing about shapes, buckets, or JAX: it moves :class:`Request` objects
and calls ``execute(endpoint, requests, cause)`` outside its lock, so a
slow mesh step never blocks enqueues.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

__all__ = ["DynamicBatcher", "Request"]


@dataclass
class Request:
    """One admitted request riding the queue.

    ``payload`` is the host-side (rows, feature_dim) array, ``deadline``
    the absolute ``time.perf_counter()`` instant after which the flush
    timer fires, ``t0`` the submit instant for the latency histogram.
    ``priority`` is the SLO class the admission gate admitted under;
    ``client_deadline`` (absolute, or ``None``) is the *caller's*
    deadline — a request still queued when it lapses is shed at flush
    (``expired``) instead of computing an answer nobody is waiting for."""

    endpoint: str
    payload: Any
    rows: int
    t0: float
    deadline: float
    priority: str = "normal"
    client_deadline: Optional[float] = None
    future: Future = field(default_factory=Future)


class DynamicBatcher:
    """Condition-variable driven coalescing queue (one worker thread).

    ``execute`` is called as ``execute(endpoint, requests, cause)`` with
    the batcher lock **released**; it owns resolving every request's
    future (success or failure) — the batcher never touches futures of
    work it has handed off."""

    def __init__(
        self,
        execute: Callable[[str, Sequence[Request], str], None],
        *,
        name: str = "heat-tpu-serving-batcher",
    ):
        self._execute = execute
        self._name = name
        self._cond = threading.Condition()
        self._queues: Dict[str, Deque[Request]] = {}
        self._caps: Dict[str, int] = {}
        self._in_flight = 0
        self._draining = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    # -- producer side --------------------------------------------------

    def enqueue(self, request: Request, max_batch_rows: int) -> None:
        """Queue an admitted request; starts the worker lazily."""
        with self._cond:
            if self._stopped:
                raise RuntimeError("batcher is stopped")
            self._caps[request.endpoint] = int(max_batch_rows)
            self._queues.setdefault(request.endpoint, deque()).append(request)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, name=self._name, daemon=True
                )
                self._thread.start()
            self._cond.notify_all()

    # -- worker side ----------------------------------------------------

    def _pick_locked(
        self, now: float
    ) -> Tuple[Optional[Tuple[str, List[Request], str]], Optional[float]]:
        """Under the lock: choose the most urgent flushable queue.

        Returns ``((endpoint, requests, cause), None)`` when a flush is
        due, else ``(None, seconds_until_next_deadline_or_None)``."""
        best: Optional[Tuple[float, str, str]] = None
        wait: Optional[float] = None
        for name, queue in self._queues.items():
            if not queue:
                continue
            head = queue[0]
            rows = sum(r.rows for r in queue)
            if rows >= self._caps.get(name, 1):
                cause = "max_batch"
            elif self._draining or self._stopped:
                cause = "drain"
            elif now >= head.deadline:
                cause = "timer"
            else:
                until = head.deadline - now
                wait = until if wait is None else min(wait, until)
                continue
            if best is None or head.deadline < best[0]:
                best = (head.deadline, name, cause)
        if best is None:
            return None, wait
        _, name, cause = best
        queue = self._queues[name]
        cap = self._caps.get(name, 1)
        picked: List[Request] = [queue.popleft()]
        total = picked[0].rows
        while queue and total + queue[0].rows <= cap:
            req = queue.popleft()
            picked.append(req)
            total += req.rows
        return (name, picked, cause), None

    def _worker(self) -> None:
        while True:
            with self._cond:
                while True:
                    picked, wait = self._pick_locked(time.perf_counter())
                    if picked is not None:
                        self._in_flight += 1
                        break
                    if self._stopped:
                        return
                    self._cond.wait(timeout=wait)
            name, requests, cause = picked
            try:
                self._execute(name, requests, cause)
            finally:
                with self._cond:
                    self._in_flight -= 1
                    self._cond.notify_all()

    # -- lifecycle ------------------------------------------------------

    def pending_requests(self) -> int:
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    def busy(self) -> int:
        """Queued requests plus in-flight batches.  Zero means the worker
        is legitimately idle (no heartbeats expected); nonzero while a
        stall detector fires means work is actually wedged — the fleet
        router uses this to tell a quiet replica from a dead one."""
        with self._cond:
            return sum(len(q) for q in self._queues.values()) + self._in_flight

    def in_flight(self) -> int:
        """Batches currently executing.  Queued-but-unflushed requests
        don't count: a queue waiting out ``max_delay_s`` is batching
        latency, not a wedged step, and must not trip the breaker."""
        with self._cond:
            return self._in_flight

    def drain(self, timeout: float = 30.0) -> bool:
        """Flush every queue (cause ``"drain"``) and wait for in-flight
        batches to land.  True when fully drained inside ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while any(self._queues.values()) or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    def cancel_pending(self) -> List[Request]:
        """Pop everything still queued (caller owns the futures)."""
        with self._cond:
            out: List[Request] = []
            for queue in self._queues.values():
                out.extend(queue)
                queue.clear()
            self._cond.notify_all()
            return out

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the worker; queued work should be drained or cancelled
        first — anything left flushes with cause ``"drain"`` on the way
        out."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout)
