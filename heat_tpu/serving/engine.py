"""Endpoint registry + compile-once step cache: the serving engine.

A :class:`ServingEngine` turns fitted estimators (``KMeans.predict``,
``Lasso.predict``, ``GaussianNB.predict``, ``KNeighborsClassifier
.predict``) or any ``DNDarray -> DNDarray`` callable (``nn.functional.
linear`` closures) into concurrently callable endpoints:

* :meth:`register` fixes the endpoint's feature dim / dtype / split and
  derives its **bucket ladder** — power-of-two row counts from
  ``min_bucket`` up through ``max_batch`` — so every batch the mesh ever
  sees has one of a handful of shapes;
* :meth:`submit` validates + admits a request and hands it to the
  :class:`~heat_tpu.serving.batcher.DynamicBatcher`; the returned
  :class:`~concurrent.futures.Future` resolves to exactly the caller's
  rows (per-request unpadding is a contiguous slice);
* the worker pads each coalesced batch up to the smallest bucket and
  runs it through a **compile-once step cache**: one step per
  (endpoint, bucket), fingerprinted into the telemetry program ledger.
  Identical shapes mean the fusion/overlap/autotune caches underneath
  never retrace after warmup — and with ``HEAT_TPU_AUTOTUNE_CACHE`` (+
  the JAX persistent compilation cache it enables) a fresh process does
  **zero explores**: every decision is ``cached`` from the first batch.

Telemetry: the ``serving`` counter group (accepted / rejected / batched
/ padded_rows / flush_cause / shed reasons / step compiles), per-
endpoint latency p50/p99 exported through ``export_prometheus()`` as
``heat_tpu_serving_latency_<endpoint>_p50_s``, flight-recorder events
for shed / drain / stall, and one span per batch.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import factories, guard, telemetry
from .admission import AdmissionController, RequestRejected
from .batcher import DynamicBatcher, Request

__all__ = ["Endpoint", "ServingEngine"]

#: per-endpoint latency reservoir depth — enough for stable p99 under CI
#: traffic without unbounded growth
_LATENCY_SAMPLES = 512

_LATENCIES: Dict[str, Deque[float]] = {}


def _quantile(ordered: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over an already-sorted sample."""
    idx = min(len(ordered) - 1, max(0, int(math.ceil(q * len(ordered))) - 1))
    return ordered[idx]


def _latency_view() -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for name in sorted(_LATENCIES):
        sample = sorted(_LATENCIES[name])
        if sample:
            out[name] = {
                "count": len(sample),
                "p50_s": round(_quantile(sample, 0.50), 6),
                "p99_s": round(_quantile(sample, 0.99), 6),
            }
    return out


_STATS = telemetry.register_group(
    "serving",
    {
        "accepted": 0,
        "rejected": 0,
        "batched": 0,
        "batches": 0,
        "padded_rows": 0,
        "step_compiles": 0,
        "step_hits": 0,
        "step_errors": 0,
        "swaps": 0,
        "drains": 0,
        "flush_cause": {"max_batch": 0, "timer": 0, "drain": 0},
        "shed": {
            "queue_full": 0,
            "hbm_pressure": 0,
            "stalled": 0,
            "draining": 0,
            "closed": 0,
            "too_large": 0,
            "expired": 0,
        },
        "accepted_by_class": {"high": 0, "normal": 0, "low": 0},
        "shed_by_class": {"high": 0, "normal": 0, "low": 0},
    },
    extra=lambda: {"latency": _latency_view()},
    on_reset=_LATENCIES.clear,
)


def _bump(counter: Dict[str, int], key: str) -> None:
    # class counters grow with operator-configured SLO classes; the
    # three defaults are pre-registered so gauges exist from process start
    counter[key] = counter.get(key, 0) + 1


def _mesh_size() -> int:
    try:
        from ..core import communication

        return int(communication.world().size)
    except Exception:
        return 1


def _pow2_buckets(min_bucket: int, max_batch: int) -> Tuple[int, ...]:
    """Power-of-two ladder covering [min_bucket, max_batch] (both
    rounded up to powers of two)."""
    if min_bucket < 1 or max_batch < 1:
        raise ValueError("min_bucket and max_batch must be >= 1")
    size = 1 << (int(min_bucket) - 1).bit_length()
    top = 1 << (int(max_batch) - 1).bit_length()
    ladder = []
    while size < top:
        ladder.append(size)
        size <<= 1
    ladder.append(top)
    return tuple(ladder)


def _dtype_name(dt: Any) -> str:
    # numpy parses its own dtypes; heat's type *classes* parse as
    # dtype('O'), so fall back to the class name ("float32")
    try:
        parsed = np.dtype(dt)
        if parsed != np.dtype(object):
            return parsed.name
    except TypeError:
        pass
    return getattr(dt, "__name__", str(dt))


def _check_swap_compat(endpoint: str, key: str, cur: Any, new: Any) -> None:
    """Refuse operand swaps that would change the step's traced shapes.

    The round-18 law: a republished checkpoint is *new operands, not a
    retrace*.  A shape/dtype/split change recompiles every bucket step,
    so it is rejected here instead of silently blowing the caches."""
    cur_shape = tuple(getattr(cur, "shape", ()) or ())
    new_shape = tuple(getattr(new, "shape", ()) or ())
    if cur_shape != new_shape:
        raise ValueError(
            f"swap_weights({endpoint!r}): operand {key!r} shape {new_shape} "
            f"!= resident {cur_shape} — a shape change retraces every bucket "
            "step; register a new endpoint instead"
        )
    cur_dt, new_dt = getattr(cur, "dtype", None), getattr(new, "dtype", None)
    if cur_dt is not None and new_dt is not None:
        if _dtype_name(cur_dt) != _dtype_name(new_dt):
            raise ValueError(
                f"swap_weights({endpoint!r}): operand {key!r} dtype "
                f"{_dtype_name(new_dt)} != resident {_dtype_name(cur_dt)} — "
                "a dtype change is a retrace"
            )
    if getattr(cur, "split", None) != getattr(new, "split", None):
        raise ValueError(
            f"swap_weights({endpoint!r}): operand {key!r} split "
            f"{getattr(new, 'split', None)} != resident "
            f"{getattr(cur, 'split', None)} — a resharding is a retrace"
        )


@dataclass(frozen=True)
class Endpoint:
    """One registered predict surface with its frozen shape contract."""

    name: str
    predict: Callable[[Any], Any]
    feature_dim: int
    dtype: "np.dtype"
    split: Optional[int]
    buckets: Tuple[int, ...]
    max_delay_s: float
    model: Any = None

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        for size in self.buckets:
            if rows <= size:
                return size
        raise ValueError(f"{rows} rows exceed largest bucket {self.buckets[-1]}")


class _Step:
    """One cached compiled program: (endpoint, bucket) -> host fn."""

    __slots__ = ("run", "fingerprint", "bucket")

    def __init__(self, run: Callable[[np.ndarray], np.ndarray], fingerprint: str, bucket: int):
        self.run = run
        self.fingerprint = fingerprint
        self.bucket = bucket


class ServingEngine:
    """The front door: endpoint registry, batcher, admission, steps.

    Usable as a context manager (``with ServingEngine() as eng: ...``);
    exit drains queued work then stops the worker."""

    def __init__(
        self,
        *,
        name: str = "",
        admission: Optional[AdmissionController] = None,
        stall_detector=None,
        default_max_delay_s: float = 0.005,
    ):
        # a name marks this engine as one replica of a fleet: its latency
        # reservoirs are keyed "<name>:<endpoint>" so the router can route
        # on *this* replica's percentiles, not a fleet-wide blur
        self.name = str(name)
        self._endpoints: Dict[str, Endpoint] = {}
        self._steps: Dict[Tuple[str, int], _Step] = {}
        self._lock = threading.Lock()
        self._swap_lock = threading.Lock()
        self._closed = False
        self.default_max_delay_s = float(default_max_delay_s)
        self.admission = admission if admission is not None else AdmissionController()
        self._batcher = DynamicBatcher(
            self._execute,
            name=f"heat-tpu-serving-batcher-{self.name}"
            if self.name
            else "heat-tpu-serving-batcher",
        )
        self._detector = None
        if stall_detector is not None:
            self.attach_stall_detector(stall_detector)

    # -- registry -------------------------------------------------------

    def attach_stall_detector(self, detector):
        """Wire a :class:`~heat_tpu.utils.fault.StallDetector` into the
        admission gate (push-based shed) and beat it per served batch."""
        self.admission.attach_stall_detector(detector)
        self._detector = detector
        return detector

    def register(
        self,
        name: str,
        model: Any = None,
        *,
        predict: Optional[Callable[[Any], Any]] = None,
        feature_dim: int,
        dtype: Any = np.float32,
        split: Optional[int] = 0,
        min_bucket: Optional[int] = None,
        max_batch: int = 64,
        max_delay_s: Optional[float] = None,
        warm: bool = False,
        quantize: bool = False,
    ) -> Endpoint:
        """Register an endpoint: exactly one of ``model`` (an object with
        ``.predict``) or ``predict`` (a ``DNDarray -> DNDarray`` callable).

        ``min_bucket`` defaults to ``max(8, mesh size)`` so split-0
        batches always give every device at least one row; ``max_batch``
        is rounded up to the bucket ladder's top rung.  ``warm=True``
        compiles every bucket before the first request lands.

        ``quantize=True`` calls ``model.quantize_()`` before serving —
        the model drops its full-precision resident state for int8
        (e.g. ``KNeighborsClassifier`` quantizes its corpus and serves
        through the quantized ring cdist); requires ``model`` with a
        ``quantize_`` method."""
        if self._closed:
            raise RuntimeError("serving engine is closed")
        if (model is None) == (predict is None):
            raise ValueError("pass exactly one of `model` or `predict`")
        if quantize:
            hook = getattr(model, "quantize_", None)
            if hook is None:
                raise ValueError(
                    "quantize=True needs a `model` exposing quantize_() "
                    f"(got {type(model).__name__})"
                )
            hook()
        if predict is None:
            predict = model.predict
        if name in self._endpoints:
            raise ValueError(f"endpoint {name!r} already registered")
        feature_dim = int(feature_dim)
        if feature_dim < 1:
            raise ValueError(f"feature_dim must be >= 1, got {feature_dim}")
        if min_bucket is None:
            min_bucket = max(8, _mesh_size())
        buckets = _pow2_buckets(min_bucket, max_batch)
        endpoint = Endpoint(
            name=name,
            predict=predict,
            feature_dim=feature_dim,
            dtype=np.dtype(dtype),
            split=split,
            buckets=buckets,
            max_delay_s=self.default_max_delay_s if max_delay_s is None else float(max_delay_s),
            model=model,
        )
        with self._lock:
            self._endpoints[name] = endpoint
        telemetry.record_event(
            "serving_endpoint",
            endpoint=name,
            feature_dim=feature_dim,
            buckets=list(buckets),
            split=split,
            quantized=bool(quantize),
        )
        if warm:
            self.warmup(name)
        return endpoint

    def endpoints(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._endpoints))

    def warmup(self, name: str) -> int:
        """Compile + run every bucket of ``name`` once on zeros so live
        traffic starts on warm caches.  Returns the bucket count."""
        endpoint = self._endpoint(name)
        for bucket in endpoint.buckets:
            step = self._get_step(endpoint, bucket)
            step.run(np.zeros((bucket, endpoint.feature_dim), dtype=endpoint.dtype))
        return len(endpoint.buckets)

    def swap_weights(self, name: str, params: Dict[str, Any]) -> Dict[str, Any]:
        """Hot-swap endpoint ``name``'s model operands under live traffic.

        ``params`` maps model attribute names to replacement operands
        (e.g. ``{"w": new_weights}``).  A model exposing
        ``swap_weights_(params) -> old_params`` owns the exchange itself;
        otherwise attributes are validated then assigned.  Shapes, dtypes
        and splits must match the resident operands — a mismatch would
        retrace every bucket step, so it is refused with ``ValueError``
        (round-18 law: a republished checkpoint is new operands, not a
        retrace — **zero step compiles**).  Returns the old operand
        values for rollback.  The exchange happens under the step lock:
        a mid-flight batch sees all-old or all-new weights, never a mix."""
        endpoint = self._endpoint(name)
        model = endpoint.model
        if model is None:
            raise ValueError(
                f"endpoint {name!r} was registered with a bare predict "
                "callable — weight swaps need `model=`"
            )
        if not params:
            raise ValueError("swap_weights needs at least one operand")
        with self._swap_lock:
            hook = getattr(model, "swap_weights_", None)
            if hook is not None:
                old = hook(params)
            else:
                old = {}
                for key, new in params.items():
                    if not hasattr(model, key):
                        raise ValueError(
                            f"swap_weights({name!r}): model has no operand {key!r}"
                        )
                    cur = getattr(model, key)
                    _check_swap_compat(name, key, cur, new)
                    old[key] = cur
                for key, new in params.items():
                    setattr(model, key, new)
        _STATS["swaps"] += 1
        telemetry.record_event(
            "serving_swap", endpoint=name, engine=self.name, params=sorted(params)
        )
        return old

    # -- request path ---------------------------------------------------

    def submit(
        self,
        name: str,
        x: Any,
        *,
        priority: str = "normal",
        deadline_s: Optional[float] = None,
    ) -> Future:
        """Admit + queue one request; resolves to the caller's rows only.

        ``priority`` picks the SLO class (``"high"``/``"normal"``/
        ``"low"`` by default — low sheds first under queue pressure) and
        ``deadline_s`` sets the *client* deadline: a request still queued
        when it lapses is shed at flush (reason ``expired``) instead of
        computing an answer nobody is waiting for.

        Raises :class:`~heat_tpu.serving.admission.RequestRejected` when
        shed — the documented fast-fail, never a hang."""
        endpoint = self._endpoint(name)
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        batch = np.asarray(x, dtype=endpoint.dtype)
        if batch.ndim == 1:
            batch = batch.reshape(1, -1)
        if batch.ndim != 2 or batch.shape[1] != endpoint.feature_dim:
            raise ValueError(
                f"endpoint {name!r} serves (rows, {endpoint.feature_dim}) "
                f"requests, got shape {np.shape(x)}"
            )
        rows = int(batch.shape[0])
        if rows == 0:
            raise ValueError("empty request")
        try:
            if self._closed:
                raise RequestRejected("closed", None, "serving engine is closed")
            if rows > endpoint.max_batch:
                raise RequestRejected(
                    "too_large",
                    None,
                    f"{rows} rows exceed endpoint max batch {endpoint.max_batch} "
                    "(split oversized requests client-side)",
                )
            self.admission.admit(name, rows, batch.nbytes, priority=priority)
        except RequestRejected as exc:
            _STATS["rejected"] += 1
            _STATS["shed"][exc.reason] += 1
            _bump(_STATS["shed_by_class"], priority)
            telemetry.record_event(
                "serving_shed",
                endpoint=name,
                reason=exc.reason,
                rows=rows,
                priority=priority,
                retry_after_s=exc.retry_after_s,
            )
            raise
        _STATS["accepted"] += 1
        _bump(_STATS["accepted_by_class"], priority)
        now = time.perf_counter()
        request = Request(
            endpoint=name,
            payload=batch,
            rows=rows,
            t0=now,
            deadline=now + endpoint.max_delay_s,
            priority=priority,
            client_deadline=None if deadline_s is None else now + float(deadline_s),
        )
        self._batcher.enqueue(request, endpoint.max_batch)
        return request.future

    def predict(
        self,
        name: str,
        x: Any,
        timeout: Optional[float] = 30.0,
        *,
        priority: str = "normal",
    ) -> np.ndarray:
        """Blocking convenience: ``submit(...).result(timeout)``.

        The timeout doubles as the client deadline — a request that
        cannot flush in time is shed ``expired`` at flush, not left
        queued (and admitted) behind the caller's back."""
        return self.submit(name, x, priority=priority, deadline_s=timeout).result(
            timeout
        )

    # -- batch execution (batcher worker thread) ------------------------

    def _endpoint(self, name: str) -> Endpoint:
        with self._lock:
            endpoint = self._endpoints.get(name)
        if endpoint is None:
            raise KeyError(
                f"unknown serving endpoint {name!r}; registered: {list(self.endpoints())}"
            )
        return endpoint

    def _get_step(self, endpoint: Endpoint, bucket: int) -> _Step:
        key = (endpoint.name, bucket)
        with self._lock:
            step = self._steps.get(key)
            if step is not None:
                _STATS["step_hits"] += 1
                return step
            _STATS["step_compiles"] += 1
            fp = telemetry.fingerprint(
                (
                    "serving",
                    endpoint.name,
                    bucket,
                    endpoint.feature_dim,
                    str(endpoint.dtype),
                    endpoint.split,
                )
            )
            telemetry.ensure_program(
                fp,
                kind="serving_step",
                endpoint=endpoint.name,
                bucket=bucket,
                feature_dim=endpoint.feature_dim,
            )

            def run(batch: np.ndarray, _ep: Endpoint = endpoint) -> np.ndarray:
                x = factories.array(batch, split=_ep.split)
                out = _ep.predict(x)
                return out.numpy() if hasattr(out, "numpy") else np.asarray(out)

            step = _Step(run, fp, bucket)
            self._steps[key] = step
        telemetry.record_event(
            "serving_compile", endpoint=endpoint.name, bucket=bucket, fingerprint=fp
        )
        return step

    def _drop_expired(self, name: str, requests: Sequence[Request]) -> List[Request]:
        """Shed requests whose *client* deadline lapsed while queued —
        their callers have already timed out, so computing them is dead
        work that only adds latency for live requests behind them."""
        now = time.perf_counter()
        live: List[Request] = []
        for request in requests:
            if request.client_deadline is None or now < request.client_deadline:
                live.append(request)
                continue
            try:
                request.future.set_exception(
                    RequestRejected(
                        "expired",
                        None,
                        f"client deadline passed "
                        f"{now - request.client_deadline:.3f}s before flush",
                    )
                )
            except InvalidStateError:
                pass
            self.admission.release(request.rows)
            _STATS["shed"]["expired"] += 1
            _bump(_STATS["shed_by_class"], request.priority)
            telemetry.record_event(
                "serving_expired",
                endpoint=name,
                rows=request.rows,
                priority=request.priority,
            )
        return live

    def _execute(self, name: str, requests: Sequence[Request], cause: str) -> None:
        endpoint = self._endpoint(name)
        requests = self._drop_expired(name, requests)
        if not requests:
            return
        rows = sum(r.rows for r in requests)
        try:
            guard.fire("serving.step")
            if self.name:
                guard.fire(f"serving.step.{self.name}")
            bucket = endpoint.bucket_for(rows)
            batch = np.zeros((bucket, endpoint.feature_dim), dtype=endpoint.dtype)
            offset = 0
            for request in requests:
                batch[offset : offset + request.rows] = request.payload
                offset += request.rows
            _STATS["batches"] += 1
            _STATS["padded_rows"] += bucket - rows
            _STATS["flush_cause"][cause] += 1
            step = self._get_step(endpoint, bucket)
            with telemetry.span(
                "serving.batch",
                endpoint=name,
                bucket=bucket,
                rows=rows,
                requests=len(requests),
                cause=cause,
            ):
                t0 = time.perf_counter()
                # swaps exchange operands under this lock, so a batch
                # reads either all-old or all-new weights — never a tear
                with self._swap_lock:
                    out = step.run(batch)
                duration = time.perf_counter() - t0
            telemetry.record_timing(step.fingerprint, duration)
            telemetry.program_hit(step.fingerprint)
            # streamed-corpus models (KNeighborsClassifier.fit_stream)
            # measure per-pass I/O overlap; surface it on the serving
            # flight recorder next to the batch that paid for it
            stream_rep = getattr(endpoint.model, "last_stream_report", None)
            if stream_rep:
                telemetry.record_event(
                    "serving_stream", endpoint=name, bucket=bucket,
                    **stream_rep,
                )
        except BaseException as exc:  # noqa: BLE001 — every future must resolve
            for request in requests:
                try:
                    request.future.set_exception(exc)
                except InvalidStateError:
                    pass
            self.admission.release(rows)
            _STATS["step_errors"] += 1
            telemetry.record_event(
                "serving_error", endpoint=name, engine=self.name, error=repr(exc)
            )
            # a failing step is liveness, not a stall: this worker is
            # alive and resolving futures.  Without the beat, a burst of
            # consecutive step errors latched `stalled` (no successful
            # batch ever called note_progress) and shed all traffic from
            # a live worker until one batch happened to succeed.
            self.admission.note_progress()
            if self._detector is not None:
                self._detector.beat()
            return
        offset = 0
        done = time.perf_counter()
        reservoir = _LATENCIES.setdefault(
            self._lat_key(name), deque(maxlen=_LATENCY_SAMPLES)
        )
        for request in requests:
            try:
                request.future.set_result(out[offset : offset + request.rows])
            except InvalidStateError:
                pass
            offset += request.rows
            reservoir.append(done - request.t0)
            _STATS["batched"] += 1
        self.admission.release(rows)
        self.admission.note_progress()
        if self._detector is not None:
            self._detector.beat()

    # -- introspection / lifecycle --------------------------------------

    def _lat_key(self, name: str) -> str:
        return f"{self.name}:{name}" if self.name else name

    def latency(self, name: str) -> Optional[Dict[str, float]]:
        """This engine's p50/p99 reservoir snapshot for endpoint ``name``
        (``None`` before the first served batch) — named engines (fleet
        replicas) keep per-replica reservoirs, so the router routes on
        each replica's own percentiles."""
        return _latency_view().get(self._lat_key(name))

    def busy(self) -> int:
        """Queued + in-flight work (see :meth:`DynamicBatcher.busy`)."""
        return self._batcher.busy()

    def in_flight(self) -> int:
        """Batches executing right now (queued rows excluded — see
        :meth:`DynamicBatcher.in_flight`)."""
        return self._batcher.in_flight()

    @property
    def detector(self):
        """The attached :class:`~heat_tpu.utils.fault.StallDetector`
        (``None`` when running without a watchdog)."""
        return self._detector

    def stats(self) -> Dict[str, Any]:
        """Live ``serving`` counter snapshot incl. latency percentiles."""
        return telemetry.snapshot_group("serving")

    def close(self, *, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful shutdown: shed new work, flush or cancel the queue,
        stop the worker.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.admission.begin_drain()
        drained = True
        if drain:
            drained = self._batcher.drain(timeout=timeout)
        for request in self._batcher.cancel_pending():
            try:
                request.future.set_exception(
                    RequestRejected("closed", None, "engine closed before execution")
                )
            except InvalidStateError:
                pass
            self.admission.release(request.rows)
        self._batcher.stop()
        self.admission.close()
        self._detector = None
        _STATS["drains"] += 1
        telemetry.record_event(
            "serving_drain", drained=bool(drained), endpoints=len(self._endpoints)
        )

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
