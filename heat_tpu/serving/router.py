"""Fault-tolerant fleet router: health-checked replicas, failover,
SLO-aware dispatch, and zero-downtime weight swaps (ISSUE 18).

A :class:`ServingFleet` fronts N :class:`~heat_tpu.serving.engine
.ServingEngine` replicas — each with its own admission gate, batcher
worker, and :class:`~heat_tpu.utils.fault.StallDetector` — behind one
``submit``/``predict`` surface:

* **placement** — a consistent-hash ring (SHA-1, virtual nodes) maps a
  request key to its *home* replica, so repeat keys hit warm caches;
  when the home's load (queued rows + in-flight batches over its queue
  bound) crosses ``spill_load``, the request spills to the least-loaded
  healthy sibling instead of queueing behind a hot spot.
* **health** — per-replica circuit breaker driven by *real* signals:
  the replica's StallDetector subscriber plane (a stall on a busy
  replica ejects it; a stall on an idle one is just quiet and re-arms
  the clock), consecutive step-error bursts, and admission sheds.
  States run healthy → degraded → ejected → half-open → healthy; an
  ejected replica re-enters only after a **probation probe** (one real
  request through the full stack) succeeds.
* **failover** — a replica failure or stall mid-flight re-dispatches
  the request to a healthy sibling: callers see added latency, never a
  lost future.  :class:`RequestRejected` with ``retry_after_s`` gets
  jittered exponential backoff; both paths are bounded by
  ``max_retries`` per request and a fleet-wide token **retry budget**
  (refilled by successes) so a meltdown cannot amplify itself.
* **swaps** — :meth:`ServingFleet.rolling_swap` promotes new weights
  canary-first with health-gated advance and automatic rollback on
  probe error or latency regression; each replica's
  ``engine.swap_weights`` exchanges operands under the step lock with
  **zero step compiles** (a republished checkpoint is new operands, not
  a retrace).
* **tuning** — per-replica autotune caches fold continuously via
  :func:`heat_tpu.core.autotune.merge` on the router's housekeeping
  thread, so every replica warm-starts from the fleet's best timings.

Telemetry: the ``router`` counter group (dispatch/spill/failover/retry,
circuit transitions, swap outcomes) exports as ``heat_tpu_router_*``
gauges; flight-recorder events ``router_health`` / ``router_failover``
/ ``router_probe`` / ``router_swap`` / ``router_rollback`` name every
transition.  Failure paths are driven for real by
:class:`~heat_tpu.utils.fault.FaultInjector` sites ``serving.replica``
(per dispatch) and ``serving.step`` (per batch) — no mocks.
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import random
import threading
import time
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Container,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

import numpy as np

from ..core import guard, telemetry
from ..utils import fault
from .admission import AdmissionController, RequestRejected
from .engine import ServingEngine

__all__ = ["Replica", "ServingFleet"]

#: replica health states (strings so snapshots/events stay greppable)
HEALTHY = "healthy"
DEGRADED = "degraded"
EJECTED = "ejected"
HALF_OPEN = "half_open"

_STATS = telemetry.register_group(
    "router",
    {
        "dispatched": 0,
        "dispatched_by_class": {"high": 0, "normal": 0, "low": 0},
        "spills": 0,
        "retries": 0,
        "failovers": 0,
        "backoffs": 0,
        "rejected": 0,
        "late_results": 0,
        "lost_futures": 0,
        "retry_budget_exhausted": 0,
        "degradations": 0,
        "ejections": 0,
        "half_opens": 0,
        "probes": 0,
        "probe_failures": 0,
        "recoveries": 0,
        "swaps": 0,
        "rollbacks": 0,
        "cache_merges": 0,
    },
)


def _bump(counter: Dict[str, int], key: str) -> None:
    counter[key] = counter.get(key, 0) + 1


def _hash64(token: str) -> int:
    return int.from_bytes(hashlib.sha1(token.encode()).digest()[:8], "big")


class Replica:
    """One engine plus its circuit-breaker bookkeeping (router-owned)."""

    def __init__(self, name: str, engine: ServingEngine, detector):
        self.name = name
        self.engine = engine
        self.detector = detector
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.ejected_until = 0.0
        self.probe_in_flight = False

    def load(self) -> float:
        """Queued rows + in-flight batches over the queue bound — the
        spill signal.  In-flight batches count so a replica grinding a
        slow step looks loaded even with an empty queue."""
        admission = self.engine.admission
        return (admission.queued_rows + self.engine.busy()) / max(
            1, admission.max_queue_rows
        )

    def snapshot(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "load": round(self.load(), 4),
            "consecutive_failures": self.consecutive_failures,
        }


@dataclass
class _FleetRequest:
    """One caller-visible request; may ride several replica dispatches."""

    endpoint: str
    payload: Any
    priority: str
    deadline: Optional[float]  # absolute perf_counter instant, or None
    key: Any
    future: Future = field(default_factory=Future)
    attempts: int = 0
    tried: Set[str] = field(default_factory=set)


class ServingFleet:
    """N health-checked serving replicas behind one front door.

    >>> fleet = ServingFleet(replicas=4)
    >>> fleet.register("centers", models=[m0, m1, m2, m3],
    ...                feature_dim=32, warm=True)
    >>> y = fleet.predict("centers", x)                  # routed
    >>> fut = fleet.submit("centers", x, priority="low", deadline_s=0.5)
    >>> report = fleet.rolling_swap("centers", {"w": new_w}, canary=1)

    Usable as a context manager; exit drains every replica.
    """

    def __init__(
        self,
        replicas: Any = 2,
        *,
        stall_timeout_s: float = 1.0,
        error_threshold: int = 3,
        cooldown_s: float = 0.5,
        spill_load: float = 0.75,
        max_retries: int = 2,
        retry_budget: float = 32.0,
        retry_refill: float = 0.1,
        backoff_base_s: float = 0.01,
        backoff_max_s: float = 0.25,
        probe_timeout_s: float = 5.0,
        vnodes: int = 32,
        admission_kwargs: Optional[Dict[str, Any]] = None,
        default_max_delay_s: float = 0.005,
        autotune_caches: Optional[Sequence[str]] = None,
        autotune_merge_out: Optional[str] = None,
        merge_every_s: float = 2.0,
    ):
        if error_threshold < 1:
            raise ValueError(f"error_threshold must be >= 1, got {error_threshold}")
        self.stall_timeout_s = float(stall_timeout_s)
        self.error_threshold = int(error_threshold)
        self.cooldown_s = float(cooldown_s)
        self.spill_load = float(spill_load)
        self.max_retries = int(max_retries)
        self.retry_budget = float(retry_budget)
        self.retry_refill = float(retry_refill)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._autotune_caches = list(autotune_caches or [])
        self._autotune_merge_out = autotune_merge_out
        self._merge_every_s = float(merge_every_s)
        self._merge_elapsed = 0.0

        if isinstance(replicas, int):
            if replicas < 1:
                raise ValueError(f"need at least one replica, got {replicas}")
            engines = [
                ServingEngine(
                    name=f"r{i}",
                    admission=AdmissionController(**(admission_kwargs or {})),
                    default_max_delay_s=default_max_delay_s,
                )
                for i in range(replicas)
            ]
        else:
            engines = list(replicas)
            if not engines:
                raise ValueError("need at least one replica engine")
            for i, engine in enumerate(engines):
                if not getattr(engine, "name", ""):
                    engine.name = f"r{i}"
            names = [engine.name for engine in engines]
            if len(set(names)) != len(names):
                raise ValueError(f"replica engine names must be unique, got {names}")

        self._lock = threading.RLock()
        self._closed = False
        self._retry_tokens = self.retry_budget
        # deterministic jitter: count-deterministic like the injector's
        # fault schedules, so CI backoff traces replay bit-for-bit
        self._rng = random.Random(fault.FaultInjector().seed or 20)
        self._endpoints: Dict[str, Dict[str, Any]] = {}
        self._inflight: Dict[Tuple[int, str], Tuple[_FleetRequest, "Replica"]] = {}
        self._timers: Dict[threading.Timer, _FleetRequest] = {}
        self._keyseq = itertools.count()

        self._replicas: List[Replica] = []
        for engine in engines:
            detector = engine.detector
            if detector is None:
                detector = fault.StallDetector(timeout=self.stall_timeout_s)
                engine.attach_stall_detector(detector)
                detector.start()
            replica = Replica(engine.name, engine, detector)
            detector.subscribe(self._detector_handler(replica))
            self._replicas.append(replica)

        self._ring: List[Tuple[int, Replica]] = []
        for replica in self._replicas:
            for v in range(max(1, int(vnodes))):
                self._ring.append((_hash64(f"{replica.name}#{v}"), replica))
        self._ring.sort(key=lambda pair: pair[0])
        self._ring_keys = [h for h, _ in self._ring]

        self._stop = threading.Event()
        self._housekeeper = threading.Thread(
            target=self._housekeep, name="heat-tpu-fleet-housekeeper", daemon=True
        )
        self._housekeeper.start()

    # -- registry -------------------------------------------------------

    @property
    def replicas(self) -> Tuple[Replica, ...]:
        return tuple(self._replicas)

    def register(
        self,
        name: str,
        model: Any = None,
        *,
        models: Optional[Sequence[Any]] = None,
        predict: Optional[Callable[[Any], Any]] = None,
        feature_dim: int,
        dtype: Any = np.float32,
        **kwargs: Any,
    ) -> None:
        """Register endpoint ``name`` on every replica.

        Pass ``models=`` (one fitted model per replica) for rolling
        swaps — a single shared ``model`` object serves fine but cannot
        canary (swapping one replica would swap them all), and
        ``rolling_swap`` refuses it.  Remaining ``kwargs`` forward to
        :meth:`ServingEngine.register` (buckets, ``warm=``, ...)."""
        if models is not None and model is not None:
            raise ValueError("pass `model=` or `models=`, not both")
        if models is not None and len(models) != len(self._replicas):
            raise ValueError(
                f"models= needs one model per replica "
                f"({len(self._replicas)}), got {len(models)}"
            )
        for i, replica in enumerate(self._replicas):
            replica.engine.register(
                name,
                models[i] if models is not None else model,
                predict=predict,
                feature_dim=feature_dim,
                dtype=dtype,
                **kwargs,
            )
        self._endpoints[name] = {
            "feature_dim": int(feature_dim),
            "dtype": np.dtype(dtype),
            "shared_model": models is None and model is not None,
        }

    def endpoints(self) -> Tuple[str, ...]:
        return tuple(sorted(self._endpoints))

    def warmup(self, name: str) -> int:
        """Warm every replica's bucket ladder; returns buckets/replica."""
        buckets = 0
        for replica in self._replicas:
            buckets = replica.engine.warmup(name)
        return buckets

    # -- request path ---------------------------------------------------

    def submit(
        self,
        name: str,
        x: Any,
        *,
        priority: str = "normal",
        deadline_s: Optional[float] = None,
        key: Any = None,
    ) -> Future:
        """Route one request; the Future resolves from whichever replica
        finally serves it.  ``key`` pins ring placement (e.g. a user or
        shard id) — omitted, placement round-robins.  Raises
        :class:`RequestRejected` only when no dispatch is possible at
        all; transient sheds are retried/failed-over internally."""
        if self._closed:
            raise RequestRejected("closed", None, "serving fleet is closed")
        if name not in self._endpoints:
            raise KeyError(
                f"unknown fleet endpoint {name!r}; registered: {list(self.endpoints())}"
            )
        now = time.perf_counter()
        request = _FleetRequest(
            endpoint=name,
            payload=x,
            priority=priority,
            deadline=None if deadline_s is None else now + float(deadline_s),
            key=key if key is not None else next(self._keyseq),
        )
        self._dispatch(request)
        return request.future

    def predict(
        self,
        name: str,
        x: Any,
        timeout: Optional[float] = 30.0,
        *,
        priority: str = "normal",
        key: Any = None,
    ) -> np.ndarray:
        """Blocking convenience; the timeout is also the client deadline."""
        return self.submit(
            name, x, priority=priority, deadline_s=timeout, key=key
        ).result(timeout)

    # -- placement ------------------------------------------------------

    def _ring_order(self, key: Any) -> List[Replica]:
        """Replicas in ring order starting at ``key``'s successor."""
        start = bisect.bisect_right(self._ring_keys, _hash64(str(key)))
        seen: Set[str] = set()
        order: List[Replica] = []
        for i in range(len(self._ring)):
            _, replica = self._ring[(start + i) % len(self._ring)]
            if replica.name not in seen:
                seen.add(replica.name)
                order.append(replica)
                if len(order) == len(self._replicas):
                    break
        return order

    def _route(
        self, request: _FleetRequest, exclude: Container[str] = ()
    ) -> Optional[Replica]:
        with self._lock:
            # degraded replicas still serve their home traffic — the
            # state is a warning, and starving them would freeze the
            # consecutive-failure counter short of the breaker threshold
            # (and the success that would clear the state).  Only
            # ejected/half-open replicas are benched.  ``exclude`` holds
            # this attempt's back-pressure (a replica that just shed
            # queue_full/hbm_pressure) — transient, unlike ``tried``.
            candidates = [
                replica
                for replica in self._ring_order(request.key)
                if replica.state in (HEALTHY, DEGRADED)
                and replica.name not in request.tried
                and replica.name not in exclude
            ]
            if not candidates:
                return None
            home = candidates[0]
            if len(candidates) > 1 and home.load() >= self.spill_load:
                healthy = [r for r in candidates if r.state == HEALTHY]
                alternate = min(healthy or candidates, key=lambda r: r.load())
                if alternate is not home and alternate.load() < home.load():
                    _STATS["spills"] += 1
                    return alternate
            return home

    # -- dispatch / failover --------------------------------------------

    def _dispatch(
        self, request: _FleetRequest, exclude: Container[str] = ()
    ) -> None:
        if request.future.done():
            return
        replica = self._route(request, exclude)
        if replica is None:
            self._fail(
                request,
                RequestRejected(
                    "unavailable",
                    self.cooldown_s,
                    "no healthy replica available (ejected or already tried)",
                ),
            )
            return
        request.attempts += 1
        _STATS["dispatched"] += 1
        _bump(_STATS["dispatched_by_class"], request.priority)
        try:
            guard.fire("serving.replica")
            guard.fire(f"serving.replica.{replica.name}")
            remaining = None
            if request.deadline is not None:
                remaining = request.deadline - time.perf_counter()
                if remaining <= 0:
                    raise RequestRejected(
                        "expired", None, "client deadline passed before dispatch"
                    )
            engine_future = replica.engine.submit(
                request.endpoint,
                request.payload,
                priority=request.priority,
                deadline_s=remaining,
            )
        except RequestRejected as exc:
            self._on_reject(request, replica, exc)
            return
        except Exception as exc:  # noqa: BLE001 — injected/replica faults
            self._record_failure(replica, f"dispatch: {exc!r}")
            request.tried.add(replica.name)
            self._retry(request, exc, failover=True)
            return
        with self._lock:
            self._inflight[(id(request), replica.name)] = (request, replica)
        engine_future.add_done_callback(
            lambda f, req=request, rep=replica: self._on_result(req, rep, f)
        )

    def _on_result(self, request: _FleetRequest, replica: Replica, engine_future: Future) -> None:
        with self._lock:
            self._inflight.pop((id(request), replica.name), None)
        exc = engine_future.exception()
        if exc is None:
            self._record_success(replica)
            try:
                request.future.set_result(engine_future.result())
            except InvalidStateError:
                # already failed-over elsewhere; the slow twin landed late
                _STATS["late_results"] += 1
            return
        if request.future.done():
            _STATS["late_results"] += 1
            if not isinstance(exc, RequestRejected):
                self._record_failure(replica, repr(exc))
            return
        if isinstance(exc, RequestRejected):
            self._on_reject(request, replica, exc)
        else:
            self._record_failure(replica, repr(exc))
            request.tried.add(replica.name)
            self._retry(request, exc, failover=True)

    def _on_reject(
        self, request: _FleetRequest, replica: Replica, exc: RequestRejected
    ) -> None:
        if exc.reason in ("expired", "too_large", "closed"):
            # retrying cannot help: the deadline is gone, the shape is
            # wrong, or the replica is shutting down for good
            self._fail(request, exc)
            return
        self._record_shed(replica, exc.reason)
        with self._lock:
            sibling = any(
                r is not replica
                and r.state in (HEALTHY, DEGRADED)
                and r.name not in request.tried
                for r in self._replicas
            )
        # back-pressure, not failure: always BACK OFF (an immediate hop
        # during a load spike touching every replica would burn the
        # whole retry allowance in milliseconds), and when a sibling
        # exists, exclude the shedding replica from the next attempt
        # only — marking it ``tried`` for good would turn that same
        # spike into a terminal `unavailable`.
        exclude = {replica.name} if sibling else ()
        self._retry(request, exc, failover=False, exclude=exclude)

    def _retry(
        self,
        request: _FleetRequest,
        exc: Exception,
        *,
        failover: bool,
        exclude: Container[str] = (),
        charge: bool = True,
    ) -> None:
        if request.future.done():
            return
        if request.attempts > self.max_retries:
            self._fail(request, exc)
            return
        with self._lock:
            if self._closed:
                self._fail(request, exc)
                return
            # ``charge=False`` is the evacuation path: when a replica
            # dies mid-flight, EVERY victim re-dispatches regardless of
            # the token bucket — a mass failover after one failure is
            # the never-lose-a-future guarantee, not a retry storm.
            # The bucket throttles repeated per-request retries only.
            if charge:
                if self._retry_tokens < 1.0:
                    _STATS["retry_budget_exhausted"] += 1
                    self._fail(request, exc)
                    return
                self._retry_tokens -= 1.0
        _STATS["retries"] += 1
        if failover:
            _STATS["failovers"] += 1
            telemetry.record_event(
                "router_failover",
                endpoint=request.endpoint,
                attempt=request.attempts,
                error=repr(exc),
            )
            self._dispatch(request, exclude)
            return
        _STATS["backoffs"] += 1
        wait = min(
            self.backoff_max_s,
            self.backoff_base_s * (2 ** max(0, request.attempts - 1)),
        ) * (0.5 + self._rng.random())
        if isinstance(exc, RequestRejected) and exc.retry_after_s:
            wait = max(wait, exc.retry_after_s)
        timer_box: Dict[str, threading.Timer] = {}

        def _fire() -> None:
            with self._lock:
                self._timers.pop(timer_box["t"], None)
            self._dispatch(request, exclude)

        timer = threading.Timer(wait, _fire)
        timer.daemon = True
        timer_box["t"] = timer
        with self._lock:
            if self._closed:
                self._fail(request, exc)
                return
            self._timers[timer] = request
        timer.start()

    def _fail(self, request: _FleetRequest, exc: Exception) -> None:
        _STATS["rejected"] += 1
        try:
            request.future.set_exception(exc)
        except InvalidStateError:
            _STATS["late_results"] += 1

    # -- health state machine -------------------------------------------

    def _set_state(self, replica: Replica, state: str, reason: str) -> None:
        # caller holds self._lock
        previous = replica.state
        if previous == state:
            return
        replica.state = state
        telemetry.record_event(
            "router_health",
            replica=replica.name,
            previous=previous,
            state=state,
            reason=reason,
        )

    def _eject_locked(self, replica: Replica, reason: str) -> None:
        if replica.state != EJECTED:
            _STATS["ejections"] += 1
        replica.ejected_until = time.perf_counter() + self.cooldown_s
        self._set_state(replica, EJECTED, reason)

    def _record_failure(self, replica: Replica, reason: str) -> None:
        with self._lock:
            replica.consecutive_failures += 1
            if replica.state == HALF_OPEN:
                # probation failed — back to the bench, fresh cooldown
                self._eject_locked(replica, f"half-open failure: {reason}")
            elif replica.consecutive_failures >= self.error_threshold:
                self._eject_locked(
                    replica,
                    f"{replica.consecutive_failures} consecutive failures: {reason}",
                )
            elif replica.state == HEALTHY:
                _STATS["degradations"] += 1
                self._set_state(replica, DEGRADED, reason)

    def _record_shed(self, replica: Replica, reason: str) -> None:
        with self._lock:
            if replica.state == HEALTHY:
                _STATS["degradations"] += 1
                self._set_state(replica, DEGRADED, f"shed: {reason}")

    def _record_success(self, replica: Replica) -> None:
        with self._lock:
            replica.consecutive_failures = 0
            self._retry_tokens = min(
                self.retry_budget, self._retry_tokens + self.retry_refill
            )
            if replica.state == DEGRADED:
                self._set_state(replica, HEALTHY, "served")

    def _detector_handler(self, replica: Replica):
        def _on_event(kind: str, info: Dict[str, Any]) -> None:
            if kind != "stall":
                return
            if replica.engine.in_flight() == 0:
                # nothing is executing ⇒ the step can't be wedged.  An
                # idle replica emits no heartbeats, and queued rows
                # waiting out an endpoint's ``max_delay_s`` flush window
                # are batching latency, not a hang.  Clear the engine's
                # stall latch and re-arm the clock — otherwise traffic
                # hashing elsewhere would eject every idle sibling, and
                # a long flush window would eject its own replica.
                replica.engine.admission.note_progress()
                replica.detector.beat()
                return
            with self._lock:
                self._eject_locked(
                    replica, f"stall ({info.get('quiet_s', '?')}s quiet)"
                )
                victims = [
                    req
                    for (req, rep) in self._inflight.values()
                    if rep is replica and not req.future.done()
                ]
            for victim in victims:
                victim.tried.add(replica.name)
                self._retry(
                    victim,
                    RuntimeError(f"replica {replica.name} stalled mid-flight"),
                    failover=True,
                    charge=False,
                )

        return _on_event

    # -- housekeeping: probes + autotune folding ------------------------

    def _housekeep(self) -> None:
        poll = max(0.01, min(0.05, self.cooldown_s / 4))
        while not self._stop.wait(poll):
            now = time.perf_counter()
            to_probe: List[Replica] = []
            with self._lock:
                for replica in self._replicas:
                    if (
                        replica.state == EJECTED
                        and now >= replica.ejected_until
                        and not replica.probe_in_flight
                    ):
                        _STATS["half_opens"] += 1
                        self._set_state(replica, HALF_OPEN, "cooldown elapsed")
                        replica.probe_in_flight = True
                        to_probe.append(replica)
                    elif replica.state == HALF_OPEN and not replica.probe_in_flight:
                        replica.probe_in_flight = True
                        to_probe.append(replica)
            for replica in to_probe:
                self._probe(replica)
            self._merge_elapsed += poll
            if (
                self._autotune_merge_out
                and self._autotune_caches
                and self._merge_elapsed >= self._merge_every_s
            ):
                self._merge_elapsed = 0.0
                self._merge_caches()

    def _probe(self, replica: Replica) -> None:
        """One real request through the full stack decides probation."""
        if not self._endpoints:
            # nothing registered yet — nothing the replica could fail at
            with self._lock:
                replica.probe_in_flight = False
                replica.consecutive_failures = 0
                self._set_state(replica, HEALTHY, "no endpoints to probe")
            return
        name = next(iter(self._endpoints))
        meta = self._endpoints[name]
        probe_x = np.zeros((1, meta["feature_dim"]), dtype=meta["dtype"])
        _STATS["probes"] += 1
        try:
            replica.engine.predict(
                name, probe_x, timeout=self.probe_timeout_s, priority="high"
            )
        except Exception as exc:  # noqa: BLE001 — any probe failure re-ejects
            _STATS["probe_failures"] += 1
            telemetry.record_event(
                "router_probe", replica=replica.name, ok=False, error=repr(exc)
            )
            with self._lock:
                replica.probe_in_flight = False
                self._eject_locked(replica, f"probe failed: {exc!r}")
        else:
            telemetry.record_event("router_probe", replica=replica.name, ok=True)
            with self._lock:
                replica.probe_in_flight = False
                replica.consecutive_failures = 0
                _STATS["recoveries"] += 1
                self._set_state(replica, HEALTHY, "probe succeeded")

    def _merge_caches(self) -> None:
        from ..core import autotune

        try:
            autotune.merge(self._autotune_caches, self._autotune_merge_out)
        except Exception as exc:  # noqa: BLE001 — folding is best-effort
            telemetry.record_event("router_merge_error", error=repr(exc))
        else:
            _STATS["cache_merges"] += 1

    # -- zero-downtime weight swaps -------------------------------------

    def rolling_swap(
        self,
        name: str,
        params: Dict[str, Any],
        *,
        canary: int = 1,
        probes: int = 3,
        regression_ratio: float = 5.0,
    ) -> Dict[str, Any]:
        """Fleet-wide weight swap, canary-first, with automatic rollback.

        Swaps ``canary`` replicas, then probes each swapped replica with
        ``probes`` real single-row requests; advance is health-gated — a
        probe error, or a median probe wall above ``regression_ratio ×``
        the replica's pre-swap p50 (reservoir when warm, else measured),
        rolls **every** swapped replica back to its old operands and
        returns ``rolled_back=True`` with the reason.  Succeeding, every
        replica serves the new weights with zero step compiles."""
        if name not in self._endpoints:
            raise KeyError(f"unknown fleet endpoint {name!r}")
        if self._endpoints[name]["shared_model"]:
            raise ValueError(
                f"rolling_swap({name!r}): replicas share one model object — "
                "register with models=[...] (one per replica) so a canary "
                "swap does not swap the whole fleet at once"
            )
        if not 1 <= canary <= len(self._replicas):
            raise ValueError(
                f"canary must be in [1, {len(self._replicas)}], got {canary}"
            )
        swapped: List[Tuple[Replica, Dict[str, Any]]] = []
        report: Dict[str, Any] = {
            "endpoint": name,
            "canary": canary,
            "swapped": [],
            "rolled_back": False,
            "reason": None,
        }
        for index, replica in enumerate(self._replicas):
            baseline = replica.engine.latency(name)
            baseline_s = baseline["p50_s"] if baseline else None
            if baseline_s is None:
                baseline_s = self._probe_wall(replica, name, probes)
            old = replica.engine.swap_weights(name, params)
            swapped.append((replica, old))
            _STATS["swaps"] += 1
            telemetry.record_event(
                "router_swap",
                endpoint=name,
                replica=replica.name,
                stage="canary" if index < canary else "fleet",
            )
            ok, why = True, None
            try:
                probe_s = self._probe_wall(replica, name, probes)
            except Exception as exc:  # noqa: BLE001 — probe errors gate advance
                ok, why = False, f"probe failed on {replica.name}: {exc!r}"
            else:
                # 100µs floor: a cold reservoir p50 of ~0 would flag any
                # real wall as a regression
                limit = regression_ratio * max(baseline_s, 1e-4)
                if probe_s > limit:
                    ok, why = False, (
                        f"latency regression on {replica.name}: probe p50 "
                        f"{probe_s:.6f}s > {regression_ratio:g}x baseline "
                        f"{baseline_s:.6f}s"
                    )
            if not ok:
                for back, old_params in reversed(swapped):
                    back.engine.swap_weights(name, old_params)
                _STATS["rollbacks"] += 1
                telemetry.record_event(
                    "router_rollback", endpoint=name, replica=replica.name, reason=why
                )
                report.update(rolled_back=True, reason=why, swapped=[])
                return report
            report["swapped"].append(replica.name)
        return report

    def _probe_wall(self, replica: Replica, name: str, probes: int) -> float:
        meta = self._endpoints[name]
        probe_x = np.zeros((1, meta["feature_dim"]), dtype=meta["dtype"])
        walls: List[float] = []
        for _ in range(max(1, int(probes))):
            t0 = time.perf_counter()
            replica.engine.predict(
                name, probe_x, timeout=self.probe_timeout_s, priority="high"
            )
            walls.append(time.perf_counter() - t0)
        walls.sort()
        return walls[len(walls) // 2]

    # -- introspection / lifecycle --------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Live ``router`` counters plus per-replica health/load."""
        snapshot = telemetry.snapshot_group("router")
        snapshot["replicas"] = {
            replica.name: replica.snapshot() for replica in self._replicas
        }
        return snapshot

    def close(self, *, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop housekeeping, fail queued backoff retries, drain every
        replica.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            timers = dict(self._timers)
            self._timers.clear()
        for timer, request in timers.items():
            timer.cancel()
            self._fail(
                request,
                RequestRejected("closed", None, "fleet closed before retry fired"),
            )
        self._stop.set()
        self._housekeeper.join(timeout=5.0)
        for replica in self._replicas:
            replica.detector.stop()
            replica.engine.close(drain=drain, timeout=timeout)

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.close()
        return False
