"""SPMD hazard analyzer: three tiers against the bug classes that are
catastrophic at mesh scale.

* :mod:`heat_tpu.analysis.lint` — AST rules HT001–HT005
  (``python -m heat_tpu.analysis --check``): raw env parses, unmeasured
  host syncs, rank-divergent branches gating collectives, orphan counter
  dicts, static use-after-donate.
* :mod:`heat_tpu.analysis.program_audit` — compiled-program auditor
  (``HEAT_TPU_AUDIT=1`` / ``hlo``) at the fusion/transport/overlap
  compile sites: donation-aliasing violations, host callbacks,
  unmodeled collectives; findings land as ``analysis_finding`` events
  and mark roofline rows audited-dirty.
* :mod:`heat_tpu.analysis.sanitize` — runtime sanitizer
  (``HEAT_TPU_SANITIZE=1``): donated-buffer poisoning (use-after-donate
  raises with the creation site) and the per-process collective-sequence
  fingerprint (the SPMD lockstep law).
"""

from . import lint, program_audit, sanitize
from .sanitize import UseAfterDonateError

__all__ = ["lint", "program_audit", "sanitize", "UseAfterDonateError"]
