"""Compiled-program audit tier of the SPMD hazard analyzer
(``HEAT_TPU_AUDIT=1``; ``HEAT_TPU_AUDIT=hlo`` adds the compiled-module
scan).

Hooked into the three compile sites — fusion's ``_run_many`` miss path,
transport's tiled programs, overlap's ring programs — each program is
audited ONCE per (kind, fingerprint), off the steady state:

* **use_after_donate** — an input buffer the sanitizer's poison ledger
  says was already donated to XLA (the auditor registers interest, so
  donation sites poison even when the raising sanitizer is off).
* **donation_unaliasable** — a ``donate_argnums`` input whose byte size
  matches no program output: XLA cannot alias it, so the donation buys
  nothing and the caller gave up a buffer for free (jax warns once,
  deep in the log; here it lands in the flight recorder with the
  cost-ledger fingerprint).
* **host_transfer** — callback primitives (``pure_callback`` /
  ``io_callback`` / debug prints) inside an engine program: a
  device-to-host round trip per dispatch that the roofline would
  mis-attribute.
* **unexpected_collective / unexpected_reshard** — collective
  primitives in a program the cost ledger modeled as local
  (``expect="none"``), or — under ``hlo`` mode — GSPMD-inserted
  resharding collectives (all-gather / all-to-all / collective-permute)
  in a fused program modeled as local-plus-reduce (``expect="reduce"``:
  the estimator prices trailing cross-shard reductions, so
  all-reduce-class ops are expected there and only data *rearrangement*
  flags).

Findings are recorded as ``analysis_finding`` flight-recorder events
carrying the cost-ledger fingerprint, so :func:`telemetry.roofline_report`
can mark audited-dirty rows — a row whose measured time includes an
unmodeled collective or host sync is not trustworthy attribution.
"""

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import telemetry
from . import sanitize

# ------------------------------------------------------------------- gating

_MODE_OVERRIDE: "List[Optional[str]]" = [None]

_VALID_MODES = ("off", "jaxpr", "hlo")


def mode() -> str:
    """``off`` | ``jaxpr`` | ``hlo`` (``HEAT_TPU_AUDIT``: unset/0 = off,
    1/on/jaxpr = jaxpr walk, hlo = jaxpr walk + compiled-module scan)."""
    if _MODE_OVERRIDE[0] is not None:
        return _MODE_OVERRIDE[0]
    raw = os.environ.get("HEAT_TPU_AUDIT", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return "off"
    if raw == "hlo":
        return "hlo"
    return "jaxpr"


def enabled() -> bool:
    return mode() != "off"


def set_mode(m: Optional[str]) -> Optional[str]:
    """Override the env toggle (``None`` restores env control).  Returns
    the previous override."""
    if m is not None and m not in _VALID_MODES:
        raise ValueError(f"audit mode must be one of {_VALID_MODES}, got {m!r}")
    prev = _MODE_OVERRIDE[0]
    _MODE_OVERRIDE[0] = m
    return prev


# donation sites poison for us even when the raising sanitizer is off
sanitize.register_interest(enabled)

# ----------------------------------------------------------------- findings

_FINDINGS: List[dict] = []
_BY_FP: Dict[str, List[dict]] = {}
_SEEN: set = set()

# named "audit", not "program_audit": heat_tpu_program_* is the reserved
# prometheus namespace for per-program labeled roofline gauges
_STATS = telemetry.register_group(
    "audit",
    {
        "audits": 0,      # programs walked (once per kind+fingerprint)
        "findings": 0,    # hazards recorded
        "audit_errors": 0,  # programs the walker could not trace
    },
)

_COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "all_gather_invariant", "all_to_all", "ppermute",
    "pmin", "pmax", "reduce_scatter", "psum_scatter", "pgather",
})
# all-reduce-class compiled ops are "modeled" for expect="reduce"
# programs (the fused-chain cost estimator prices trailing cross-shard
# reductions); data-rearrangement ops are never modeled there
_RESHARD_HLO = (
    "all-gather(", "all-gather-start(", "all-to-all(", "all-to-all-start(",
    "collective-permute(", "collective-permute-start(",
)
_ALL_HLO = _RESHARD_HLO + ("all-reduce(", "all-reduce-start(",
                           "reduce-scatter(")
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "callback", "debug_callback",
    "outside_call", "host_callback_call",
})


def findings(fp: Optional[str] = None) -> List[dict]:
    """All recorded findings, or just those for one fingerprint."""
    if fp is not None:
        return list(_BY_FP.get(fp, ()))
    return list(_FINDINGS)


def dirty_fingerprints() -> set:
    """Fingerprints with at least one finding — the roofline marks these
    rows audited-dirty."""
    return set(_BY_FP)


def reset() -> None:
    del _FINDINGS[:]
    _BY_FP.clear()
    _SEEN.clear()


def _record(kind: str, fp: Optional[str], rule: str, detail: str) -> dict:
    finding = {"kind": kind, "fingerprint": fp, "rule": rule,
               "detail": detail}
    _FINDINGS.append(finding)
    if fp is not None:
        _BY_FP.setdefault(fp, []).append(finding)
    _STATS["findings"] += 1
    telemetry.record_event(
        "analysis_finding", kind=kind, fingerprint=fp, rule=rule,
        detail=detail,
    )
    return finding


# -------------------------------------------------------------- jaxpr walk


def _walk_jaxpr(jaxpr, prims: set) -> None:
    for eqn in getattr(jaxpr, "eqns", ()):
        prims.add(eqn.primitive.name)
        for val in eqn.params.values():
            _walk_params(val, prims)


def _walk_params(val, prims: set) -> None:
    inner = getattr(val, "jaxpr", None)
    if inner is not None:  # ClosedJaxpr
        _walk_jaxpr(inner, prims)
        return
    if hasattr(val, "eqns"):  # raw Jaxpr
        _walk_jaxpr(val, prims)
        return
    if isinstance(val, (tuple, list)):
        for v in val:
            _walk_params(v, prims)


def _nbytes(shape, dtype) -> int:
    n = int(getattr(dtype, "itemsize", 0) or 0)
    for d in shape:
        n *= int(d)
    return n


# -------------------------------------------------------------------- audit


def audit_program(
    kind: str,
    fp: Optional[str],
    fn,
    args: Sequence,
    donate: Tuple[int, ...] = (),
    expect: str = "any",
) -> List[dict]:
    """Audit one compiled program; returns the findings it produced.

    ``fn`` is the (jitted or plain) callable about to run on ``args``;
    ``donate`` the positional donate_argnums; ``expect`` declares the
    collective contract the caller's cost model assumed: ``"any"``
    (transport/overlap — collectives are the point), ``"reduce"``
    (fused programs — trailing cross-shard reductions are modeled,
    resharding is not), ``"none"`` (modeled fully local)."""
    if not enabled():
        return []
    import jax

    got: List[dict] = []

    # (1) inputs already donated elsewhere — the poison ledger knows.
    # This check runs on EVERY call (dict lookups, cheap): the same
    # program fingerprint can be fed clean buffers on one call and a
    # donated one on the next, so it must not dedup with the walk below.
    for i, a in enumerate(args):
        entry = sanitize.poison_entry(a)
        if entry is not None:
            got.append(_record(
                kind, fp, "use_after_donate",
                f"input {i} was donated at {entry['donated']} "
                f"(buffer created at {entry['created']}) and is fed back "
                "into this program",
            ))

    # the program-structure walk is once per (kind, fingerprint) — off
    # the steady state
    key = (kind, fp) if fp is not None else (
        kind,
        tuple(
            (tuple(getattr(a, "shape", ())), str(getattr(a, "dtype", "?")))
            for a in args
        ),
        tuple(donate), expect,
    )
    if key in _SEEN:
        return got
    _SEEN.add(key)
    _STATS["audits"] += 1

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as err:  # an unauditable program must not block it
        _STATS["audit_errors"] += 1
        telemetry.record_event(
            "analysis_finding", kind=kind, fingerprint=fp,
            rule="audit_error", detail=str(err)[:200],
        )
        return got

    prims: set = set()
    _walk_jaxpr(closed.jaxpr, prims)

    # (2) host round trips inside the program
    callbacks = sorted(prims & _CALLBACK_PRIMS)
    if callbacks:
        got.append(_record(
            kind, fp, "host_transfer",
            f"callback primitive(s) {callbacks} force a device-to-host "
            "round trip per dispatch",
        ))

    # (3) trace-level collectives in a modeled-local program
    colls = sorted(prims & _COLLECTIVE_PRIMS)
    if expect == "none" and colls:
        got.append(_record(
            kind, fp, "unexpected_collective",
            f"collective primitive(s) {colls} in a program the cost "
            "ledger modeled as local",
        ))

    # (4) donation aliasing: a donated input must byte-match some output
    out_sizes = [
        _nbytes(getattr(av, "shape", ()), getattr(av, "dtype", None))
        for av in closed.out_avals
    ]
    for i in donate:
        if i >= len(args):
            continue
        a = args[i]
        nb = _nbytes(getattr(a, "shape", ()), getattr(a, "dtype", None))
        if nb not in out_sizes:
            got.append(_record(
                kind, fp, "donation_unaliasable",
                f"donated input {i} ({nb} bytes) matches no output "
                f"(outputs: {out_sizes}) — XLA cannot alias it; the "
                "buffer is given up for nothing",
            ))

    # (5) hlo mode: GSPMD-inserted collectives in the compiled module
    if mode() == "hlo" and expect in ("none", "reduce"):
        try:
            lowered = fn.lower(*args) if hasattr(fn, "lower") else (
                jax.jit(fn).lower(*args)
            )
            text = lowered.compile().as_text()
        except Exception as err:
            _STATS["audit_errors"] += 1
            telemetry.record_event(
                "analysis_finding", kind=kind, fingerprint=fp,
                rule="audit_error", detail=f"hlo: {str(err)[:200]}",
            )
            return got
        markers = _ALL_HLO if expect == "none" else _RESHARD_HLO
        seen_ops = sorted(
            {m.rstrip("(") for m in markers if m in text}
        )
        if seen_ops:
            got.append(_record(
                kind, fp, "unexpected_reshard",
                f"GSPMD inserted {seen_ops} into a program modeled as "
                f"{'local' if expect == 'none' else 'local+reduce'} — "
                "the roofline row's measured time includes unmodeled "
                "wire traffic",
            ))
    return got
