"""AST lint tier of the SPMD hazard analyzer (``python -m heat_tpu.analysis``).

Project-specific rules with ``HT0xx`` codes, each encoding a bug class
this repo has already paid for once:

* **HT001** — raw ``int(os.environ...)`` / ``float(os.environ...)``
  parsing that bypasses :func:`heat_tpu.core.autotune.env_bytes` /
  :func:`heat_tpu.core.envparse.env_int`.  The silent ``try/except``
  fallback turns an operator's typo'd budget into an invisible perf bug
  (the r14 ``RING_MIN_BYTES`` fix).
* **HT002** — host syncs (``.item()``, ``block_until_ready``,
  ``float()/int()/bool()`` of a device value) outside
  ``telemetry.timed_call``-wrapped sites.  An unmeasured sync in an
  engine hot path stalls the dispatch pipeline AND mis-attributes its
  wall to whatever the roofline timed next.
* **HT003** — data-dependent Python ``if``/``while`` on sharded values
  gating a collective call.  Under SPMD every rank must reach every
  collective in the same order; a rank-divergent branch around one is a
  deadlock on a multi-host mesh.
* **HT004** — a module-level counter dict mutated without a registered
  telemetry group.  Orphan counters miss ``snapshot()`` /
  ``reset_all()`` / ``export_prometheus()`` and silently drift.
* **HT005** — ``jax.jit(..., donate_argnums=...)`` where the donated
  Python name is loaded again after the call: use-after-donate is
  silent corruption on TPU (and silently *works* on CPU, which is how
  it survives CI).  ``quantize_weights(w, ..., donate=True)`` counts as
  a donation of ``w`` too — it consumes the master through a
  donate_argnums dispatch (core/quantize.py) and poisons it for the
  runtime sanitizer.

Suppression: append ``# ht: HT00x ok — <reason>`` to the flagged line.
Residual findings live in ``baseline.json`` next to this file; every
baseline entry must carry a non-empty ``reason`` or ``--check`` refuses
it.  ``--update-baseline`` rewrites the file from the current scan,
preserving reasons for findings that persist.
"""

import argparse
import ast
import hashlib
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# ------------------------------------------------------------------ findings

_SUPPRESS_RE = re.compile(r"#\s*ht:\s*(HT\d{3})\s+ok\b")

# namespaces whose call results / attributes are device values
_ARRAY_NS = {"jnp", "jax", "lax", "ht", "heat_tpu"}
# attribute reads that alias the underlying device buffer
_ARRAY_ATTRS = {"larray", "parray"}
# calls a rank-divergent branch must never gate (collective entry points
# and the layout changes that dispatch them); deliberately narrow —
# convergence checks on replicated host scalars around plain math are
# the legitimate SPMD idiom and stay clean
_COLLECTIVES = {
    "resplit", "resplit_", "redistribute_", "all_gather", "all_to_all",
    "psum", "pmax", "pmin", "ppermute", "ring_shift", "bcast", "exscan",
    "reduce_scatter", "psum_scatter", "tiled_resplit", "tiled_gather",
    "tiled_reshape", "rechunk", "matmul_raw", "barrier",
}


class Finding:
    """One lint hit.  ``identity`` is line-drift-stable: the rule code,
    the repo-relative path, a hash of the normalized source line, and an
    occurrence index among same-hash hits in the file."""

    __slots__ = ("code", "path", "line", "col", "message", "identity")

    def __init__(self, code, path, line, col, message, identity):
        self.code = code
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.identity = identity

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def as_dict(self) -> dict:
        return {
            "identity": self.identity, "code": self.code, "path": self.path,
            "line": self.line, "message": self.message,
        }


class _Ctx:
    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self._hash_seen: Dict[str, int] = {}

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, code: str, lineno: int) -> bool:
        m = _SUPPRESS_RE.search(self.line_text(lineno))
        return bool(m and m.group(1) == code)

    def finding(self, code: str, node: ast.AST, message: str) -> Optional[Finding]:
        lineno = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if self.suppressed(code, lineno):
            return None
        norm = " ".join(self.line_text(lineno).split())
        h = hashlib.md5(f"{code}|{norm}".encode()).hexdigest()[:10]
        n = self._hash_seen.get(h, 0)
        self._hash_seen[h] = n + 1
        identity = f"{code}::{self.relpath}::{h}::{n}"
        return Finding(code, self.relpath, lineno, col, message, identity)


# --------------------------------------------------------------- AST helpers


def _dotted(node: ast.AST) -> str:
    """``a.b.c`` for a Name/Attribute chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _mentions_environ(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "environ":
            return True
        if isinstance(sub, ast.Name) and sub.id == "environ":
            return True
    return False


# attribute reads that are host metadata, not device values: coercing
# shape/dtype arithmetic is not a sync
_METADATA_ATTRS = {
    "shape", "gshape", "lshape", "ndim", "dtype", "itemsize", "size",
    "sharding", "split", "ravel_order",
}
# array-namespace calls that return host metadata objects
_METADATA_CALLS = {
    "dtype", "result_type", "promote_types", "issubdtype", "finfo",
    "iinfo", "device_count", "local_device_count", "canonicalize_dtype",
}


def _mentions_array_source(node: ast.AST, tainted: frozenset) -> bool:
    """Does this expression derive from a device *value* — an
    array-namespace call, a ``.larray``/``.parray`` alias, or a tainted
    name?  Metadata reads (``.shape``, ``.itemsize``, ``jnp.dtype(...)``)
    are host-side and never trigger."""
    if isinstance(node, ast.Attribute):
        if node.attr in _ARRAY_ATTRS:
            return True
        if node.attr in _METADATA_ATTRS:
            return False  # metadata read of anything is host-side
        return _mentions_array_source(node.value, tainted)
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func)
        root = dotted.split(".", 1)[0]
        leaf = dotted.rsplit(".", 1)[-1]
        if root in _ARRAY_NS:
            return leaf not in _METADATA_CALLS
        return any(
            _mentions_array_source(c, tainted)
            for c in ast.iter_child_nodes(node)
        )
    if isinstance(node, ast.Name):
        return node.id in tainted
    return any(
        _mentions_array_source(c, tainted)
        for c in ast.iter_child_nodes(node)
    )


def _target_names(target: ast.AST) -> List[str]:
    out = []
    for sub in ast.walk(target):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
    return out


def _function_taint(fn: ast.AST) -> Dict[ast.stmt, frozenset]:
    """Per-statement taint snapshot for a function body: which local names
    (at that statement) derive from device values.  Linear, order-of-body
    approximation — loops are walked once, which over-taints slightly and
    never under-taints for the straight-line hazards HT002/HT003 target."""
    tainted: set = set()
    snap: Dict[ast.stmt, frozenset] = {}

    def visit_block(stmts: Sequence[ast.stmt]):
        for st in stmts:
            snap[st] = frozenset(tainted)
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = st.value
                targets = (
                    st.targets if isinstance(st, ast.Assign) else [st.target]
                )
                if value is not None and _mentions_array_source(
                    value, frozenset(tainted)
                ):
                    for t in targets:
                        tainted.update(_target_names(t))
                elif isinstance(st, ast.Assign):
                    for t in targets:
                        if isinstance(t, ast.Name):
                            tainted.discard(t.id)
            for block in _child_blocks(st):
                visit_block(block)

    visit_block(getattr(fn, "body", []))
    return snap


def _child_blocks(st: ast.stmt) -> Iterable[Sequence[ast.stmt]]:
    for field in ("body", "orelse", "finalbody"):
        block = getattr(st, field, None)
        if block and isinstance(block, list):
            yield block
    for h in getattr(st, "handlers", []) or []:
        yield h.body


def _functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _inside_timed_call(ancestors: Sequence[ast.AST]) -> bool:
    for anc in ancestors:
        if isinstance(anc, ast.Call):
            name = _dotted(anc.func)
            if name.endswith("timed_call") or name.endswith(".timed"):
                return True
    return False


def _walk_with_ancestors(root: ast.AST):
    stack: List[Tuple[ast.AST, Tuple[ast.AST, ...]]] = [(root, ())]
    while stack:
        node, anc = stack.pop()
        yield node, anc
        child_anc = anc + (node,)
        for child in ast.iter_child_nodes(node):
            stack.append((child, child_anc))


# --------------------------------------------------------------------- rules


def _rule_ht001(tree: ast.Module, ctx: _Ctx) -> List[Finding]:
    """Raw env int/byte parse bypassing env_bytes/env_int."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("int", "float")
            and node.args
            and any(_mentions_environ(a) for a in node.args)
        ):
            f = ctx.finding(
                "HT001", node,
                f"raw {node.func.id}(os.environ...) parse — route through "
                "autotune.env_bytes / envparse.env_int so malformed values "
                "raise instead of silently falling back",
            )
            if f:
                out.append(f)
    return out


def _rule_ht002(tree: ast.Module, ctx: _Ctx) -> List[Finding]:
    """Host syncs outside telemetry.timed_call-wrapped sites."""
    out = []
    taint_by_fn = {}
    for fn in _functions(tree):
        taint_by_fn[fn] = _function_taint(fn)

    def nearest_taint(ancestors, node) -> frozenset:
        for anc in reversed(ancestors):
            snap = taint_by_fn.get(anc)
            if snap is not None:
                # the statement snapshot nearest to this expression
                for a in reversed(ancestors):
                    got = snap.get(a)
                    if got is not None:
                        return got
                return frozenset()
        return frozenset()

    for node, ancestors in _walk_with_ancestors(tree):
        if not isinstance(node, ast.Call):
            continue
        hit = None
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                hit = ".item() host sync"
            elif node.func.attr == "block_until_ready":
                hit = "block_until_ready host sync"
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and _mentions_array_source(
                node.args[0], nearest_taint(ancestors, node)
            )
        ):
            hit = f"{node.func.id}() of a device value (host sync)"
        if hit is None:
            continue
        if _inside_timed_call(ancestors):
            continue
        f = ctx.finding(
            "HT002", node,
            f"{hit} outside a telemetry.timed_call-wrapped site — wrap it "
            "or justify with '# ht: HT002 ok — <reason>'",
        )
        if f:
            out.append(f)
    return out


def _rule_ht003(tree: ast.Module, ctx: _Ctx) -> List[Finding]:
    """Data-dependent branch on sharded values gating a collective."""
    out = []
    for fn in _functions(tree):
        snap = _function_taint(fn)
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            tainted = snap.get(node, frozenset())
            if not _mentions_array_source(node.test, tainted):
                continue
            gated = None
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _dotted(sub.func).rsplit(".", 1)[-1]
                    if name in _COLLECTIVES:
                        gated = name
                        break
            if gated is None:
                continue
            kw = "while" if isinstance(node, ast.While) else "if"
            f = ctx.finding(
                "HT003", node,
                f"data-dependent `{kw}` on a sharded/device value gates "
                f"collective `{gated}` — a rank-divergent branch here "
                "deadlocks the mesh; hoist the collective or branch on a "
                "replicated host scalar",
            )
            if f:
                out.append(f)
    return out


def _rule_ht004(tree: ast.Module, ctx: _Ctx) -> List[Finding]:
    """Module-level counter dict mutated without a registered group."""
    out = []
    dict_literals: Dict[str, ast.Assign] = {}
    registered: set = set()
    for st in tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and isinstance(
            st.targets[0], ast.Name
        ):
            name = st.targets[0].id
            if isinstance(st.value, ast.Dict):
                dict_literals[name] = st
            elif isinstance(st.value, ast.Call) and _dotted(
                st.value.func
            ).endswith("register_group"):
                registered.add(name)
    if not dict_literals:
        return out
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.AugAssign)
            and isinstance(node.target, ast.Subscript)
            and isinstance(node.target.value, ast.Name)
            and node.target.value.id in dict_literals
            and node.target.value.id not in registered
        ):
            name = node.target.value.id
            f = ctx.finding(
                "HT004", node,
                f"counter dict `{name}` mutated without a registered "
                "telemetry group — register it via "
                "telemetry.register_group so snapshot()/reset_all()/"
                "export_prometheus() see it",
            )
            if f:
                out.append(f)
            # one finding per dict keeps the signal readable
            del dict_literals[name]
    return out


def _rule_ht005(tree: ast.Module, ctx: _Ctx) -> List[Finding]:
    """Donated name loaded after a donate_argnums jit call."""
    out = []
    for fn in _functions(tree):
        # jitted-name -> donated positions
        jitted: Dict[str, Tuple[int, ...]] = {}
        # donated value name -> line of the donating call
        donated: Dict[str, int] = {}

        def donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
            if not _dotted(call.func).endswith("jit"):
                return None
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    positions = []
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, int
                        ):
                            positions.append(sub.value)
                    return tuple(positions)
            return None

        body_nodes = []
        for node in ast.walk(fn):
            if node is not fn and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue  # nested scopes analyzed on their own visit
            body_nodes.append(node)

        for node in body_nodes:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                pos = donate_positions(node.value)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = pos
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                pos = jitted.get(node.func.id)
                if pos:
                    for p in pos:
                        if p < len(node.args) and isinstance(
                            node.args[p], ast.Name
                        ):
                            donated.setdefault(
                                node.args[p].id, node.lineno
                            )
            if (
                isinstance(node, ast.Call)
                and _dotted(node.func).endswith("quantize_weights")
                and node.args
                and isinstance(node.args[0], ast.Name)
                and any(
                    kw.arg == "donate"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords
                )
            ):
                # quantize_weights(w, ..., donate=True) consumes the
                # master exactly like a donate_argnums dispatch (and
                # poisons it for the runtime sanitizer)
                donated.setdefault(node.args[0].id, node.lineno)
        if not donated:
            continue
        rebound: Dict[str, int] = {}
        for node in body_nodes:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    # >= catches the same-line self-rebind `x = g(x)`:
                    # the name now holds the call's result, not the
                    # donated buffer
                    if isinstance(t, ast.Name) and t.id in donated and (
                        node.lineno >= donated[t.id]
                    ):
                        rebound.setdefault(t.id, node.lineno)
        flagged = set()
        for node in body_nodes:
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in donated
                and node.id not in flagged
                and node.lineno > donated[node.id]
                and node.lineno < rebound.get(node.id, 1 << 30)
            ):
                flagged.add(node.id)
                f = ctx.finding(
                    "HT005", node,
                    f"`{node.id}` was donated to XLA at line "
                    f"{donated[node.id]} (donate_argnums) and is read "
                    "again here — use-after-donate is silent corruption "
                    "on TPU",
                )
                if f:
                    out.append(f)
    return out


RULES = {
    "HT001": _rule_ht001,
    "HT002": _rule_ht002,
    "HT003": _rule_ht003,
    "HT004": _rule_ht004,
    "HT005": _rule_ht005,
}


# -------------------------------------------------------------------- engine


def repo_root() -> str:
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..")
    )


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def lint_source(
    source: str, path: str = "<string>", relpath: Optional[str] = None
) -> List[Finding]:
    """Lint one source string; the fixture-level entry the tests use."""
    ctx = _Ctx(path, relpath or path, source)
    try:
        tree = ast.parse(source)
    except SyntaxError as err:
        return [
            Finding(
                "HT000", ctx.relpath, err.lineno or 1, 0,
                f"syntax error: {err.msg}",
                f"HT000::{ctx.relpath}::syntax::0",
            )
        ]
    out = []
    for rule in RULES.values():
        out.extend(rule(tree, ctx))
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path, rel)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", ".git", "node_modules")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def lint_paths(
    paths: Sequence[str], root: Optional[str] = None
) -> List[Finding]:
    out = []
    for path in iter_python_files(paths):
        out.extend(lint_file(path, root=root))
    return out


# ------------------------------------------------------------------ baseline


def load_baseline(path: Optional[str] = None) -> List[dict]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return doc.get("findings", [])


def save_baseline(
    findings: Sequence[Finding], path: Optional[str] = None,
    prev: Optional[List[dict]] = None,
) -> str:
    """Write the baseline from the current scan, carrying forward the
    ``reason`` of entries that persist; fresh entries get a TODO reason
    that ``--check`` will refuse until a human justifies them."""
    path = path or default_baseline_path()
    reasons = {e["identity"]: e.get("reason", "") for e in (prev or [])}
    doc = {
        "comment": (
            "Residual analyzer findings, each with a human justification. "
            "python -m heat_tpu.analysis --update-baseline regenerates; "
            "--check refuses entries without a reason."
        ),
        "findings": [
            dict(f.as_dict(), reason=reasons.get(f.identity, "TODO: justify"))
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    return path


def check(
    paths: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
    out=sys.stdout,
) -> int:
    """The ``--check`` gate: scan, subtract justified baseline entries,
    report the rest.  Returns a process exit code."""
    root = repo_root()
    paths = list(paths) if paths else [os.path.join(root, "heat_tpu")]
    findings = lint_paths(paths, root=root)
    baseline = load_baseline(baseline_path)
    by_id = {e["identity"]: e for e in baseline}
    fresh, unjustified = [], []
    for f in findings:
        entry = by_id.pop(f.identity, None)
        if entry is None:
            fresh.append(f)
        elif not str(entry.get("reason", "")).strip() or str(
            entry.get("reason", "")
        ).startswith("TODO"):
            unjustified.append(f)
    for f in fresh:
        print(f.render(), file=out)
    for f in unjustified:
        print(f.render() + "  [baselined without justification]", file=out)
    stale = list(by_id)
    if stale:
        print(
            f"note: {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'} no longer found "
            "(run --update-baseline)", file=out,
        )
    n_bad = len(fresh) + len(unjustified)
    total = len(findings)
    print(
        f"heat_tpu.analysis: {total} finding(s), "
        f"{total - n_bad} baselined+justified, {n_bad} blocking",
        file=out,
    )
    return 1 if n_bad else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m heat_tpu.analysis",
        description="SPMD hazard lint (HT001-HT005) over the heat_tpu tree",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: heat_tpu/)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any non-baselined finding (CI gate)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite baseline.json from the current scan, "
                         "keeping existing justifications")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: heat_tpu/analysis/"
                         "baseline.json)")
    args = ap.parse_args(argv)
    if args.update_baseline:
        root = repo_root()
        paths = args.paths or [os.path.join(root, "heat_tpu")]
        findings = lint_paths(paths, root=root)
        prev = load_baseline(args.baseline)
        path = save_baseline(findings, args.baseline, prev=prev)
        print(f"baseline: {len(findings)} finding(s) -> {path}")
        return 0
    return check(args.paths or None, args.baseline)
