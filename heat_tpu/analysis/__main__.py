"""``python -m heat_tpu.analysis`` — the lint CLI (see lint.main)."""

import sys

from .lint import main

if __name__ == "__main__":
    sys.exit(main())
