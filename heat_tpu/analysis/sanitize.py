"""Runtime sanitizer tier of the SPMD hazard analyzer
(``HEAT_TPU_SANITIZE=1``).

Two jobs, both near-zero when off:

* **Donated-buffer poisoning.**  Donation sites (``resplit_``, the
  reshape stage pipeline, fused donating programs) report the consumed
  buffer here; use funnels (fusion leaves, transport entries, the ring
  matmul operands) ask :func:`check_use` on their inputs and a poisoned
  buffer raises :class:`UseAfterDonateError` naming the buffer's
  *creation* site (from the memtrack ledger) and its *donation* site.
  On CPU ``donate_argnums`` is ignored, so use-after-donate silently
  reads stale-but-valid data and survives CI — the sanitizer is what
  makes the hazard test-visible before TPU turns it into corruption.

* **Collective-sequence fingerprint.**  Every collective dispatch
  (transport tile programs, overlap ring programs) appends
  ``(site, op, axis)`` to a per-process hash chain.  Under SPMD the
  chain must be identical on every rank — the lockstep law the
  multi-host mesh will depend on; census tests assert it across the
  forced-device mesh and across processes.

Poison entries hold a weakref to the donated buffer: a dead referent
whose ``id`` was recycled by the allocator must never convict an
innocent new buffer, so :func:`check_use` confirms identity through the
weakref before raising.
"""

import hashlib
import os
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..core import guard, memtrack, telemetry

# ------------------------------------------------------------------- gating

_ENABLED_OVERRIDE: "List[Optional[bool]]" = [None]

# program_audit registers its own interest so donation sites poison for
# the auditor even when the raising sanitizer is off (registration via
# callable: sanitize never imports program_audit)
_AUX_INTEREST: "List[Any]" = []


def enabled() -> bool:
    """Whether the raising sanitizer is live (``HEAT_TPU_SANITIZE``,
    default off)."""
    if _ENABLED_OVERRIDE[0] is not None:
        return _ENABLED_OVERRIDE[0]
    return os.environ.get("HEAT_TPU_SANITIZE", "").strip().lower() in (
        "1", "on", "true", "yes",
    )


def set_enabled(on: Optional[bool]) -> Optional[bool]:
    """Override the env toggle (``None`` restores env control).  Returns
    the previous override."""
    prev = _ENABLED_OVERRIDE[0]
    _ENABLED_OVERRIDE[0] = None if on is None else bool(on)
    return prev


def register_interest(fn) -> None:
    """Register a zero-arg callable; poison bookkeeping also runs while
    any registered callable returns True (the auditor's hook)."""
    if fn not in _AUX_INTEREST:
        _AUX_INTEREST.append(fn)


def _tracking() -> bool:
    if enabled():
        return True
    for fn in _AUX_INTEREST:
        try:
            if fn():
                return True
        except Exception:
            pass
    return False


# ------------------------------------------------------------ poison ledger


class UseAfterDonateError(RuntimeError):
    """A buffer handed to XLA via ``donate_argnums`` was fed back into an
    engine entry point."""


# id(buffer) -> {"ref": weakref|None, "created": site, "donated": site,
#                "nbytes": int, "shape": tuple, "dtype": str}
_POISON: Dict[int, dict] = {}
_POISON_MAX = 4096  # bounded: a long-lived process must not grow this

_STATS = telemetry.register_group(
    "sanitize",
    {
        "poisoned": 0,        # donation sites reported
        "checks": 0,          # check_use consults while tracking
        "use_after_donate": 0,  # raised (or audited) hits
        "collective_events": 0,  # fingerprint chain appends
    },
)


def poison(value, donated_site: Optional[str] = None) -> None:
    """Record ``value`` as donated.  Called by donation sites *after* the
    donating dispatch (the dispatch itself is the legitimate last use).
    The creation site comes from the memtrack ledger when the buffer was
    ledgered, else it is captured here."""
    if value is None or not _tracking():
        return
    rec = memtrack._LEDGER.get(id(value))
    created = rec.get("site") if rec is not None else None
    if donated_site is None:
        donated_site = guard.format_site(guard.capture_site(2))
    try:
        ref = weakref.ref(value)
    except TypeError:
        ref = None
    if len(_POISON) >= _POISON_MAX:
        _POISON.pop(next(iter(_POISON)), None)
    _POISON[id(value)] = {
        "ref": ref,
        "created": created or "<unledgered buffer>",
        "donated": donated_site,
        "nbytes": int(getattr(value, "nbytes", 0) or 0),
        "shape": tuple(getattr(value, "shape", ()) or ()),
        "dtype": str(getattr(value, "dtype", "?")),
    }
    _STATS["poisoned"] += 1


def poison_entry(value) -> Optional[dict]:
    """The poison record for ``value`` if it is a *confirmed* donated
    buffer (weakref identity check defeats id reuse), else None."""
    entry = _POISON.get(id(value))
    if entry is None:
        return None
    ref = entry.get("ref")
    if ref is not None and ref() is not value:
        # the donated buffer died and the allocator recycled its id —
        # this is a different, innocent object
        del _POISON[id(value)]
        return None
    return entry


def check_use(value, context: str) -> None:
    """Raise :class:`UseAfterDonateError` if ``value`` was donated.
    Engine entry funnels call this on their inputs; no-op unless the
    sanitizer is enabled."""
    if not enabled() or value is None:
        return
    _STATS["checks"] += 1
    entry = poison_entry(value)
    if entry is None:
        return
    _STATS["use_after_donate"] += 1
    telemetry.record_event(
        "analysis_finding", rule="use_after_donate", context=context,
        created=entry["created"], donated=entry["donated"],
        nbytes=entry["nbytes"],
    )
    raise UseAfterDonateError(
        f"use-after-donate in {context}: this "
        f"{entry['dtype']}{list(entry['shape'])} buffer "
        f"({entry['nbytes']} bytes) was donated to XLA at "
        f"{entry['donated']} and must not be read again. "
        f"Buffer created at {entry['created']}. On TPU this reads "
        "XLA-recycled memory (silent corruption); copy before the "
        "donating call, or keep the DNDarray instead of its raw buffer."
    )


def clear_poison() -> None:
    _POISON.clear()


# ----------------------------------------------- collective-sequence chain

# the running fingerprint: a hash chain over (site, op, axis) — identical
# across ranks iff every rank dispatched the same collectives in the same
# order with the same axes (the SPMD lockstep law)
_CHAIN = {"n": 0, "digest": hashlib.sha256(b"heat_tpu").hexdigest()}
_TRAIL: "List[Tuple[str, str, Optional[str]]]" = []
_TRAIL_MAX = 256


def collective_event(
    op: str, axis: Optional[str] = None, site: Optional[str] = None
) -> None:
    """Append one collective dispatch to the per-process chain.  Gated on
    the sanitizer toggle: the steady state pays one boolean check."""
    if not enabled():
        return
    if site is None:
        site = guard.format_site(guard.capture_site(2))
    link = f"{site}|{op}|{axis or ''}"
    _CHAIN["digest"] = hashlib.sha256(
        (_CHAIN["digest"] + link).encode()
    ).hexdigest()
    _CHAIN["n"] += 1  # ht: HT004 ok — hash-chain state, not a counter; sanitize._STATS carries the counters
    _STATS["collective_events"] += 1
    if len(_TRAIL) < _TRAIL_MAX:
        _TRAIL.append((site, op, axis))


def collective_fingerprint() -> dict:
    """The current chain: ``{"n", "digest", "trail"}`` (trail bounded).
    Census tests assert the digest is equal across every rank."""
    return {
        "n": _CHAIN["n"], "digest": _CHAIN["digest"],
        "trail": list(_TRAIL),
    }


def reset_collective_fingerprint() -> None:
    _CHAIN["n"] = 0
    _CHAIN["digest"] = hashlib.sha256(b"heat_tpu").hexdigest()
    del _TRAIL[:]


def reset() -> None:
    """Full sanitizer reset (tests)."""
    clear_poison()
    reset_collective_fingerprint()
