"""heat_tpu type system: a NumPy-like dtype class lattice over JAX dtypes.

TPU-native re-design of the reference's type system (heat/core/types.py:64-420):
the same class hierarchy (``datatype`` → ``bool``/``number`` →
``integer``/``floating``/``complexfloating`` → concrete types), each concrete
type exposing the backing JAX dtype via :meth:`datatype.jax_type` (the
reference's ``torch_type()``), plus ``canonical_heat_type`` (types.py:495),
``heat_type_of`` (:565), ``can_cast`` (:671), ``promote_types`` (:836),
``result_type`` (:868, scalar-aware), ``finfo``/``iinfo`` (:950/:1005).

TPU-first additions: :class:`bfloat16` and :class:`float16` are first-class
members of the lattice (the MXU's native matmul dtype is bf16).

64-bit policy: JAX's ``jax_enable_x64`` flag decides whether 64-bit types are
real or silently demoted. ``heat_tpu`` enables x64 when running on CPU (test
parity with NumPy) and leaves it off on TPU, where float64 would be emulated;
dtype metadata on arrays always reflects the *actual* on-device dtype.
"""

from __future__ import annotations

import builtins
from typing import Any, Iterator, Tuple, Type, Union

import numpy as np

import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "datatype",
    "bool",
    "bool_",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "complexfloating",
    "complex",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "flexible",
    "canonical_heat_type",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "heat_type_of",
    "issubdtype",
    "can_cast",
    "promote_types",
    "result_type",
    "iscomplex",
    "isreal",
    "finfo",
    "iinfo",
]


class datatype:
    """Base class of the dtype lattice (reference: heat/core/types.py:64).

    Concrete subclasses act both as dtype tags (``ht.float32``) and as casting
    constructors: ``ht.float32(x)`` builds a DNDarray of that type.
    """

    _jnp_type = None
    _char = "??"
    _nbytes = 0

    def __new__(cls, *value, device=None, comm=None, split=None):
        from . import factories

        if cls._jnp_type is None:
            raise TypeError(f"cannot instantiate abstract type {cls.__name__}")
        if len(value) == 0:
            value = ((0,) if not issubclass(cls, complexfloating) else (0j,),)
            value = value[0]
        elif len(value) == 1:
            value = value[0]
        else:
            value = list(value)
        return factories.array(value, dtype=cls, device=device, comm=comm, split=split)

    @classmethod
    def jax_type(cls):
        """The backing jnp dtype (reference's ``torch_type()``, types.py:86)."""
        if cls._jnp_type is None:
            raise TypeError(f"abstract type {cls.__name__} has no JAX dtype")
        return cls._jnp_type

    # reference-compat alias so generic code written against Heat still works
    torch_type = jax_type

    @classmethod
    def char(cls) -> str:
        """Short identifier (reference: types.py:94)."""
        return cls._char

    @classmethod
    def nbytes(cls) -> builtins.int:
        return cls._nbytes


class bool(datatype):
    """Boolean (reference: types.py:142)."""

    _jnp_type = jnp.bool_
    _char = "u1"
    _nbytes = 1


bool_ = bool


class number(datatype):
    """Abstract numeric type (reference: types.py:151)."""


class integer(number):
    """Abstract integer (reference: types.py:157)."""


class signedinteger(integer):
    """Abstract signed integer (reference: types.py:163)."""


class unsignedinteger(integer):
    """Abstract unsigned integer (reference: types.py:169)."""


class floating(number):
    """Abstract float (reference: types.py:175)."""


class complexfloating(number):
    """Abstract complex (reference: types.py:181)."""


class flexible(datatype):
    """Abstract non-numeric (kept for API parity; reference: types.py:187)."""


class int8(signedinteger):
    _jnp_type = jnp.int8
    _char = "i1"
    _nbytes = 1


byte = int8


class int16(signedinteger):
    _jnp_type = jnp.int16
    _char = "i2"
    _nbytes = 2


short = int16


class int32(signedinteger):
    _jnp_type = jnp.int32
    _char = "i4"
    _nbytes = 4


int = int32  # canonical heat int alias (reference aliases int→int32, types.py:266)


class int64(signedinteger):
    _jnp_type = jnp.int64
    _char = "i8"
    _nbytes = 8


long = int64


class uint8(unsignedinteger):
    _jnp_type = jnp.uint8
    _char = "u1"
    _nbytes = 1


ubyte = uint8


class float16(floating):
    """IEEE half precision — TPU-first addition (not in the reference)."""

    _jnp_type = jnp.float16
    _char = "f2"
    _nbytes = 2


half = float16


class bfloat16(floating):
    """Brain float — the MXU's native matmul dtype. TPU-first addition."""

    _jnp_type = jnp.bfloat16
    _char = "bf2"
    _nbytes = 2


class float32(floating):
    _jnp_type = jnp.float32
    _char = "f4"
    _nbytes = 4


float = float32
float_ = float32


class float64(floating):
    _jnp_type = jnp.float64
    _char = "f8"
    _nbytes = 8


double = float64


class complex64(complexfloating):
    _jnp_type = jnp.complex64
    _char = "c8"
    _nbytes = 8


cfloat = complex64
csingle = complex64


class complex128(complexfloating):
    _jnp_type = jnp.complex128
    _char = "c16"
    _nbytes = 16


cdouble = complex128

# reference: heat/core/types.py:367 names the abstract complex class
# ``complex`` (shadowing the builtin); keep that spelling as an alias so
# ``ht.types.complex`` resolves for users of the reference API.
complex = complexfloating


# ----------------------------------------------------------------- mappings
_NP_TO_HEAT = {
    np.dtype(np.bool_): bool,
    np.dtype(np.int8): int8,
    np.dtype(np.int16): int16,
    np.dtype(np.int32): int32,
    np.dtype(np.int64): int64,
    np.dtype(np.uint8): uint8,
    np.dtype(np.uint16): int32,  # promoted: no uint16 in lattice (reference parity)
    np.dtype(np.uint32): int64,
    np.dtype(np.uint64): int64,
    np.dtype(np.float16): float16,
    np.dtype(ml_dtypes.bfloat16): bfloat16,
    np.dtype(np.float32): float32,
    np.dtype(np.float64): float64,
    np.dtype(np.complex64): complex64,
    np.dtype(np.complex128): complex128,
}

_PY_TO_HEAT = {
    builtins.bool: bool,
    builtins.int: int64,
    builtins.float: float32,
    builtins.complex: complex64,
}


def _all_concrete() -> Iterator[Type[datatype]]:
    stack = [datatype]
    while stack:
        cls = stack.pop()
        stack.extend(cls.__subclasses__())
        if cls._jnp_type is not None:
            yield cls


def canonical_heat_type(a_type: Any) -> Type[datatype]:
    """Normalize any dtype-like to its canonical heat type (reference:
    types.py:495). Accepts heat types, python scalar types, numpy/jnp dtypes,
    dtype strings."""
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if a_type._jnp_type is None:
            raise TypeError(f"abstract type {a_type.__name__} is not a canonical type")
        return a_type
    if a_type in _PY_TO_HEAT:
        return _PY_TO_HEAT[a_type]
    # strings like "float32", "f4", numpy dtypes, jnp dtypes
    if isinstance(a_type, str):
        for cls in _all_concrete():
            if cls.__name__ == a_type or cls._char == a_type:
                return cls
    try:
        np_dtype = np.dtype(a_type)
    except TypeError:
        raise TypeError(f"data type {a_type!r} not understood")
    if np_dtype in _NP_TO_HEAT:
        return _NP_TO_HEAT[np_dtype]
    raise TypeError(f"data type {a_type!r} not understood")


def heat_type_of(obj: Any) -> Type[datatype]:
    """Infer the heat type of an array-like (reference: types.py:565)."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        return obj.dtype
    if isinstance(obj, (type(None),)):
        raise TypeError("cannot infer heat type of None")
    if type(obj) in _PY_TO_HEAT:
        return _PY_TO_HEAT[type(obj)]
    if hasattr(obj, "dtype"):
        return canonical_heat_type(obj.dtype)
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    raise TypeError(f"cannot infer heat type of {type(obj)}")


def heat_type_is_exact(ht_dtype: Type[datatype]) -> builtins.bool:
    """True for integer/bool types (reference: types.py:~640)."""
    return issubclass(ht_dtype, integer) or ht_dtype is bool


def heat_type_is_inexact(ht_dtype: Type[datatype]) -> builtins.bool:
    return issubclass(ht_dtype, (floating, complexfloating))


def heat_type_is_complexfloating(ht_dtype: Type[datatype]) -> builtins.bool:
    return issubclass(ht_dtype, complexfloating)


def issubdtype(arg1: Any, arg2: Any) -> builtins.bool:
    """NumPy-style subtype check over the heat lattice."""
    if not (isinstance(arg1, type) and issubclass(arg1, datatype)):
        arg1 = canonical_heat_type(arg1)
    if not (isinstance(arg2, type) and issubclass(arg2, datatype)):
        if arg2 in (number, integer, floating, complexfloating, signedinteger, unsignedinteger):
            pass
        else:
            arg2 = canonical_heat_type(arg2)
    return issubclass(arg1, arg2)


def _np_equivalent(ht_dtype: Type[datatype]):
    t = ht_dtype.jax_type()
    return np.dtype(t)


def can_cast(from_: Any, to: Any, casting: str = "safe") -> builtins.bool:
    """NumPy-semantics castability over heat types (reference: types.py:671)."""
    if not isinstance(from_, type):
        # scalars / arrays: use their inferred type
        try:
            from_ = heat_type_of(from_)
        except TypeError:
            from_ = canonical_heat_type(from_)
    else:
        from_ = canonical_heat_type(from_)
    to = canonical_heat_type(to)
    return np.can_cast(_np_equivalent(from_), _np_equivalent(to), casting=casting)


def promote_types(type1: Any, type2: Any) -> Type[datatype]:
    """Smallest common safe type (reference: types.py:836). Delegates to
    jnp.promote_types so bfloat16 participates correctly."""
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    return canonical_heat_type(jnp.promote_types(t1.jax_type(), t2.jax_type()))


def result_type(*operands: Any) -> Type[datatype]:
    """Scalar-aware promotion across DNDarrays/scalars/dtypes (reference:
    types.py:868). Delegates to jnp.result_type (NumPy promotion rules with
    weak scalar types)."""
    from .dndarray import DNDarray

    args = []
    for op in operands:
        if isinstance(op, DNDarray):
            args.append(op.larray)
        elif isinstance(op, type) and issubclass(op, datatype):
            args.append(op.jax_type())
        else:
            args.append(op)
    return canonical_heat_type(jnp.result_type(*args))


def iscomplex(x) -> "Any":
    """Elementwise imaginary-part-nonzero test (reference: types.py:764)."""
    from . import _operations

    return _operations._local_op(jnp.iscomplex, x, no_cast=True)


def isreal(x) -> "Any":
    """Elementwise real test (reference: types.py:786)."""
    from . import _operations

    return _operations._local_op(jnp.isreal, x, no_cast=True)


class finfo:
    """Float machine limits (reference: types.py:950)."""

    def __new__(cls, ht_dtype: Type[datatype]):
        ht_dtype = canonical_heat_type(ht_dtype)
        if not issubclass(ht_dtype, (floating, complexfloating)):
            raise TypeError(f"data type {ht_dtype} not inexact")
        info = jnp.finfo(ht_dtype.jax_type())
        obj = object.__new__(cls)
        obj.bits = info.bits
        obj.eps = builtins.float(info.eps)
        obj.max = builtins.float(info.max)
        obj.min = builtins.float(info.min)
        obj.tiny = builtins.float(info.tiny)
        obj.resolution = builtins.float(getattr(info, "resolution", info.eps))
        return obj


class iinfo:
    """Integer machine limits (reference: types.py:1005)."""

    def __new__(cls, ht_dtype: Type[datatype]):
        ht_dtype = canonical_heat_type(ht_dtype)
        if not issubclass(ht_dtype, (integer,)) and ht_dtype is not bool:
            raise TypeError(f"data type {ht_dtype} not integral")
        info = jnp.iinfo(ht_dtype.jax_type()) if ht_dtype is not bool else None
        obj = object.__new__(cls)
        if info is None:
            obj.bits, obj.max, obj.min = 8, 1, 0
        else:
            obj.bits = info.bits
            obj.max = builtins.int(info.max)
            obj.min = builtins.int(info.min)
        return obj
