"""heat_tpu type system: a NumPy-like dtype class lattice over JAX dtypes.

TPU-native re-design of the reference's type system (heat/core/types.py:64-420):
the same class hierarchy (``datatype`` → ``bool``/``number`` →
``integer``/``floating``/``complexfloating`` → concrete types), each concrete
type exposing the backing JAX dtype via :meth:`datatype.jax_type` (the
reference's ``torch_type()``), plus ``canonical_heat_type`` (types.py:495),
``heat_type_of`` (:565), ``can_cast`` (:671), ``promote_types`` (:836),
``result_type`` (:868, scalar-aware), ``finfo``/``iinfo`` (:950/:1005).

TPU-first additions: :class:`bfloat16` and :class:`float16` are first-class
members of the lattice (the MXU's native matmul dtype is bf16).

64-bit policy: JAX's ``jax_enable_x64`` flag decides whether 64-bit types are
real or silently demoted. ``heat_tpu`` enables x64 when running on CPU (test
parity with NumPy) and leaves it off on TPU, where float64 would be emulated;
dtype metadata on arrays always reflects the *actual* on-device dtype.
"""

from __future__ import annotations

import builtins
from typing import Any, Iterator, Tuple, Type, Union

import numpy as np

import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "datatype",
    "bool",
    "bool_",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "complexfloating",
    "complex",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "flexible",
    "canonical_heat_type",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "heat_type_of",
    "issubdtype",
    "can_cast",
    "promote_types",
    "result_type",
    "iscomplex",
    "isreal",
    "finfo",
    "iinfo",
]


class datatype:
    """Base class of the dtype lattice (reference: heat/core/types.py:64).

    Concrete subclasses act both as dtype tags (``ht.float32``) and as casting
    constructors: ``ht.float32(x)`` builds a DNDarray of that type.
    """

    _jnp_type = None
    _char = "??"
    _nbytes = 0

    def __new__(cls, *value, device=None, comm=None, split=None):
        from . import factories

        if cls._jnp_type is None:
            raise TypeError(f"cannot instantiate abstract type {cls.__name__}")
        if len(value) == 0:
            value = ((0,) if not issubclass(cls, complexfloating) else (0j,),)
            value = value[0]
        elif len(value) == 1:
            value = value[0]
        else:
            value = list(value)
        return factories.array(value, dtype=cls, device=device, comm=comm, split=split)

    @classmethod
    def jax_type(cls):
        """The backing jnp dtype (reference's ``torch_type()``, types.py:86)."""
        if cls._jnp_type is None:
            raise TypeError(f"abstract type {cls.__name__} has no JAX dtype")
        return cls._jnp_type

    # reference-compat alias so generic code written against Heat still works
    torch_type = jax_type

    @classmethod
    def char(cls) -> str:
        """Short identifier (reference: types.py:94)."""
        return cls._char

    @classmethod
    def nbytes(cls) -> builtins.int:
        return cls._nbytes


class bool(datatype):
    """Boolean (reference: types.py:142)."""

    _jnp_type = jnp.bool_
    _char = "u1"
    _nbytes = 1


bool_ = bool


class number(datatype):
    """Abstract numeric type (reference: types.py:151)."""


class integer(number):
    """Abstract integer (reference: types.py:157)."""


class signedinteger(integer):
    """Abstract signed integer (reference: types.py:163)."""


class unsignedinteger(integer):
    """Abstract unsigned integer (reference: types.py:169)."""


class floating(number):
    """Abstract float (reference: types.py:175)."""


class complexfloating(number):
    """Abstract complex (reference: types.py:181)."""


class flexible(datatype):
    """Abstract non-numeric (kept for API parity; reference: types.py:187)."""


class int8(signedinteger):
    _jnp_type = jnp.int8
    _char = "i1"
    _nbytes = 1


byte = int8


class int16(signedinteger):
    _jnp_type = jnp.int16
    _char = "i2"
    _nbytes = 2


short = int16


class int32(signedinteger):
    _jnp_type = jnp.int32
    _char = "i4"
    _nbytes = 4


int = int32  # canonical heat int alias (reference aliases int→int32, types.py:266)


class int64(signedinteger):
    _jnp_type = jnp.int64
    _char = "i8"
    _nbytes = 8


long = int64


class uint8(unsignedinteger):
    _jnp_type = jnp.uint8
    _char = "u1"
    _nbytes = 1


ubyte = uint8


class float16(floating):
    """IEEE half precision — TPU-first addition (not in the reference)."""

    _jnp_type = jnp.float16
    _char = "f2"
    _nbytes = 2


half = float16


class bfloat16(floating):
    """Brain float — the MXU's native matmul dtype. TPU-first addition."""

    _jnp_type = jnp.bfloat16
    _char = "bf2"
    _nbytes = 2


class float32(floating):
    _jnp_type = jnp.float32
    _char = "f4"
    _nbytes = 4


float = float32
float_ = float32


class float64(floating):
    _jnp_type = jnp.float64
    _char = "f8"
    _nbytes = 8


double = float64


class complex64(complexfloating):
    _jnp_type = jnp.complex64
    _char = "c8"
    _nbytes = 8


cfloat = complex64
csingle = complex64


class complex128(complexfloating):
    _jnp_type = jnp.complex128
    _char = "c16"
    _nbytes = 16


cdouble = complex128

# reference: heat/core/types.py:367 names the abstract complex class
# ``complex`` (shadowing the builtin); keep that spelling as an alias so
# ``ht.types.complex`` resolves for users of the reference API.
complex = complexfloating


# ----------------------------------------------------------------- mappings
_NP_TO_HEAT = {
    np.dtype(np.bool_): bool,
    np.dtype(np.int8): int8,
    np.dtype(np.int16): int16,
    np.dtype(np.int32): int32,
    np.dtype(np.int64): int64,
    np.dtype(np.uint8): uint8,
    np.dtype(np.uint16): int32,  # promoted: no uint16 in lattice (reference parity)
    np.dtype(np.uint32): int64,
    np.dtype(np.uint64): int64,
    np.dtype(np.float16): float16,
    np.dtype(ml_dtypes.bfloat16): bfloat16,
    np.dtype(np.float32): float32,
    np.dtype(np.float64): float64,
    np.dtype(np.complex64): complex64,
    np.dtype(np.complex128): complex128,
}

_PY_TO_HEAT = {
    builtins.bool: bool,
    builtins.int: int64,
    builtins.float: float32,
    builtins.complex: complex64,
}


def _all_concrete() -> Iterator[Type[datatype]]:
    stack = [datatype]
    while stack:
        cls = stack.pop()
        stack.extend(cls.__subclasses__())
        if cls._jnp_type is not None:
            yield cls


def canonical_heat_type(a_type: Any) -> Type[datatype]:
    """Normalize any dtype-like to its canonical heat type (reference:
    types.py:495). Accepts heat types, python scalar types, numpy/jnp dtypes,
    dtype strings."""
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if a_type._jnp_type is None:
            raise TypeError(f"abstract type {a_type.__name__} is not a canonical type")
        return a_type
    if a_type in _PY_TO_HEAT:
        return _PY_TO_HEAT[a_type]
    # strings like "float32", "f4", numpy dtypes, jnp dtypes
    if isinstance(a_type, str):
        for cls in _all_concrete():
            if cls.__name__ == a_type or cls._char == a_type:
                return cls
    try:
        np_dtype = np.dtype(a_type)
    except TypeError:
        raise TypeError(f"data type {a_type!r} not understood")
    if np_dtype in _NP_TO_HEAT:
        return _NP_TO_HEAT[np_dtype]
    raise TypeError(f"data type {a_type!r} not understood")


def heat_type_of(obj: Any) -> Type[datatype]:
    """Infer the heat type of an array-like (reference: types.py:565)."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        return obj.dtype
    if isinstance(obj, (type(None),)):
        raise TypeError("cannot infer heat type of None")
    if type(obj) in _PY_TO_HEAT:
        return _PY_TO_HEAT[type(obj)]
    if hasattr(obj, "dtype"):
        return canonical_heat_type(obj.dtype)
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    raise TypeError(f"cannot infer heat type of {type(obj)}")


def heat_type_is_exact(ht_dtype: Type[datatype]) -> builtins.bool:
    """True for integer/bool types (reference: types.py:~640)."""
    return issubclass(ht_dtype, integer) or ht_dtype is bool


def heat_type_is_inexact(ht_dtype: Type[datatype]) -> builtins.bool:
    return issubclass(ht_dtype, (floating, complexfloating))


def heat_type_is_complexfloating(ht_dtype: Type[datatype]) -> builtins.bool:
    return issubclass(ht_dtype, complexfloating)


def issubdtype(arg1: Any, arg2: Any) -> builtins.bool:
    """NumPy-style subtype check over the heat lattice."""
    if not (isinstance(arg1, type) and issubclass(arg1, datatype)):
        arg1 = canonical_heat_type(arg1)
    if not (isinstance(arg2, type) and issubclass(arg2, datatype)):
        if arg2 in (number, integer, floating, complexfloating, signedinteger, unsignedinteger):
            pass
        else:
            arg2 = canonical_heat_type(arg2)
    return issubclass(arg1, arg2)


def _np_equivalent(ht_dtype: Type[datatype]):
    t = ht_dtype.jax_type()
    return np.dtype(t)


def _cast_kind(t: Type[datatype]) -> str:
    if t is bool:
        return "b"
    if issubclass(t, unsignedinteger):
        return "u"
    if issubclass(t, signedinteger):
        return "i"
    if issubclass(t, floating):
        return "f"
    return "c"


def can_cast(from_: Any, to: Any, casting: str = "intuitive") -> builtins.bool:
    """Castability over heat types (reference: types.py:671).  The default
    ``"intuitive"`` rule is the reference's: everything ``"safe"`` allows,
    plus int→float of the *same* bit length (e.g. int32→float32)."""
    if not isinstance(from_, type):
        # scalars / arrays: use their inferred type
        try:
            from_ = heat_type_of(from_)
        except TypeError:
            from_ = canonical_heat_type(from_)
    else:
        from_ = canonical_heat_type(from_)
    to = canonical_heat_type(to)
    if casting == "intuitive":
        if np.can_cast(_np_equivalent(from_), _np_equivalent(to), casting="safe"):
            return True
        to_bits = to.nbytes() // 2 if _cast_kind(to) == "c" else to.nbytes()
        return (
            _cast_kind(from_) in ("u", "i")
            and _cast_kind(to) in ("f", "c")
            and to_bits >= from_.nbytes()
        )
    return np.can_cast(_np_equivalent(from_), _np_equivalent(to), casting=casting)


def promote_types(type1: Any, type2: Any) -> Type[datatype]:
    """Smallest type both operands can "intuitively" cast to
    (reference: types.py:836 and its doctests — same-bitlength promotion:
    int32+float32→float32, int64+float32→float64, int8+uint8→int16 — not
    numpy's widening).  bfloat16, absent from the reference lattice, follows
    jax: it wins against same-or-narrower ints and meets float16 at
    float32."""
    a = canonical_heat_type(type1)
    b = canonical_heat_type(type2)
    if a is b:
        return a
    if {a, b} == {bfloat16, float16}:
        # no common exact 2-byte float: meet at float32 (jax rule)
        return float32
    ka, kb = _cast_kind(a), _cast_kind(b)
    order = "buifc"
    if order.index(ka) > order.index(kb):
        a, b, ka, kb = b, a, kb, ka
    if ka == "b":
        return b
    na, nb = a.nbytes(), b.nbytes()
    if ka == kb:
        return a if na >= nb else b
    if ka == "u" and kb == "i":
        # signed type wide enough for the unsigned range (uint8→int16 floor)
        if nb > na:
            return b
        return {1: int16, 2: int32, 4: int64}.get(na, int64)
    if kb == "f":
        # int vs float: the float operand survives if it is at least as
        # wide (bfloat16 included — keeps its identity against u8/i8/i16);
        # a wider int forces the same-bitlength float
        if na <= nb:
            return b
        return {4: float32}.get(na, float64)
    # kb == "c": the real part must carry the wider operand
    real = max(na if ka != "c" else na // 2, nb // 2)
    return complex64 if real <= 4 else complex128


def result_type(*operands: Any) -> Type[datatype]:
    """Promotion across arrays/types/scalars with the reference's precedence
    rules (types.py:868): arrays > named types > python scalars within the
    same kind (a scalar never widens an array of its own kind); across
    kinds the higher kind wins (an int array + float scalar goes float)."""
    from .dndarray import DNDarray

    def classify(op):
        if isinstance(op, DNDarray):
            return op.dtype, 0 if op.ndim > 0 else 2
        if isinstance(op, np.ndarray):
            t = canonical_heat_type(op.dtype)
            return t, 0 if op.ndim > 0 else 2
        if hasattr(op, "dtype") and hasattr(op, "shape"):  # jax arrays
            return canonical_heat_type(op.dtype), 0 if op.ndim > 0 else 2
        try:
            return canonical_heat_type(op), 1
        except TypeError:
            return heat_type_of(op), 3

    def combine(t1, p1, t2, p2):
        if t1 is t2:
            return t1, min(p1, p2)
        if p1 == p2:
            return promote_types(t1, t2), p1
        for parent in (bool, integer, floating, complexfloating):
            if issubdtype(t1, parent) and issubdtype(t2, parent):
                return (t1, min(p1, p2)) if p1 < p2 else (t2, min(p1, p2))
        order = "buifc"
        k1, k2 = order.index(_cast_kind(t1)), order.index(_cast_kind(t2))
        return (t2, min(p1, p2)) if k1 < k2 else (t1, min(p1, p2))

    if not operands:
        raise TypeError("result_type requires at least one operand")
    # fold from the right, exactly like the reference's recursion
    # (types.py:916: rec(a, b, c) = combine(a, rec(b, c))) — the fold
    # direction is observable when a cross-kind scalar sits between arrays
    t, p = classify(operands[-1])
    for op in reversed(operands[:-1]):
        t2, p2 = classify(op)
        t, p = combine(t2, p2, t, p)
    return t


def iscomplex(x) -> "Any":
    """Elementwise imaginary-part-nonzero test (reference: types.py:764)."""
    from . import _operations

    return _operations._local_op(jnp.iscomplex, x, no_cast=True)


def isreal(x) -> "Any":
    """Elementwise real test (reference: types.py:786)."""
    from . import _operations

    return _operations._local_op(jnp.isreal, x, no_cast=True)


class finfo:
    """Float machine limits (reference: types.py:950)."""

    def __new__(cls, ht_dtype: Type[datatype]):
        ht_dtype = canonical_heat_type(ht_dtype)
        if not issubclass(ht_dtype, (floating, complexfloating)):
            raise TypeError(f"data type {ht_dtype} not inexact")
        info = jnp.finfo(ht_dtype.jax_type())
        obj = object.__new__(cls)
        obj.bits = info.bits
        obj.eps = builtins.float(info.eps)
        obj.max = builtins.float(info.max)
        obj.min = builtins.float(info.min)
        obj.tiny = builtins.float(info.tiny)
        obj.resolution = builtins.float(getattr(info, "resolution", info.eps))
        return obj


class iinfo:
    """Integer machine limits (reference: types.py:1005)."""

    def __new__(cls, ht_dtype: Type[datatype]):
        ht_dtype = canonical_heat_type(ht_dtype)
        if not issubclass(ht_dtype, (integer,)) and ht_dtype is not bool:
            raise TypeError(f"data type {ht_dtype} not integral")
        info = jnp.iinfo(ht_dtype.jax_type()) if ht_dtype is not bool else None
        obj = object.__new__(cls)
        if info is None:
            obj.bits, obj.max, obj.min = 8, 1, 0
        else:
            obj.bits = info.bits
            obj.max = builtins.int(info.max)
            obj.min = builtins.int(info.min)
        return obj
