"""Quantized collectives: absmax-scaled wire formats for the movement engines.

Heat's value is moving shards (PAPER.md: every op is local compute + MPI
collectives), and the roofline plane names the collective-heavy rows of
the memory-bound tail as the top unreclaimed cost.  Round 16 proved int8
blocks can ride the ring for one consumer (``spatial.cdist_quantized``);
this module generalizes it into a property of the transport/overlap layer:
every split-crossing byte becomes a tuning decision.

The format: immediately before the ``all_to_all``/``ppermute``, each tile
is snapped to int8 (or ``float8_e4m3fn``) on an absmax grid with ONE f32
scale per tile-row (:func:`absmax_encode` — the same grid math as
``core/quantize.py``'s weight quantizer, which now delegates here); the
quantized payload and its scales ride the collective side by side, and
:func:`absmax_decode` lands them back in the payload dtype inside the
same shard_map program.  Accumulation stays f32.  All-zero rows carry
scale 1 so zeros round-trip exactly — in particular, the engines' masked
pad lanes stay exact zeros on the far side.

Dispatch rides the tuning plane as a ``("wire_f32", "wire_int8",
"wire_fp8")`` arm tuple per (site, geometry, device kind) —
``core/autotune.py``'s :data:`~heat_tpu.core.autotune.WIRE_ARMS`:

- **wire_f32** — today's full-precision collective, byte-for-byte.  This
  is the *reference* arm: explore calls return its result bitwise, and
  ``HEAT_TPU_WIRE=off`` (or ``HEAT_TPU_AUTOTUNE=off``) restores it with
  zero table decisions.
- **wire_int8 / wire_fp8** — 1-byte elements on the wire (~4x less ICI
  traffic for f32 payloads), f32 scales beside them (one per tile-row),
  dequantize-on-landing, measured against the f32 arm by the same
  explore/exploit machinery as ring-vs-GSPMD.  Winners persist through
  ``HEAT_TPU_AUTOTUNE_CACHE`` and ``autotune.merge``.

Exactness-sensitive paths decline STATICALLY — no table entry, no
decision, the f32 wire bit-for-bit: bool/integer payloads
(:func:`eligible`), index gathers whose payload IS the data
(``transport.tiled_take`` — its ``psum_scatter`` also sums across
sources, which per-source scales cannot survive), guard-folded
finiteness chains (``overlap._Spec.fold`` — the guard's verdict must
describe the caller's numbers, not the quantized ones), the traveling
``rs`` accumulator (re-quantizing partial sums every hop compounds the
error), and any caller passing ``exact=True``.

Knobs (both HT001-clean): ``HEAT_TPU_WIRE`` = ``on`` (default: arm per
site via autotune) | ``off`` | ``int8`` | ``fp8`` (force one arm, zero
table decisions — benchmarks/law tests); ``HEAT_TPU_WIRE_MIN_BYTES``
(``autotune.env_bytes``, default 64 KiB) — below it the wire stays f32:
tiny transfers are latency-bound and the quant/dequant pass only adds
work.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax.numpy as jnp

from . import autotune, telemetry

__all__ = [
    "QMAX",
    "absmax_decode",
    "absmax_encode",
    "account",
    "choose",
    "consume",
    "decline",
    "eligible",
    "explore",
    "fp8_available",
    "min_bytes",
    "mode",
    "payload_nbytes",
    "qdtype",
    "set_mode",
    "stats",
]

# absmax maps onto the quantized grid's largest representable magnitude
QMAX = {"int8": 127.0, "fp8": 448.0}

_VALID_MODES = ("on", "off", "int8", "fp8")
_MODE_OVERRIDE: "list[Optional[str]]" = [None]

_WIRE_MIN_BYTES_DEFAULT = 64 << 10  # below this the hop is latency-bound


def qdtype(mode_str: str):
    """The jnp dtype of one wire/quant mode (``int8`` | ``fp8``)."""
    if mode_str == "int8":
        return jnp.dtype(jnp.int8)
    if mode_str == "fp8":
        f8 = getattr(jnp, "float8_e4m3fn", None)
        if f8 is None:
            raise ValueError(
                "fp8 quantization needs a jax with float8_e4m3fn support"
            )
        return jnp.dtype(f8)
    raise ValueError(
        f"quantize dtype must be 'int8' or 'fp8', got {mode_str!r}"
    )


def fp8_available() -> bool:
    return getattr(jnp, "float8_e4m3fn", None) is not None


def mode(env: Optional[dict] = None) -> str:
    """The ``HEAT_TPU_WIRE`` mode: ``on`` (tuned arm per site, default),
    ``off`` (f32 wire bit-for-bit, zero table decisions), or a forced
    ``int8``/``fp8`` arm.  Malformed values raise naming the variable —
    an operator's typo'd mode must not silently become a different one."""
    if _MODE_OVERRIDE[0] is not None:
        return _MODE_OVERRIDE[0]
    raw = (os.environ if env is None else env).get("HEAT_TPU_WIRE", "on")
    raw = raw.strip().lower() or "on"
    if raw not in _VALID_MODES:
        raise ValueError(
            f"HEAT_TPU_WIRE must be one of {_VALID_MODES}, got {raw!r}"
        )
    return raw


def set_mode(mode_str: Optional[str]) -> Optional[str]:
    """Process-wide override of ``HEAT_TPU_WIRE`` (``None`` restores the
    environment variable).  Returns the previous override."""
    if mode_str is not None and mode_str not in _VALID_MODES:
        raise ValueError(
            f"mode must be one of {_VALID_MODES}, got {mode_str!r}"
        )
    prev = _MODE_OVERRIDE[0]
    _MODE_OVERRIDE[0] = mode_str
    return prev


def min_bytes(env: Optional[dict] = None) -> int:
    # one parser with HEAT_TPU_TILE_BYTES (autotune.env_bytes): a
    # malformed threshold raises instead of silently running the default
    return autotune.env_bytes(
        "HEAT_TPU_WIRE_MIN_BYTES", _WIRE_MIN_BYTES_DEFAULT, env
    )


# Registered as the "wire" telemetry group → Prometheus heat_tpu_wire_*
_STATS = telemetry.register_group(
    "wire",
    {
        # dispatches that actually shipped a quantized wire format
        "quantized_dispatches": 0,
        # static declines while the wire plane was live (bool/int dtype,
        # exact=True, index gathers, folded guards, below min-bytes)
        "declined_static": 0,
        # explore rounds (all arms measured, f32 result returned)
        "explores": 0,
        # modeled bytes the f32 wire would have moved for quantized
        # dispatches, and what the quantized wire moved instead — the
        # on-wire delta the cb rows and dashboards prove the win from
        "bytes_logical": 0,
        "bytes_wire": 0,
        "by_arm": {"wire_f32": 0, "wire_int8": 0, "wire_fp8": 0},
    },
)


def stats() -> dict:
    """Snapshot of the ``wire`` counter group (Prometheus:
    ``heat_tpu_wire_*``)."""
    return telemetry.snapshot_group("wire")


# ---------------------------------------------------------------- grid math


def absmax_encode(x, mode_str: str, axes: tuple):
    """Absmax quantization: reduce ``|x|`` over every non-kept axis, snap
    to the int8/fp8 grid.  ``axes`` is the tuple of KEPT (scale-carrying)
    axes — ``(0,)`` gives one f32 scale per tile-row, ``()`` one scalar
    scale for the whole block.  Scales stay f32; all-zero rows get scale
    1 so the dequant is exact zeros, never 0/0.  Pure traced-safe jnp —
    usable inside shard_map bodies (the wire sites) and under the weight
    quantizer's jitted wrappers (``core/quantize.py`` delegates here)."""
    qdt = qdtype(mode_str)
    qmax = QMAX[mode_str]
    xf = x.astype(jnp.float32)
    reduce_axes = tuple(d for d in range(x.ndim) if d not in axes)
    absmax = jnp.max(jnp.abs(xf), axis=reduce_axes)
    scale = jnp.where(absmax > 0, absmax / qmax, 1.0).astype(jnp.float32)
    sb = jnp.expand_dims(scale, reduce_axes)
    grid = xf / sb
    if mode_str == "int8":
        q = jnp.clip(jnp.round(grid), -qmax, qmax).astype(qdt)
    else:
        q = jnp.clip(grid, -qmax, qmax).astype(qdt)
    return q, scale


def absmax_decode(q, scale, axes: tuple, dtype):
    """Land a quantized tile back in ``dtype``: ``q * scale`` with the
    scale broadcast over the reduced axes, f32 multiply."""
    reduce_axes = tuple(d for d in range(q.ndim) if d not in axes)
    sb = jnp.expand_dims(scale, reduce_axes)
    return (q.astype(jnp.float32) * sb).astype(dtype)


# ----------------------------------------------------------------- dispatch


def eligible(dtype, nbytes: int, *, exact: bool = False) -> bool:
    """Static wire eligibility for one transfer: a floating payload (bool
    and integer payloads must arrive bit-exact; complex has no absmax
    grid) of at least ``HEAT_TPU_WIRE_MIN_BYTES``, from a caller that did
    not request ``exact=True``, with the wire plane on.  Ineligible
    transfers take today's f32 path with ZERO wire-arm table decisions."""
    if exact:
        return _note_declined()
    if mode() == "off":
        return False
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return _note_declined()
    if jnp.dtype(dtype).itemsize <= 1:
        return _note_declined()  # already wire-minimal
    if int(nbytes) < min_bytes():
        return _note_declined()
    return True


def _note_declined() -> bool:
    _STATS["declined_static"] += 1
    return False


def decline(site: str) -> None:
    """Count one always-ineligible site consult (``tiled_take``: the
    gathered payload IS the data, and its ``psum_scatter`` sums across
    sources — per-source scales cannot survive the reduction)."""
    if mode() != "off":
        _STATS["declined_static"] += 1


def choose(site: str, geometry: tuple, desc: str = ""):
    """THE wire-arm consult for one ELIGIBLE dispatch: returns
    ``(arm, decision_or_None)``.  A forced mode (``HEAT_TPU_WIRE=int8|
    fp8``) returns its arm with no table decision; ``HEAT_TPU_AUTOTUNE=
    off`` means wire_f32 (the acceptance bit-for-bit restore); otherwise
    the autotune plane decides per (site, geometry, device kind) — the
    caller runs :func:`explore` when ``decision.explore`` is set."""
    m = mode()
    if m in ("int8", "fp8"):
        if m == "fp8" and not fp8_available():
            return "wire_f32", None
        return "wire_" + m, None
    if not autotune.enabled():
        return "wire_f32", None
    key = autotune.wire_key(site, *geometry)
    d = autotune.decide(
        key, "wire_f32", desc=desc or f"wire {site} {geometry}",
        arms=autotune.WIRE_ARMS,
    )
    return d.arm, d


def consume(site: str, geometry: tuple) -> str:
    """Consume-only consult for ELIGIBLE dispatches at sites that must
    not double-execute their program (the fused resplit tail, the lazy
    matmul chain): a forced mode applies directly; otherwise only an
    already-RESOLVED winner for the shared (site, geometry) key is
    served — the eager engine's explores of the same geometry warm it —
    and an unresolved key records the f32 prior.  Returns the wire mode
    string (``""`` | ``"int8"`` | ``"fp8"``)."""
    m = mode()
    if m in ("int8", "fp8"):
        if m == "fp8" and not fp8_available():
            return ""
        return m
    if not autotune.enabled():
        return ""
    key = autotune.wire_key(site, *geometry)
    w = autotune.winner(key)
    if w in ("wire_int8", "wire_fp8"):
        return w[len("wire_"):]
    if w is None:
        autotune.note_prior(key, "wire_f32", site=f"wire_{site}")
    return ""


def explore(decision, run_for) -> object:
    """One explore round at a wire site: run every arm under measurement
    — ``run_for(wire_mode)`` with ``""`` (f32), ``"int8"``, ``"fp8"`` —
    and return the f32 result, so numerics never depend on tuning state.
    An arm that cannot run (no fp8 dtype, a backend refusing the wire
    format) loses by forfeit — inf keeps the explore phase bounded."""
    out, f32_s = autotune.timed(run_for, "")
    autotune.observe(decision.key, "wire_f32", f32_s)
    for arm, wm in (("wire_int8", "int8"), ("wire_fp8", "fp8")):
        if wm == "fp8" and not fp8_available():
            dur = float("inf")
        else:
            try:
                _, dur = autotune.timed(run_for, wm)
            except Exception:
                dur = float("inf")
        autotune.observe(decision.key, arm, dur)
    _STATS["explores"] += 1
    _STATS["by_arm"]["wire_f32"] += 1
    return out


def payload_nbytes(n_elems: int, n_scales: int, mode_str: str) -> int:
    """Exact on-wire byte model of one quantized transfer: 1-byte grid
    elements plus the f32 scales riding beside them."""
    return int(n_elems) * 1 + int(n_scales) * 4


def account(site: str, arm: str, logical_bytes: int, wire_bytes: int) -> None:
    """Ledger one quantized dispatch: the f32 bytes the wire WOULD have
    moved vs what the quantized format moved (``heat_tpu_wire_*``)."""
    _STATS["quantized_dispatches"] += 1
    _STATS["by_arm"][arm] += 1
    _STATS["bytes_logical"] += int(logical_bytes)
    _STATS["bytes_wire"] += int(wire_bytes)
    telemetry.record_event(
        "wire_dispatch", site=site, arm=arm,
        logical_bytes=int(logical_bytes), wire_bytes=int(wire_bytes),
    )
