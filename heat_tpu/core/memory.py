"""Memory layout helpers (reference: heat/core/memory.py).

``copy`` (:13) and ``sanitize_memory_layout`` (:42). XLA owns physical layout
on TPU (tiled, not strided), so C/F order is metadata-only here.
"""

from __future__ import annotations

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]

_JIT_COPY = None


def copy(x: DNDarray) -> DNDarray:
    """A copy of the array (reference: memory.py:13).

    jax arrays are immutable, but a metadata-fresh wrapper is NOT enough:
    a later destructive ``resplit_`` of the original would DONATE the
    shared buffer to XLA and invalidate the "copy".  So the PHYSICAL
    array (pad kept — the split metadata stays truthful) goes through a
    jitted identity, which without donation is guaranteed to produce a
    genuinely new buffer, and the result keeps the source's sharding
    (``jnp.copy`` alone gathers a NamedSharding array to one device)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, got {type(x)}")
    global _JIT_COPY
    if _JIT_COPY is None:
        import jax
        import jax.numpy as jnp

        _JIT_COPY = jax.jit(jnp.copy)
    phys = x.parray
    out = _JIT_COPY(phys)
    if getattr(out, "sharding", None) != getattr(phys, "sharding", None):
        import jax

        out = jax.device_put(out, phys.sharding)
    return DNDarray(out, x.shape, x.dtype, x.split, x.device, x.comm)


def sanitize_memory_layout(x, order: str = "C"):
    """Memory-order handling (reference: memory.py:42). TPU layouts are
    XLA-tiled; ``order`` is accepted for API parity and ignored."""
    if order not in ("C", "F"):
        raise ValueError(f"order must be 'C' or 'F', got {order!r}")
    return x


# method binding (the reference binds copy on DNDarray)
DNDarray.copy = lambda self: copy(self)
