"""Memory layout helpers (reference: heat/core/memory.py).

``copy`` (:13) and ``sanitize_memory_layout`` (:42). XLA owns physical layout
on TPU (tiled, not strided), so C/F order is metadata-only here.
"""

from __future__ import annotations

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(x: DNDarray) -> DNDarray:
    """A (logical) copy of the array (reference: memory.py:13). jax arrays are
    immutable, so a metadata-fresh wrapper suffices."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, got {type(x)}")
    import jax.numpy as jnp

    return DNDarray(
        jnp.copy(x.larray), x.shape, x.dtype, x.split, x.device, x.comm
    )


def sanitize_memory_layout(x, order: str = "C"):
    """Memory-order handling (reference: memory.py:42). TPU layouts are
    XLA-tiled; ``order`` is accepted for API parity and ignored."""
    if order not in ("C", "F"):
        raise ValueError(f"order must be 'C' or 'F', got {order!r}")
    return x


# method binding (the reference binds copy on DNDarray)
DNDarray.copy = lambda self: copy(self)
