"""Parallel I/O (reference: heat/core/io.py, 1111 LoC).

``load``/``save`` dispatch on file extension (io.py:662, 1060); HDF5
(load_hdf5:57/save_hdf5:149), NetCDF (:268/:351), CSV (:713/:926), plus
NumPy ``.npy``/``.npz`` as a TPU-first addition (the natural host format for
JAX).  Feature probes ``supports_hdf5``/``supports_netcdf`` mirror the
reference.  Each loader reads a per-process slab (``comm.chunk``) and
assembles the global sharded array with one host→device transfer per shard.
"""

from __future__ import annotations

import os
from typing import Optional, Union

import numpy as np

from . import devices, factories, types
from .dndarray import DNDarray
from ..parallel.mesh import sanitize_comm

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "load_npy",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "save_npy",
    "supports_hdf5",
    "supports_netcdf",
]

try:
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:
    import netCDF4

    __NETCDF = True
except ImportError:
    netCDF4 = None
    __NETCDF = False

try:
    from scipy.io import netcdf_file as __scipy_netcdf
except ImportError:
    __scipy_netcdf = None


def supports_hdf5() -> bool:
    """True iff h5py is importable (reference: io.py feature probe)."""
    return __HDF5


def supports_netcdf() -> bool:
    """True iff a NetCDF backend is importable (reference: io.py feature
    probe); netCDF4 when present, else scipy's classic-format reader."""
    return __NETCDF or __scipy_netcdf is not None


def load(path: str, *args, **kwargs) -> DNDarray:
    """Extension-dispatched load (reference: io.py:662)."""
    if not isinstance(path, str):
        raise TypeError(f"expected str path, got {type(path)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        return load_hdf5(path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return load_netcdf(path, *args, **kwargs)
    if ext in (".csv", ".txt"):
        return load_csv(path, *args, **kwargs)
    if ext in (".npy", ".npz"):
        return load_npy(path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext!r}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Extension-dispatched save (reference: io.py:1060)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"expected DNDarray, got {type(data)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        return save_hdf5(data, path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return save_netcdf(data, path, *args, **kwargs)
    if ext in (".csv", ".txt"):
        return save_csv(data, path, *args, **kwargs)
    if ext in (".npy",):
        return save_npy(data, path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext!r}")


def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
    slices=None,
) -> DNDarray:
    """Parallel HDF5 load (reference: io.py:57 — a slab per rank via
    comm.chunk, MPI-IO where available)."""
    if not __HDF5:
        raise RuntimeError("h5py is not available")
    comm = sanitize_comm(comm)
    with h5py.File(path, "r") as handle:
        data = handle[dataset]
        if slices is not None:
            data = data[slices]
        else:
            data = data[...]
    arr = np.asarray(data)
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """HDF5 save (reference: io.py:149)."""
    if not __HDF5:
        raise RuntimeError("h5py is not available")
    with h5py.File(path, mode) as handle:
        handle.create_dataset(dataset, data=data.numpy(), **kwargs)


def load_netcdf(
    path: str,
    variable: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """NetCDF load (reference: io.py:268)."""
    comm = sanitize_comm(comm)
    if __NETCDF:
        with netCDF4.Dataset(path, "r") as handle:
            arr = np.asarray(handle.variables[variable][:])
    elif __scipy_netcdf is not None:
        with __scipy_netcdf(path, "r", mmap=False) as handle:
            arr = np.asarray(handle.variables[variable][:])
    else:
        raise RuntimeError("no NetCDF backend (netCDF4 or scipy) is available")
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs) -> None:
    """NetCDF save (reference: io.py:351)."""
    if not __NETCDF:
        if __scipy_netcdf is not None and mode == "w":
            arr = data.numpy()
            with __scipy_netcdf(path, "w") as handle:
                for i, dim in enumerate(arr.shape):
                    handle.createDimension(f"dim_{i}", dim)
                var = handle.createVariable(
                    variable, arr.dtype.char, tuple(f"dim_{i}" for i in range(arr.ndim))
                )
                var[:] = arr
            return
        raise RuntimeError("no NetCDF backend (netCDF4 or scipy) is available")
    with netCDF4.Dataset(path, mode) as handle:
        arr = data.numpy()
        for i, dim in enumerate(arr.shape):
            handle.createDimension(f"dim_{i}", dim)
        var = handle.createVariable(variable, arr.dtype, tuple(f"dim_{i}" for i in range(arr.ndim)))
        var[:] = arr


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """CSV load (reference: io.py:713 — byte-range splitting per rank there).

    Parsing goes through the native multi-threaded byte-range parser
    (heat_tpu/native, the same line-alignment rule as the reference's
    per-rank ranges) when available, with a NumPy fallback; placement onto
    the mesh is one sharded device_put either way."""
    comm = sanitize_comm(comm)
    np_dtype = np.dtype(types.canonical_heat_type(dtype).jax_type())
    arr = None
    if (
        len(sep) == 1
        and encoding in ("utf-8", "ascii", None)
        and np_dtype == np.float32  # the native parser emits f32 exactly
    ):
        from .. import native

        arr = native.csv_parse(path, header_lines=header_lines, sep=sep)
        if arr is not None:
            arr = np.squeeze(arr)  # match genfromtxt: 1-D for single col/row
    if arr is None:
        arr = np.genfromtxt(
            path, delimiter=sep, skip_header=header_lines, dtype=np_dtype, encoding=encoding
        )
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines=None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    comm=None,
    truncate: bool = True,
    **kwargs,
) -> None:
    """CSV save (reference: io.py:926).  ``comm`` is accepted for signature
    parity (the write is host-side here); ``truncate=False`` appends."""
    arr = data.numpy()
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    mode = "w" if truncate else "a"
    # header only at the start of a file — appending must not repeat it
    appending_to_content = mode == "a" and os.path.exists(path) and os.path.getsize(path) > 0
    header = "\n".join(header_lines) if header_lines and not appending_to_content else ""
    with open(path, mode, encoding=encoding, newline="") as fh:
        np.savetxt(fh, arr, delimiter=sep, fmt=fmt, header=header, comments="")


def load_npy(path: str, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """NumPy .npy/.npz load (TPU-first addition)."""
    arr = np.load(path)
    if isinstance(arr, np.lib.npyio.NpzFile):
        arr = arr[arr.files[0]]
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_npy(data: DNDarray, path: str) -> None:
    """NumPy .npy save (TPU-first addition)."""
    np.save(path, data.numpy())


DNDarray.save = lambda self, path, *args, **kwargs: save(self, path, *args, **kwargs)
DNDarray.save_hdf5 = lambda self, path, dataset, mode="w", **kw: save_hdf5(self, path, dataset, mode, **kw)
DNDarray.save_netcdf = lambda self, path, variable, mode="w", **kw: save_netcdf(self, path, variable, mode, **kw)
