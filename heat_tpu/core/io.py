"""Parallel I/O (reference: heat/core/io.py, 1111 LoC).

``load``/``save`` dispatch on file extension (io.py:662, 1060); HDF5
(load_hdf5:57/save_hdf5:149), NetCDF (:268/:351), CSV (:713/:926), plus
NumPy ``.npy``/``.npz`` as a TPU-first addition (the natural host format for
JAX).  Feature probes ``supports_hdf5``/``supports_netcdf`` mirror the
reference.  Split loads read one slab per device shard (the mesh chunk
rule) and stitch the global array with
``jax.make_array_from_single_device_arrays``; split saves write one shard
slab at a time — in neither direction does the global logical array
materialize on the host (the reference's MPI-IO slab-per-rank model,
io.py:57-266, restated for a single controller).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Union

import jax
import numpy as np

from . import devices, factories, stream, types
from .dndarray import DNDarray, _physical_dim, _split_axis_shards
from ..parallel.mesh import sanitize_comm

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "load_npy",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "save_npy",
    "supports_hdf5",
    "supports_netcdf",
]

try:
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:
    import netCDF4

    __NETCDF = True
except ImportError:
    netCDF4 = None
    __NETCDF = False

try:
    from scipy.io import netcdf_file as __scipy_netcdf
except ImportError:
    __scipy_netcdf = None


def supports_hdf5() -> bool:
    """True iff h5py is importable (reference: io.py feature probe)."""
    return __HDF5


def supports_netcdf() -> bool:
    """True iff a NetCDF backend is importable (reference: io.py feature
    probe); netCDF4 when present, else scipy's classic-format reader."""
    return __NETCDF or __scipy_netcdf is not None


def _read_region(source, sel) -> np.ndarray:
    """All slab reads funnel through here (tests spy on it to prove the
    loaders never request more than one shard's slab at a time)."""
    return np.asarray(source[sel])


def _write_region(sink, sel, value: np.ndarray) -> None:
    """All slab writes funnel through here (same test hook as reads)."""
    sink[sel] = value


def _assemble_sharded(
    read_slab: Callable[[int, int], np.ndarray],
    gshape,
    np_dtype,
    split: int,
    device,
    comm,
) -> DNDarray:
    """Assemble a split DNDarray from per-shard slabs, one host buffer at a
    time (reference: io.py:57-147 reads one slab per rank via comm.chunk).

    ``read_slab(lo, hi)`` returns the logical rows ``[lo, hi)`` of the split
    dim (full extent elsewhere).  Each slab is padded to the even physical
    chunk, placed on its device, and the global array is stitched with
    ``jax.make_array_from_single_device_arrays`` — the global logical array
    never exists on the host.
    """
    ndim = len(gshape)
    split = split % ndim
    n = gshape[split]
    phys_shape = list(gshape)
    phys_shape[split] = _physical_dim(n, comm.size)
    sharding = comm.sharding(split, ndim)
    idx_map = sharding.addressable_devices_indices_map(tuple(phys_shape))
    # group devices by split-axis offset: multi-axis meshes replicate over
    # the other axes, and each slab must hit the disk only once
    groups = {}
    for dev, idx in idx_map.items():
        start = idx[split].start or 0
        groups.setdefault(start, (idx, []))[1].append(dev)
    arrays = []
    for start, (idx, devs) in groups.items():
        stop = idx[split].stop
        stop = phys_shape[split] if stop is None else stop
        lo, hi = min(start, n), min(stop, n)
        slab = read_slab(lo, hi)
        if slab.dtype != np_dtype:
            slab = slab.astype(np_dtype)
        if hi - lo < stop - start:
            pad = [(0, 0)] * ndim
            pad[split] = (0, (stop - start) - (hi - lo))
            slab = np.pad(slab, pad)
        arrays.extend(jax.device_put(slab, dev) for dev in devs)
    garray = jax.make_array_from_single_device_arrays(
        tuple(phys_shape), sharding, arrays
    )
    return DNDarray(
        garray,
        tuple(gshape),
        types.canonical_heat_type(np_dtype),
        split,
        devices.sanitize_device(device),
        comm,
    )


def _iter_shard_slabs(data: DNDarray):
    """Yield ``(rank, slices, slab)`` per device shard in split order, one
    host buffer at a time — the save-side counterpart of
    :func:`_assemble_sharded` (reference: slab-per-rank writes,
    io.py:149-266)."""
    split = data.split
    shards = _split_axis_shards(data.parray, split)
    for r, sh in enumerate(shards):
        _, lshape, slices = data.comm.chunk(data.shape, split, rank=r)
        if lshape[split] == 0:
            continue
        slab = np.asarray(sh.data)
        sel = [slice(None)] * data.ndim
        sel[split] = slice(0, lshape[split])
        yield r, slices, slab[tuple(sel)]


def load(path: str, *args, **kwargs) -> DNDarray:
    """Extension-dispatched load (reference: io.py:662)."""
    if not isinstance(path, str):
        raise TypeError(f"expected str path, got {type(path)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        return load_hdf5(path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return load_netcdf(path, *args, **kwargs)
    if ext in (".csv", ".txt"):
        return load_csv(path, *args, **kwargs)
    if ext in (".npy", ".npz"):
        return load_npy(path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext!r}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Extension-dispatched save (reference: io.py:1060)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"expected DNDarray, got {type(data)}")
    ext = os.path.splitext(path)[-1].lower().strip()
    if ext in (".h5", ".hdf5"):
        return save_hdf5(data, path, *args, **kwargs)
    if ext in (".nc", ".nc4", ".netcdf"):
        return save_netcdf(data, path, *args, **kwargs)
    if ext in (".csv", ".txt"):
        return save_csv(data, path, *args, **kwargs)
    if ext in (".npy",):
        return save_npy(data, path, *args, **kwargs)
    raise ValueError(f"unsupported file extension {ext!r}")


def _normalize_slices(slices, shape):
    """Normalize a user ``slices`` argument (slice or tuple of slices, None
    entries allowed) into one concrete ``slice`` per dim plus the resulting
    shape."""
    if not isinstance(slices, tuple):
        slices = (slices,)
    if len(slices) > len(shape):
        raise ValueError(f"too many slices for shape {shape}")
    norm, out_shape = [], []
    for d, dim in enumerate(shape):
        s = slices[d] if d < len(slices) else None
        if s is None:
            s = slice(None)
        if not isinstance(s, slice):
            raise TypeError(f"slices entries must be slice/None, got {type(s)}")
        start, stop, step = s.indices(dim)
        norm.append(slice(start, stop, step))
        out_shape.append(max(0, -(-(stop - start) // step)))
    return tuple(norm), tuple(out_shape)


def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
    slices=None,
) -> DNDarray:
    """Parallel HDF5 load: one slab per device shard via the mesh chunk
    rule, assembled with ``jax.make_array_from_single_device_arrays`` — the
    full dataset is never materialized on the host when ``split`` is given
    (reference: io.py:57-147, a slab per rank via comm.chunk + MPI-IO)."""
    if not __HDF5:
        raise RuntimeError("h5py is not available")
    comm = sanitize_comm(comm)
    np_dtype = np.dtype(types.canonical_heat_type(dtype).jax_type())
    with h5py.File(path, "r") as handle:
        dset = handle[dataset]
        base, gshape = _normalize_slices(
            slices if slices is not None else (), dset.shape
        )
        if split is None or comm.size == 1 or len(gshape) == 0:
            arr = _read_region(dset, base)
            return factories.array(
                arr, dtype=dtype, split=split, device=device, comm=comm
            )
        split_ = split % len(gshape)

        # shared chunk reader (core/stream.py): the one copy of the
        # rank-local slab math, honoring the user slices' step
        def read_slab(lo: int, hi: int) -> np.ndarray:
            return stream.read_rows(dset, lo, hi, split_axis=split_, base=base)

        return _assemble_sharded(read_slab, gshape, np_dtype, split_, device, comm)


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Parallel HDF5 save: the dataset is created at the global shape and
    filled one shard slab at a time — no host copy of the global array
    (reference: io.py:149-266)."""
    if not __HDF5:
        raise RuntimeError("h5py is not available")
    np_dtype = np.dtype(data.dtype.jax_type())
    with h5py.File(path, mode) as handle:
        if dataset in handle:
            # reference (and plain h5py create_dataset) raise on a name
            # collision under append modes — silent replacement would be
            # silent data loss for ported code (advisor round 2).  Mode
            # 'w' truncates the file first, so it can't reach here.
            raise ValueError(
                f"dataset {dataset!r} already exists in {path!r}; "
                "delete it first or save to a new name"
            )
        dset = handle.create_dataset(
            dataset, shape=data.shape, dtype=np_dtype, **kwargs
        )
        if data.split is None or data.comm.size == 1:
            _write_region(dset, Ellipsis, data.numpy())
            return
        for _, slices, slab in _iter_shard_slabs(data):
            _write_region(dset, slices, slab)


def load_netcdf(
    path: str,
    variable: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """NetCDF load, slab-per-shard along ``split`` like :func:`load_hdf5`
    (reference: io.py:268)."""
    comm = sanitize_comm(comm)
    np_dtype = np.dtype(types.canonical_heat_type(dtype).jax_type())
    if __NETCDF:
        opener = lambda: netCDF4.Dataset(path, "r")  # noqa: E731
    elif __scipy_netcdf is not None:
        # mmap keeps slab reads lazy for the classic format
        opener = lambda: __scipy_netcdf(path, "r", mmap=True)  # noqa: E731
    else:
        raise RuntimeError("no NetCDF backend (netCDF4 or scipy) is available")
    handle = opener()
    var = read_slab = None
    try:
        var = handle.variables[variable]
        gshape = tuple(var.shape)
        if split is None or comm.size == 1 or len(gshape) == 0:
            # np.array: copy out of the mmap before the file closes
            arr = np.array(_read_region(var, tuple(slice(0, n) for n in gshape)))
            return factories.array(
                arr, dtype=dtype, split=split, device=device, comm=comm
            )
        split_ = split % len(gshape)

        def read_slab(lo: int, hi: int) -> np.ndarray:
            # copy=True: slabs must not stay views into scipy's file mmap
            return stream.read_rows(var, lo, hi, split_axis=split_, copy=True)

        return _assemble_sharded(read_slab, gshape, np_dtype, split_, device, comm)
    finally:
        # scipy's mmap-backed reader warns about lingering views on close;
        # every slab was copied with np.array above, so the warning is noise
        import warnings

        var = read_slab = None  # noqa: F841 — drop mmap views before close
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            handle.close()


def _netcdf_write_var(var, data: DNDarray) -> None:
    """Fill a NetCDF variable one shard slab at a time."""
    if data.split is None or data.comm.size == 1:
        _write_region(var, tuple(slice(0, n) for n in data.shape) or Ellipsis, data.numpy())
        return
    for _, slices, slab in _iter_shard_slabs(data):
        _write_region(var, slices, slab)


def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs) -> None:
    """NetCDF save, slab-per-shard writes (reference: io.py:351)."""
    np_dtype = np.dtype(data.dtype.jax_type())
    if not __NETCDF:
        if __scipy_netcdf is not None and mode == "w":
            with __scipy_netcdf(path, "w") as handle:
                for i, dim in enumerate(data.shape):
                    handle.createDimension(f"dim_{i}", dim)
                var = handle.createVariable(
                    variable, np_dtype.char, tuple(f"dim_{i}" for i in range(data.ndim))
                )
                _netcdf_write_var(var, data)
            return
        raise RuntimeError("no NetCDF backend (netCDF4 or scipy) is available")
    with netCDF4.Dataset(path, mode) as handle:
        for i, dim in enumerate(data.shape):
            handle.createDimension(f"dim_{i}", dim)
        var = handle.createVariable(
            variable, np_dtype, tuple(f"dim_{i}" for i in range(data.ndim))
        )
        _netcdf_write_var(var, data)


def _csv_row_bounds_py(path: str, header_lines: int, nshards: int):
    """Pure-Python fallback for native.csv_row_bounds: stream the file once
    recording data-line offsets (blank/comment lines skipped, matching
    np.genfromtxt), then cut at the even ``ceil(rows/nshards)`` chunk rule."""
    offsets = []
    with open(path, "rb") as fh:
        skipped = 0
        while skipped < header_lines and fh.readline():
            skipped += 1
        pos = fh.tell()
        for line in fh:
            body = line.split(b"#", 1)[0].strip()
            if body:
                offsets.append(pos)
            pos += len(line)
        end = pos
    rows = len(offsets)
    per = -(-rows // nshards) if rows else 0
    bounds = [
        offsets[min(k * per, rows)] if per and k * per < rows else end
        for k in range(nshards)
    ]
    if rows:
        bounds[0] = offsets[0]
    bounds.append(end)
    return bounds, rows


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """CSV load (reference: io.py:713 — per-rank line-aligned byte ranges).

    With ``split=0`` the file is cut into one line-aligned byte range per
    device shard at the mesh chunk rule (native two-pass scan, Python
    fallback) and each range is parsed and placed independently — host
    memory stays one slab, matching the reference's slab-per-rank reads.
    Other splits parse fully (native multi-threaded parser when available)
    and shard on placement."""
    comm = sanitize_comm(comm)
    np_dtype = np.dtype(types.canonical_heat_type(dtype).jax_type())
    from .. import native

    native_ok = (
        len(sep) == 1
        and encoding in ("utf-8", "ascii", None)
        and np_dtype == np.float32  # the native parser emits f32 exactly
    )

    if split == 0 and comm.size > 1:
        bounds = (
            native.csv_row_bounds(path, header_lines, comm.size)
            if native_ok
            else None
        )
        if bounds is None:
            bounds = _csv_row_bounds_py(path, header_lines, comm.size)
        bounds, nrows = bounds
        if nrows > 1:  # single row squeezes to 1-D; use the full parse below
            per = -(-nrows // comm.size)
            # one tiny probe parse for the column count
            first = _csv_parse_byte_range(
                path, bounds[0], bounds[-1], sep,
                np_dtype, encoding, native_ok, probe=True,
            )
            ncols = first.shape[1]
            gshape = (nrows, ncols) if ncols > 1 else (nrows,)

            def read_slab(lo: int, hi: int) -> np.ndarray:
                if hi <= lo:
                    return np.empty(
                        (0, ncols) if ncols > 1 else (0,), dtype=np_dtype
                    )
                r = lo // per
                assert lo == r * per and hi == min((r + 1) * per, nrows)
                slab = _csv_parse_byte_range(
                    path, bounds[r], bounds[r + 1], sep, np_dtype, encoding,
                    native_ok,
                )
                return slab if ncols > 1 else slab.reshape(-1)

            return _assemble_sharded(read_slab, gshape, np_dtype, 0, device, comm)

    arr = None
    if native_ok:
        arr = native.csv_parse(path, header_lines=header_lines, sep=sep)
        if arr is not None:
            arr = np.squeeze(arr)  # match genfromtxt: 1-D for single col/row
    if arr is None:
        arr = np.genfromtxt(
            path, delimiter=sep, skip_header=header_lines, dtype=np_dtype, encoding=encoding
        )
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def _csv_parse_byte_range(
    path, start, stop, sep, np_dtype, encoding, native_ok, probe=False
) -> np.ndarray:
    """Parse the line-aligned byte range [start, stop) into a 2-D array.
    ``probe`` parses only the range's first line (column-count sniff)."""
    if native_ok and not probe:
        from .. import native

        arr = native.csv_parse_range(path, start, stop, sep=sep)
        if arr is not None:
            return arr.astype(np_dtype, copy=False)
    import io as _io

    with open(path, "rb") as fh:
        fh.seek(start)
        # probe: exactly the first line, however long (a 64KB-capped read
        # would truncate very wide rows and mis-sniff the column count)
        raw = fh.readline() if probe else fh.read(stop - start)
    arr = np.genfromtxt(
        _io.BytesIO(raw), delimiter=sep, dtype=np_dtype,
        encoding=encoding or "utf-8",
    )
    return np.atleast_2d(arr) if arr.ndim < 2 else arr


def save_csv(
    data: DNDarray,
    path: str,
    header_lines=None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    comm=None,
    truncate: bool = True,
    **kwargs,
) -> None:
    """CSV save (reference: io.py:926).  ``comm`` is accepted for signature
    parity (the write is host-side here); ``truncate=False`` appends."""
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    mode = "w" if truncate else "a"
    # header only at the start of a file — appending must not repeat it
    appending_to_content = mode == "a" and os.path.exists(path) and os.path.getsize(path) > 0
    header = "\n".join(header_lines) if header_lines and not appending_to_content else ""
    with open(path, mode, encoding=encoding, newline="") as fh:
        if data.split is None or data.comm.size == 1:
            np.savetxt(fh, data.numpy(), delimiter=sep, fmt=fmt, header=header, comments="")
            return
        if header:
            fh.write(header + "\n")
        if data.split != 0:
            # row-major text wants row blocks: reshard onto rows first
            from .manipulations import resplit

            data = resplit(data, 0)
        # one shard slab at a time — never the global array
        for _, _, slab in _iter_shard_slabs(data):
            np.savetxt(fh, slab, delimiter=sep, fmt=fmt)


def load_npy(path: str, dtype=None, split: Optional[int] = None, device=None, comm=None) -> DNDarray:
    """NumPy .npy/.npz load (TPU-first addition).  ``.npy`` with a split
    reads one memory-mapped slab per shard; the global array never lands on
    the host."""
    comm = sanitize_comm(comm)
    if path.endswith(".npy"):
        arr = np.load(path, mmap_mode="r")
        gshape = tuple(arr.shape)
        if split is not None and comm.size > 1 and len(gshape) > 0:
            split_ = split % len(gshape)
            np_dtype = (
                arr.dtype
                if dtype is None
                else np.dtype(types.canonical_heat_type(dtype).jax_type())
            )

            def read_slab(lo: int, hi: int) -> np.ndarray:
                return stream.read_rows(arr, lo, hi, split_axis=split_, copy=True)

            return _assemble_sharded(read_slab, gshape, np_dtype, split_, device, comm)
        arr = np.array(arr)
    else:
        arr = np.load(path)
        if isinstance(arr, np.lib.npyio.NpzFile):
            arr = arr[arr.files[0]]
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_npy(data: DNDarray, path: str) -> None:
    """NumPy .npy save (TPU-first addition).  Split arrays stream one shard
    slab at a time into a memory-mapped destination."""
    if data.split is None or data.comm.size == 1:
        np.save(path, data.numpy())
        return
    np_dtype = np.dtype(data.dtype.jax_type())
    out = np.lib.format.open_memmap(
        path, mode="w+", dtype=np_dtype, shape=data.shape
    )
    try:
        for _, slices, slab in _iter_shard_slabs(data):
            _write_region(out, slices, slab)
        out.flush()
    finally:
        del out


DNDarray.save = lambda self, path, *args, **kwargs: save(self, path, *args, **kwargs)
DNDarray.save_hdf5 = lambda self, path, dataset, mode="w", **kw: save_hdf5(self, path, dataset, mode, **kw)
DNDarray.save_netcdf = lambda self, path, variable, mode="w", **kw: save_netcdf(self, path, variable, mode, **kw)
