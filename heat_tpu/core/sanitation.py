"""Input/output validation (reference: heat/core/sanitation.py).

``sanitize_distribution`` (:31-157) — the reference's redistribution workhorse
— is declarative here: aligning an operand to a target's layout is a
``resplit`` (one device_put). ``sanitize_in`` (:159), ``sanitize_out`` (:259),
``sanitize_lshape`` (:213), ``scalar_to_1d`` (:375) keep their roles.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from .dndarray import DNDarray

__all__ = [
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_out",
    "sanitize_distribution",
    "sanitize_lshape",
    "sanitize_sequence",
    "scalar_to_1d",
    "sanitize_in_tensor",
]


def sanitize_in(x) -> None:
    """Raise unless ``x`` is a DNDarray (reference: sanitation.py:159)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input must be a DNDarray, got {type(x)}")


def sanitize_in_tensor(x):
    """Accept DNDarray or array-like, return the jax value."""
    import jax.numpy as jnp

    if isinstance(x, DNDarray):
        return x.larray
    return jnp.asarray(x)


def sanitize_out(
    out: DNDarray,
    output_shape: Tuple[int, ...],
    output_split: Optional[int],
    output_device,
    output_comm=None,
) -> None:
    """Validate an ``out=`` target (reference: sanitation.py:259)."""
    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, got {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"expected out shape {tuple(output_shape)}, got {tuple(out.shape)}")
    # reference semantics (sanitation.py:259): out adopts the result's
    # distribution; invalidate cached shard metadata along with it
    object.__setattr__(out, "_DNDarray__split", output_split)
    object.__setattr__(out, "_DNDarray__gshape", tuple(output_shape))
    object.__setattr__(out, "_DNDarray__lshape_map", None)


def sanitize_distribution(*args: DNDarray, target: DNDarray, diff_map=None):
    """Align every input to the target's split (reference: sanitation.py:31).

    Under GSPMD this is a metadata-level resplit; the data movement happens in
    the compiled computation."""
    out = []
    for x in args:
        sanitize_in(x)
        if x.split == target.split or x.ndim == 0:
            out.append(x)
        else:
            from . import manipulations

            out.append(manipulations.resplit(x, target.split))
    return out[0] if len(out) == 1 else tuple(out)


def sanitize_infinity(x) -> Union[int, float]:
    """Largest representable value for the dtype of ``x`` (reference:
    sanitation.py:177); used to substitute infinity in integer contexts."""
    dtype = np.dtype(x.larray.dtype if isinstance(x, DNDarray) else x.dtype)
    if np.issubdtype(dtype, np.floating):
        return float(np.finfo(dtype).max)
    return int(np.iinfo(dtype).max)


def sanitize_sequence(seq) -> list:
    """Validate that ``seq`` is a list or tuple, return a list (reference:
    sanitation.py:351)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    raise TypeError(f"seq must be a list or a tuple, got {type(seq)}")


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Validate that a local tensor matches the array's shard shape
    (reference: sanitation.py:213)."""
    if tuple(tensor.shape) != tuple(array.lshape):
        raise ValueError(f"local tensor shape {tuple(tensor.shape)} != lshape {array.lshape}")


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Reshape a scalar DNDarray to shape (1,) (reference: sanitation.py:375)."""
    if x.ndim == 0:
        return DNDarray(
            x.larray.reshape(1), (1,), x.dtype, None, x.device, x.comm
        )
    return x
