"""Array factories (reference: heat/core/factories.py).

``array()`` (reference :151) is the central constructor; ``arange`` (:41),
``empty/full/ones/zeros`` + ``_like`` variants via shared helpers (:672, :726),
``eye`` (:593), ``linspace`` (:1053), ``logspace`` (:1139), ``meshgrid``
(:1202), ``asarray`` (:441), ``from_partitioned`` (:796).

TPU-native behavior: a factory builds the *global* array and places it with a
``NamedSharding`` in one step; with a ``split``, XLA materializes each shard on
its own device (no scatter of host data when the input is a shape, and a
single host→device transfer per shard when the input is host data).
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Optional, Sequence, Tuple, Type, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices, memtrack, types
from .dndarray import DNDarray, _physical_dim, _to_physical
from ..parallel.mesh import MeshComm, sanitize_comm
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "full",
    "full_like",
    "from_partitioned",
    "from_partition_dict",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def _finalize(
    garray: jax.Array,
    split: Optional[int],
    device: Optional[Union[str, devices.Device]],
    comm: Optional[MeshComm],
    dtype: Optional[Type[types.datatype]] = None,
) -> DNDarray:
    """Place a global jax array onto the mesh with the canonical sharding for
    ``split`` and wrap it."""
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    split = sanitize_axis(garray.shape, split)
    gshape = tuple(garray.shape)
    garray = _to_physical(garray, gshape, split, comm)
    heat_type = types.canonical_heat_type(garray.dtype) if dtype is None else dtype
    # every factory funnels here: ledger the buffer NOW so the creation
    # site is the user's factory call, not the DNDarray ctor (the ctor's
    # own registration dedupes to a rebind)
    memtrack.register_buffer(garray, tag="leaf", split=split)
    return DNDarray(garray, gshape, heat_type, split, device, comm)


def array(
    obj,
    dtype: Optional[Type[types.datatype]] = None,
    copy: bool = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm: Optional[MeshComm] = None,
) -> DNDarray:
    """Create a DNDarray from array-like data (reference: factories.py:151).

    ``split`` shards the (global) input along that axis; ``is_split`` declares
    the input to be this *process's* local chunk of a pre-distributed global
    array (multi-host; with a single controller process the local chunk is the
    whole array).
    """
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive")
    comm = sanitize_comm(comm)

    if isinstance(obj, DNDarray):
        base = obj.larray
        if dtype is not None:
            base = base.astype(types.canonical_heat_type(dtype).jax_type())
        if split is None and is_split is None:
            split = obj.split
        new = _finalize(base, split if is_split is None else is_split, device or obj.device, comm, dtype=None)
        return new

    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)

    if is_split is not None:
        # multi-host: assemble the global array from per-process local chunks
        local = np.asarray(obj, order=order)
        if dtype is not None:
            local = local.astype(np.dtype(types._np_equivalent(dtype)))
        if local.ndim < ndmin:
            local = local.reshape((1,) * (ndmin - local.ndim) + local.shape)
        is_split = sanitize_axis(local.shape, is_split)
        if jax.process_count() > 1:
            sharding = comm.sharding(is_split, local.ndim)
            garray = jax.make_array_from_process_local_data(sharding, local)
            return _finalize(garray, is_split, device, comm)
        return _finalize(jnp.asarray(local), is_split, device, comm)

    if isinstance(obj, (jax.Array,)):
        garray = obj
        if dtype is not None:
            garray = garray.astype(dtype.jax_type())
    else:
        host = np.asarray(obj, order=order)
        if dtype is not None:
            host = host.astype(np.dtype(types._np_equivalent(dtype)))
        garray = jnp.asarray(host)
    if garray.ndim < ndmin:
        garray = garray.reshape((1,) * (ndmin - garray.ndim) + garray.shape)
    return _finalize(garray, split, device, comm)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None, comm=None) -> DNDarray:
    """No-copy-when-possible array construction (reference: factories.py:441)."""
    if isinstance(obj, DNDarray) and dtype is None and is_split is None:
        return obj
    return array(obj, dtype=dtype, copy=False, order=order, is_split=is_split, device=device, comm=comm)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Evenly spaced values in [start, stop) (reference: factories.py:41)."""
    num_args = len(args)
    if num_args == 1:
        start, stop, step = 0, args[0], 1
    elif num_args == 2:
        start, stop, step = args[0], args[1], 1
    elif num_args == 3:
        start, stop, step = args
    else:
        raise TypeError(f"arange takes 1-3 positional arguments, got {num_args}")
    jdtype = types.canonical_heat_type(dtype).jax_type() if dtype is not None else None
    garray = jnp.arange(start, stop, step, dtype=jdtype)
    return _finalize(garray, split, device, comm)


@functools.lru_cache(maxsize=512)
def _factory_jit(kind: str, pshape, jdtype, sharding):
    """One compiled fill program per (kind, shape, dtype, sharding).

    Cached because a fresh ``jax.jit(lambda ...)`` per call misses jax's
    trace cache (new function identity) and re-compiles every ``zeros``/
    ``ones``/``full`` — a full compile round trip per factory call.  The
    fill value for ``full`` rides as a traced operand so all values share
    one program.
    """
    if kind == "full":
        return jax.jit(
            lambda v: jnp.full(pshape, v.astype(jdtype)), out_shardings=sharding
        )
    fill = jnp.zeros if kind == "zeros" else jnp.ones
    return jax.jit(lambda: fill(pshape, jdtype), out_shardings=sharding)


@functools.lru_cache(maxsize=512)
def _eye_jit(pshape, n, m, jdtype, sharding):
    """One compiled SHARDED eye program per (shape, dtype, sharding): each
    device computes its slab of the iota compare — the previous eager
    ``jnp.eye(n, m)`` materialized the whole O(n*m) identity replicated on
    every device before sharding it (round-5 global-temporary sweep;
    VERDICT r4 weak #4).  Padded cells (i >= n or j >= m) stay zero."""

    from .dndarray import _diag_mask

    def build():
        return jnp.where(
            _diag_mask(pshape, n, m), jnp.ones((), jdtype), jnp.zeros((), jdtype)
        )

    return jax.jit(build, out_shardings=sharding)


def __factory(shape, dtype, split, kind, device, comm, order="C", fill_value=None) -> DNDarray:
    """Shared shape-based factory (reference: factories.py:672)."""
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    comm = sanitize_comm(comm)
    split = sanitize_axis(shape, split)
    # build on-device directly at the physical (even-chunk) shape: jit with
    # out_shardings materializes each shard on its own device, no host round-trip
    pshape = list(shape)
    if split is not None and shape:
        pshape[split] = _physical_dim(shape[split], comm.size)
    sharding = comm.sharding(split, len(shape))
    fn = _factory_jit(kind, tuple(pshape), jnp.dtype(dtype.jax_type()), sharding)
    if kind == "full":
        garray = fn(jnp.asarray(fill_value, dtype.jax_type()))
    else:
        garray = fn()
    return DNDarray(
        garray, shape, types.canonical_heat_type(garray.dtype),
        split, devices.sanitize_device(device), comm,
    )


def __factory_like(a, dtype, split, factory, device, comm, **kwargs) -> DNDarray:
    """Shared like-based factory (reference: factories.py:726)."""
    if isinstance(a, DNDarray):
        shape = a.shape
        dtype = dtype if dtype is not None else a.dtype
        split = split if split is not None else a.split
        device = device if device is not None else a.device
        comm = comm if comm is not None else a.comm
    else:
        arr = np.asarray(a)
        shape = arr.shape
        dtype = dtype if dtype is not None else types.canonical_heat_type(arr.dtype)
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, **kwargs)


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Uninitialized array (reference: factories.py:495). XLA has no
    uninitialized allocation; zeros are as cheap under fusion."""
    return __factory(shape, dtype, split, "zeros", device, comm)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, empty, device, comm)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order: str = "C") -> DNDarray:
    """2-D identity-like array (reference: factories.py:593)."""
    if order not in ("C",):
        # the reference only ever materializes C order; F-order layouts do
        # not exist for jax.Arrays (XLA picks physical layout)
        raise NotImplementedError("only C (row-major) order is supported")
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = sanitize_shape(shape)
        if len(shape) == 1:
            n = m = shape[0]
        else:
            n, m = shape[0], shape[1]
    dtype_ = types.canonical_heat_type(dtype)
    comm = sanitize_comm(comm)
    split_ = sanitize_axis((n, m), split)
    pshape = [n, m]
    if split_ is not None:
        pshape[split_] = _physical_dim(pshape[split_], comm.size)
    garray = _eye_jit(
        tuple(pshape), n, m, dtype_.jax_type(), comm.sharding(split_, 2)
    )()
    return DNDarray(
        garray, (n, m), types.canonical_heat_type(garray.dtype),
        split_, devices.sanitize_device(device), comm,
    )


def full(shape, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Constant-filled array (reference: factories.py:946)."""
    if dtype is None:
        dtype = types.float32  # reference default (factories.py:946)
    value = fill_value.item() if hasattr(fill_value, "item") else fill_value  # ht: HT002 ok — fill_value is a caller-supplied host scalar, not an engine value
    return __factory(shape, dtype, split, "full", device, comm, fill_value=value)


def full_like(a, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, lambda *ar, **kw: full(ar[0], fill_value, dtype=kw.get("dtype"), split=kw.get("split"), device=kw.get("device"), comm=kw.get("comm")), device, comm)


def linspace(
    start, stop, num=50, endpoint=True, retstep=False, dtype=None, split=None, device=None, comm=None
):
    """num evenly spaced samples over [start, stop] (reference: factories.py:1053)."""
    num = int(num)
    jdtype = types.canonical_heat_type(dtype).jax_type() if dtype is not None else None
    garray = jnp.linspace(float(start), float(stop), num=num, endpoint=endpoint, dtype=jdtype)
    ht = _finalize(garray, split, device, comm)
    if retstep:
        step = (float(stop) - float(start)) / max(num - (1 if endpoint else 0), 1)
        return ht, step
    return ht


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Log-spaced samples (reference: factories.py:1139)."""
    jdtype = types.canonical_heat_type(dtype).jax_type() if dtype is not None else None
    garray = jnp.logspace(float(start), float(stop), num=int(num), endpoint=endpoint, base=base, dtype=jdtype)
    return _finalize(garray, split, device, comm)


def meshgrid(*arrays, indexing: str = "xy") -> List[DNDarray]:
    """Coordinate matrices from coordinate vectors (reference: factories.py:1202).

    The reference supports at most one split input; here any input split is
    propagated to the corresponding output dimension."""
    if not arrays:
        return []
    splits = [a.split if isinstance(a, DNDarray) else None for a in arrays]
    jargs = [a.larray if isinstance(a, DNDarray) else jnp.asarray(a) for a in arrays]
    comm = next((a.comm for a in arrays if isinstance(a, DNDarray)), None)
    device = next((a.device for a in arrays if isinstance(a, DNDarray)), None)
    outs = jnp.meshgrid(*jargs, indexing=indexing)
    results = []
    ndim = len(jargs)
    for i, out in enumerate(outs):
        # dim that input i varies along in the output
        if indexing == "xy" and ndim >= 2:
            dim_of_input = {0: 1, 1: 0}.get(i, i)
        else:
            dim_of_input = i
        out_split = dim_of_input if splits[i] is not None else None
        results.append(_finalize(out, out_split, device, comm))
    return results


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Ones (reference: factories.py:1285)."""
    return __factory(shape, dtype, split, "ones", device, comm)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, ones, device, comm)


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Zeros (reference: factories.py:1382)."""
    return __factory(shape, dtype, split, "zeros", device, comm)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, zeros, device, comm)


def from_partitioned(x, comm=None) -> DNDarray:
    """Construct from an object exposing ``__partitioned__`` (reference:
    factories.py:796)."""
    parts = x.__partitioned__
    return from_partition_dict(parts, comm=comm)


def from_partition_dict(parted: dict, comm=None) -> DNDarray:
    """Construct from a GAI partition dict (reference: factories.py:841)."""
    shape = tuple(parted["shape"])
    tiling = tuple(parted["partition_tiling"])
    split_dims = [i for i, t in enumerate(tiling) if t > 1]
    split = split_dims[0] if split_dims else None
    get = parted["get"]
    chunks = []
    keys = sorted(parted["partitions"].keys())
    for key in keys:
        p = parted["partitions"][key]
        data = p["data"] if p.get("data") is not None else get(
            tuple(slice(s, s + l) for s, l in zip(p["start"], p["shape"]))
        )
        chunks.append(np.asarray(data))
    if split is None:
        global_arr = chunks[0]
    else:
        global_arr = np.concatenate(chunks, axis=split)
    return array(global_arr, split=split, comm=comm)
