"""Relational operations (reference: heat/core/relational.py, 420 LoC)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater", "greater_equal", "gt", "le", "less", "less_equal", "lt", "ne", "not_equal"]


def eq(t1, t2) -> DNDarray:
    """Elementwise ==."""
    return _operations._binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """True iff shapes and all elements match (reference: global Allreduce of
    the local verdicts; here one jnp.all over the sharded comparison)."""
    if isinstance(t1, DNDarray) and isinstance(t2, DNDarray):
        if tuple(t1.shape) != tuple(t2.shape):
            return False
        return bool(jnp.all(t1.larray == t2.larray))
    a = t1.larray if isinstance(t1, DNDarray) else t1
    b = t2.larray if isinstance(t2, DNDarray) else t2
    try:
        return bool(jnp.all(jnp.equal(a, b)))
    except (ValueError, TypeError):
        return False


def ge(t1, t2) -> DNDarray:
    return _operations._binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    return _operations._binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    return _operations._binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    return _operations._binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    return _operations._binary_op(jnp.not_equal, t1, t2)


not_equal = ne


def _bind_operators():
    DNDarray.__eq__ = lambda self, other: eq(self, other)
    DNDarray.__ne__ = lambda self, other: ne(self, other)
    DNDarray.__lt__ = lambda self, other: lt(self, other)
    DNDarray.__le__ = lambda self, other: le(self, other)
    DNDarray.__gt__ = lambda self, other: gt(self, other)
    DNDarray.__ge__ = lambda self, other: ge(self, other)


_bind_operators()
