"""Relational operations (reference: heat/core/relational.py, 420 LoC)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater", "greater_equal", "gt", "le", "less", "less_equal", "lt", "ne", "not_equal"]


def eq(x, y) -> DNDarray:
    """Elementwise ==."""
    return _operations._binary_op(jnp.equal, x, y)


def equal(x, y) -> bool:
    """True iff shapes and all elements match (reference: global Allreduce of
    the local verdicts; here one jnp.all over the sharded comparison)."""
    if isinstance(x, DNDarray) and isinstance(y, DNDarray):
        if tuple(x.shape) != tuple(y.shape):
            return False
        return bool(jnp.all(x.larray == y.larray))  # ht: HT002 ok — equal() returns a Python bool by NumPy-parity contract
    a = x.larray if isinstance(x, DNDarray) else x
    b = y.larray if isinstance(y, DNDarray) else y
    try:
        return bool(jnp.all(jnp.equal(a, b)))  # ht: HT002 ok — equal() returns a Python bool by NumPy-parity contract
    except (ValueError, TypeError):
        return False


def ge(x, y) -> DNDarray:
    return _operations._binary_op(jnp.greater_equal, x, y)


greater_equal = ge


def gt(x, y) -> DNDarray:
    return _operations._binary_op(jnp.greater, x, y)


greater = gt


def le(x, y) -> DNDarray:
    return _operations._binary_op(jnp.less_equal, x, y)


less_equal = le


def lt(x, y) -> DNDarray:
    return _operations._binary_op(jnp.less, x, y)


less = lt


def ne(x, y) -> DNDarray:
    return _operations._binary_op(jnp.not_equal, x, y)


not_equal = ne


def _bind_operators():
    DNDarray.__eq__ = lambda self, other: eq(self, other)
    DNDarray.__ne__ = lambda self, other: ne(self, other)
    DNDarray.__lt__ = lambda self, other: lt(self, other)
    DNDarray.__le__ = lambda self, other: le(self, other)
    DNDarray.__gt__ = lambda self, other: gt(self, other)
    DNDarray.__ge__ = lambda self, other: ge(self, other)


_bind_operators()

# fusion op table (see arithmetics.py): comparisons are elementwise nodes —
# a relational tail on a fused chain stays in the same executable, and the
# Python-control-flow __bool__ on the result is the materialization boundary
from . import fusion as _fusion  # noqa: E402

for _fn, _name in [
    (jnp.equal, "eq"), (jnp.not_equal, "ne"), (jnp.less, "lt"),
    (jnp.less_equal, "le"), (jnp.greater, "gt"), (jnp.greater_equal, "ge"),
]:
    _fusion.register_op(_fn, _name, kind="comparison")

