"""Weight quantization for the inference path (ROADMAP item 5).

The sharded GEMM is the universal hot path — every estimator,
``nn.functional.linear``, and the MoE FFN route through it — and the ring
matmul's fused :class:`~heat_tpu.parallel.overlap.Epilogue` was built one
step away from low-precision weights: per-channel scales are exactly
"a runtime extra sliced per out-split block", and the ring already
accumulates half-precision inputs in f32.  This module supplies that step:

* :func:`quantize_weights` → :class:`QuantizedDNDarray`: an int8 (or
  fp8 ``e4m3``) buffer with absmax-per-output-channel f32 scales stored
  beside it, both ledgered in memtrack so the residency win is
  attributed in ``live_buffers()`` / ``census()`` / ``bytes_by_dtype``.
  ``donate=True`` consumes the master through a ``donate_argnums``
  dispatch and poisons it for the use-after-donate sanitizer (on CPU the
  donation is a no-op, which is exactly why the poison matters — see
  ``analysis/sanitize.py``).

* :func:`matmul_quantized` / :func:`linear`: the quantized GEMM behind
  ``nn.functional.linear`` and ``linalg.basics.matmul``.  Dispatch rides
  the tuning plane as a ``("bf16", "int8")`` arm pair per (site,
  geometry, device kind) — ``core/autotune.py``'s :data:`~heat_tpu.core
  .autotune.QUANT_ARMS`:

  - **bf16** — dequantize, then the ordinary (itself ring-vs-GSPMD
    tuned) matmul.  This is the *reference* arm: explore calls return
    its result bitwise, and ``HEAT_TPU_AUTOTUNE=off`` restores it
    bit-for-bit with zero table decisions.
  - **int8** — the low-precision buffer rides the GEMM (the ring
    program's per-block ``astype`` is the only upcast; HBM and the ICI
    wire carry 1-byte elements), accumulation stays f32, and the
    per-channel scale + output cast fold into the ring epilogue as
    runtime extras — new checkpoints never retrace.

  Safe decline: traced operands (a grad/training path), unsupported
  layouts, and a failing int8 arm all fall back to bf16.  Winners
  persist through ``HEAT_TPU_AUTOTUNE_CACHE`` like every other arm.

* :func:`quantize_tensor` / :func:`quantize_params`: the raw-array tier
  for the MoE FFN (``parallel/expert.py``) — :class:`QuantizedTensor` is
  a registered pytree so quantized expert weights pass through
  ``shard_map`` / jit boundaries unchanged.

Exactness at shard boundaries is inherited, not re-proven: the ring
masks both operands' k-pads to exact zeros and re-zeros out-split pad
rows after the epilogue, so a mesh-4 quantized product equals the
mesh-1 one to accumulation-order tolerance (pinned by the law tests).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from . import autotune, memtrack, telemetry, types, wire as _wire
from .dndarray import DNDarray, _ensure_split
from ..analysis import sanitize

__all__ = [
    "QuantizedDNDarray",
    "QuantizedTensor",
    "dequantize_tensor",
    "linear",
    "matmul_quantized",
    "quantize_params",
    "quantize_tensor",
    "quantize_weights",
    "stats",
    "tuned_arm",
]

# absmax-per-channel maps onto the quantized grid's largest magnitude.
# The grid math lives in core/wire.py now (round 17 made it the shared
# tile-quant helper of the quantized-collective wire formats); these
# aliases keep this module's surface stable.
_QMAX = _wire.QMAX
_qdtype = _wire.qdtype


_STATS = telemetry.register_group(
    "quantize",
    {
        "quantized": 0,       # quantize_weights / quantize_tensor calls
        "donated": 0,         # masters consumed via donate=True
        "dequantized": 0,     # full-weight dequants (the bf16 arm's cost)
        "matmuls": 0,         # matmul_quantized entries
        "by_arm": {"bf16": 0, "int8": 0},
        "declines": 0,        # safe declines straight to bf16 (tracer, off)
        "int8_fallbacks": 0,  # int8 arm failed at run time -> bf16 rescue
    },
)


def stats() -> dict:
    """Snapshot of the ``quantize`` counter group (Prometheus:
    ``heat_tpu_quantize_*``)."""
    return telemetry.snapshot_group("quantize")


# ------------------------------------------------------------ raw-array tier


@functools.partial(jax.jit, static_argnames=("qdt", "axes"))
def _quantize_arr(w, *, qdt, axes):
    return _quantize_body(w, qdt, axes)


@functools.partial(
    jax.jit, static_argnames=("qdt", "axes"), donate_argnums=(0,)
)
def _quantize_arr_donating(w, *, qdt, axes):
    return _quantize_body(w, qdt, axes)


def _quantize_body(w, qdt, axes):
    """absmax-per-channel quantization: reduce |w| over every non-kept
    axis, snap to the grid.  ``axes`` is the tuple of KEPT (channel)
    axes — ``(1,)`` for a 2-D weight's columns, ``(0, 2)`` for
    per-(expert, channel) scales on a 3-D MoE weight.  Scales stay f32;
    all-zero channels get scale 1 so the dequant is exact zeros, never
    0/0.  One grid, one implementation: this is the same
    ``wire.absmax_encode`` the quantized collectives ship tiles through,
    so a weight quantized here and a tile quantized on the wire agree
    bit-for-bit on the same values."""
    mode = "int8" if qdt == jnp.dtype(jnp.int8) else "fp8"
    return _wire.absmax_encode(w, mode, axes)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QuantizedTensor:
    """Raw-array quantized weight: ``q`` (int8/fp8), f32 ``scale`` with
    one entry per channel over the kept ``axes``, and the master's dtype
    for the round trip.  A registered pytree — passes through jit /
    shard_map boundaries, so the MoE FFN's expert weights can be
    quantized once and served."""

    q: Any
    scale: Any
    axes: Tuple[int, ...]
    orig_dtype: str

    @property
    def shape(self) -> tuple:
        return tuple(self.q.shape)

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes) + int(self.scale.nbytes)

    def tree_flatten(self):
        return (self.q, self.scale), (self.axes, self.orig_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    def scale_broadcast(self):
        """The scale shaped to broadcast against ``q``."""
        reduce_axes = tuple(
            d for d in range(self.q.ndim) if d not in self.axes
        )
        return jnp.expand_dims(self.scale, reduce_axes)


def _norm_axes(axis, ndim: int) -> Tuple[int, ...]:
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return tuple(sorted(a % ndim for a in axes))


def quantize_tensor(w, dtype: str = "int8", *, axis=-1) -> QuantizedTensor:
    """Quantize one raw jax array with absmax scales per channel along
    ``axis`` — an int, or a tuple of kept axes (the MoE expert weights
    ``(E, d, h)``/``(E, h, d)`` use ``axis=(0, 2)`` for per-(expert,
    out-channel) scales)."""
    qdt = _qdtype(dtype)
    w = jnp.asarray(w)
    axes = _norm_axes(axis, w.ndim)
    q, scale = _quantize_arr(w, qdt=qdt, axes=axes)
    if not _is_traced(q):  # call-time quantize inside a jit trace
        memtrack.register_buffer(q, tag="leaf")
        memtrack.register_buffer(scale, tag="leaf")
    _STATS["quantized"] += 1
    return QuantizedTensor(q, scale, axes, str(w.dtype))


def dequantize_tensor(qt: QuantizedTensor):
    """Round-trip a :class:`QuantizedTensor` back to its master dtype."""
    _STATS["dequantized"] += 1
    out = qt.q.astype(jnp.float32) * qt.scale_broadcast()
    return out.astype(jnp.dtype(qt.orig_dtype))


def quantize_params(
    params,
    dtype: str = "int8",
    *,
    targets: Tuple[str, ...] = ("w_in", "w_out"),
    axis=(0, 2),
):
    """Walk a (flax-style) nested param dict and replace every leaf whose
    key is in ``targets`` with a :class:`QuantizedTensor`.  Returns a new
    tree; untouched leaves are shared, not copied.  The quantized tree
    feeds :func:`~heat_tpu.parallel.expert.moe_ffn` directly — flax's
    ``apply`` param-shape check predates pytree-valued params, so serve
    through the functional entry, not ``Module.apply``."""
    if not isinstance(params, dict):
        return params
    out = {}
    for key, val in params.items():
        if isinstance(val, dict):
            out[key] = quantize_params(
                val, dtype, targets=targets, axis=axis
            )
        elif key in targets and hasattr(val, "ndim"):
            out[key] = quantize_tensor(val, dtype, axis=axis)
        else:
            out[key] = val
    return out


# ----------------------------------------------------------- DNDarray tier


class QuantizedDNDarray:
    """Per-output-channel-scaled low-precision weight with DNDarray-style
    metadata (gshape / split / device / comm), deliberately NOT a
    :class:`~heat_tpu.core.dndarray.DNDarray` subclass: the quantized
    buffer must never wander into the generic op surface — only the
    GEMM consumers (``matmul_quantized``, the ring cdist) and
    :meth:`dequantize` understand it."""

    __slots__ = ("q", "scale", "axis", "orig_dtype", "gshape", "split",
                 "device", "comm")

    def __init__(self, q, scale, axis, orig_dtype, gshape, split, device,
                 comm):
        self.q = q                    # logical low-precision buffer
        self.scale = scale            # f32, (gshape[axis],)
        self.axis = int(axis)         # the per-channel axis
        self.orig_dtype = orig_dtype  # heat type of the master
        self.gshape = tuple(gshape)
        self.split = split
        self.device = device
        self.comm = comm

    # -- DNDarray-flavored metadata ------------------------------------
    @property
    def shape(self) -> tuple:
        return self.gshape

    @property
    def ndim(self) -> int:
        return len(self.gshape)

    @property
    def dtype(self):
        """The MASTER's heat type — what consumers compute in/return."""
        return self.orig_dtype

    @property
    def qdtype(self) -> str:
        return str(self.q.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes) + int(self.scale.nbytes)

    def __repr__(self) -> str:
        return (
            f"QuantizedDNDarray(shape={self.gshape}, q={self.qdtype}, "
            f"channel_axis={self.axis}, split={self.split}, "
            f"master={self.orig_dtype.__name__})"
        )

    # -- ops ------------------------------------------------------------
    def dequantize(self) -> DNDarray:
        """Back to a master-dtype DNDarray (the bf16 arm's operand)."""
        _STATS["dequantized"] += 1
        reduce_axes = tuple(
            d for d in range(self.ndim) if d != self.axis
        )
        sb = jnp.expand_dims(self.scale, reduce_axes)
        w = (self.q.astype(jnp.float32) * sb).astype(
            self.orig_dtype.jax_type()
        )
        out = DNDarray(
            w, self.gshape, self.orig_dtype, self.split, self.device,
            self.comm,
        )
        return _ensure_split(out, self.split)

    def transpose(self) -> "QuantizedDNDarray":
        """2-D transpose: the channel axis and split follow the permute
        (the ``F.linear`` ``(out, in)`` → ``(in, out)`` hop)."""
        if self.ndim != 2:
            raise ValueError("QuantizedDNDarray.transpose is 2-D only")
        split = None if self.split is None else 1 - self.split
        return QuantizedDNDarray(
            self.q.T, self.scale, 1 - self.axis, self.orig_dtype,
            (self.gshape[1], self.gshape[0]), split, self.device, self.comm,
        )

    @property
    def T(self) -> "QuantizedDNDarray":
        return self.transpose()


def quantize_weights(
    w: DNDarray,
    dtype: str = "int8",
    *,
    axis: int = 0,
    donate: bool = False,
) -> QuantizedDNDarray:
    """Quantize a weight DNDarray to int8/fp8 with absmax scales per
    ``axis`` channel (default 0 — torch's ``(out_features, in_features)``
    linear convention).  The quantized buffer and its scales are
    memtrack-ledgered, so the residency win shows up in
    ``live_buffers()`` / ``census()["bytes_by_dtype"]``.

    ``donate=True`` hands the master to XLA via ``donate_argnums`` and
    poisons it for the use-after-donate sanitizer: reading ``w`` (or its
    buffer) afterwards raises under ``HEAT_TPU_SANITIZE=1`` and is
    flagged by lint HT005 — on TPU that read is silent corruption."""
    from . import sanitation

    sanitation.sanitize_in(w)
    qdt = _qdtype(dtype)
    axis = axis % w.ndim
    master = w.larray
    phys = w.parray
    fn = _quantize_arr_donating if donate else _quantize_arr
    q, scale = fn(master, qdt=qdt, axes=(axis,))
    memtrack.register_buffer(q, tag="leaf")
    memtrack.register_buffer(scale, tag="leaf")
    _STATS["quantized"] += 1
    if donate:
        _STATS["donated"] += 1
        site = "quantize.quantize_weights(donate=True)"
        memtrack.tag_buffer(master, "donated")
        sanitize.poison(master, donated_site=site)
        if phys is not master:
            memtrack.tag_buffer(phys, "donated")
            sanitize.poison(phys, donated_site=site)
    telemetry.record_event(
        "quantize",
        dtype=str(qdt),
        shape=tuple(w.shape),
        axis=axis,
        donate=bool(donate),
        master_nbytes=int(master.nbytes),  # ht: HT002 ok — .nbytes is shape metadata, no device readback
        quant_nbytes=int(q.nbytes) + int(scale.nbytes),  # ht: HT002 ok — .nbytes is shape metadata, no device readback
    )
    return QuantizedDNDarray(
        q, scale, axis, w.dtype, tuple(w.shape), w.split, w.device, w.comm,
    )


# ------------------------------------------------------------ arm dispatch


def _is_traced(value) -> bool:
    tracer = getattr(jax.core, "Tracer", ())
    return isinstance(value, tracer)


def tuned_arm(
    site: str,
    geometry: tuple,
    bf16_fn: Callable[[], Any],
    int8_fn: Callable[[], Any],
    *,
    desc: str = "",
    arm: Optional[str] = None,
):
    """THE quantized-arm dispatch: per (site, geometry, device kind),
    explore runs BOTH arms under measurement and returns the bf16
    (reference) result bitwise; a resolved winner runs alone; the tuning
    plane off means bf16, bit-for-bit, zero table decisions.  ``arm``
    forces one arm (law tests / benchmarks).  An int8 arm that raises
    falls back to bf16 — quantization must never turn a working call
    into an error."""
    if arm is not None:
        if arm not in autotune.QUANT_ARMS:
            raise ValueError(f"arm must be one of {autotune.QUANT_ARMS}")
        _STATS["by_arm"][arm] += 1
        return int8_fn() if arm == "int8" else bf16_fn()
    if not autotune.enabled():
        _STATS["declines"] += 1
        _STATS["by_arm"]["bf16"] += 1
        return bf16_fn()
    key = autotune.quant_key(site, *geometry)
    decision = autotune.decide(
        key, "bf16", desc=desc or f"{site} {geometry}",
        arms=autotune.QUANT_ARMS,
    )
    if decision.explore:
        out, bf16_s = autotune.timed(bf16_fn)
        autotune.observe(key, "bf16", bf16_s)
        try:
            _, int8_s = autotune.timed(int8_fn)
        except Exception:
            # an arm that cannot run loses by forfeit (bounded explore)
            int8_s = float("inf")
        autotune.observe(key, "int8", int8_s)
        _STATS["by_arm"]["bf16"] += 1
        return out
    if decision.arm == "int8":
        try:
            result = int8_fn()
        except Exception:
            _STATS["int8_fallbacks"] += 1
            telemetry.record_event(
                "fallback", site="quantize." + site, reason="int8-arm-error",
            )
            _STATS["by_arm"]["bf16"] += 1
            return bf16_fn()
        _STATS["by_arm"]["int8"] += 1
        return result
    _STATS["by_arm"]["bf16"] += 1
    return bf16_fn()


# ------------------------------------------------------------- matmul tier


@functools.partial(jax.jit, static_argnames=("comp", "out_dt"))
def _gspmd_quant_mm(x, q, scale, *, comp, out_dt):
    """The int8 arm's GSPMD form (the ring's decline target): one einsum
    over the low-precision buffer with f32+ accumulation, scale and cast
    fused in the same program."""
    out = jnp.matmul(x.astype(comp), q.astype(comp))
    return (out * scale).astype(out_dt)


def matmul_quantized(
    x: DNDarray,
    qw: QuantizedDNDarray,
    out_split="auto",
    *,
    arm: Optional[str] = None,
) -> DNDarray:
    """``x @ qw`` for a 2-D quantized right operand whose channel axis is
    the output (column) axis.  Arm dispatch per the module docstring;
    the int8 arm goes ring-first (`overlap.matmul_raw` with the scale +
    cast folded into the :class:`~heat_tpu.parallel.overlap.Epilogue`)
    and declines to the fused GSPMD einsum."""
    from ..parallel import overlap as _overlap

    if qw.ndim != 2 or x.ndim != 2:
        raise ValueError(
            f"matmul_quantized is 2-D only, got {x.shape} @ {qw.shape}"
        )
    if qw.axis != 1:
        raise ValueError(
            "matmul_quantized needs the channel axis on the output "
            "(column) axis of the right operand — transpose the "
            f"QuantizedDNDarray first (channel axis is {qw.axis})"
        )
    m, k = x.shape
    k2, n = qw.shape
    if k != k2:
        raise ValueError(
            f"matmul_quantized: inner dimensions do not match: "
            f"{x.shape} @ {qw.shape}"
        )
    _STATS["matmuls"] += 1
    if out_split == "auto":
        out_split = 0 if x.split == 0 else (1 if qw.split == 1 else None)
    out_ht = types.promote_types(x.dtype, qw.orig_dtype)
    out_dt = jnp.dtype(out_ht.jax_type())
    comp = jnp.promote_types(x.larray.dtype, jnp.float32)

    def _bf16() -> DNDarray:
        from .linalg import basics

        return basics.matmul(x, qw.dequantize())

    def _int8() -> DNDarray:
        ep = _overlap.Epilogue(scale=qw.scale, dtype=out_dt)
        out = _overlap.matmul_raw(
            x.comm, x.parray, qw.q, (m, k), (k, n), x.split, qw.split,
            out_split, comp_dtype=comp, epilogue=ep,
        )
        if out is None:
            out = _gspmd_quant_mm(
                x.larray, qw.q, qw.scale, comp=comp, out_dt=out_dt,
            )
        wrapped = DNDarray(
            out, (m, n), out_ht, out_split, x.device, x.comm,
        )
        return _ensure_split(wrapped, out_split)

    if arm is None and (_is_traced(x.larray) or _is_traced(qw.q)):
        # a grad/training trace must not explore, time, or mutate tables
        _STATS["declines"] += 1
        return _bf16()
    geometry = (m, k, n, x.comm.size, str(comp), x.split, qw.split,
                out_split, qw.qdtype)
    return tuned_arm(
        "linear", geometry, _bf16, _int8,
        desc=f"linear {m}x{k}x{n} {qw.qdtype} S={x.comm.size}",
        arm=arm,
    )


def linear(x: DNDarray, qw: QuantizedDNDarray, bias=None) -> DNDarray:
    """Quantized ``F.linear``: ``x @ qw.T + bias`` with ``qw`` in torch's
    ``(out_features, in_features)`` layout (channel axis 0)."""
    if qw.ndim != 2 or qw.axis != 0:
        raise ValueError(
            "linear expects a (out_features, in_features) quantized "
            f"weight with channel axis 0, got shape {qw.shape} axis "
            f"{qw.axis}"
        )
    out = matmul_quantized(x, qw.transpose())
    if bias is not None:
        out = out + bias
    return out
