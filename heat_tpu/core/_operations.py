"""Generic operation machinery (reference: heat/core/_operations.py).

The reference's four workhorses map as follows:

* ``__binary_op`` (:22-203) — type-promote, broadcast, align distributions,
  apply. Here alignment is *declarative*: we pick the result split with the
  reference's dominance rule and let XLA re-shard the other operand when the
  computation runs (the hand-written lshape-map surgery at :149-174 has no
  analog).
* ``__reduce_op`` (:381-507) — local partial reduce + MPI Allreduce becomes a
  single jnp reduction; XLA emits the cross-device all-reduce when the split
  axis is reduced. Custom MPI ops (argmax twin-payload :476-482) are ordinary
  jnp reductions.
* ``__cum_op`` (:206-304) — local cumop + Exscan becomes jnp.cumsum/cumprod;
  XLA partitions the scan.
* ``__local_op`` (:307-378) — elementwise with float-cast policy; identical
  role here.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import fusion, sanitation, types
from .dndarray import DNDarray, _ensure_split
from .stride_tricks import broadcast_shape, sanitize_axes_for_reduction, sanitize_axis

__all__ = ["_binary_op", "_local_op", "_reduce_op", "_cum_op"]


def _as_operand(x, ref_dtype=None):
    """Lift one binary-op operand to ``(value, split, is_scalar)``.

    DNDarrays pass through with their split; array-likes become jnp arrays
    (replicated, ``split=None``); python scalars promote against
    ``ref_dtype`` with the reference's scalar-aware ``result_type`` rule
    (types.py:868 — a scalar must not widen the array dtype; jax's
    weak-type rules under x64 would take int32 + 1.5 to f64) and report
    ``is_scalar=True``."""
    if isinstance(x, DNDarray):
        return x, x.split, False
    if np.isscalar(x):
        if ref_dtype is not None:
            return jnp.asarray(x, types.result_type(ref_dtype, x).jax_type()), None, True
        return jnp.asarray(x), None, True
    return jnp.asarray(x), None, False


def _result_split(s1: Optional[int], s2: Optional[int], nd_out: int, nd1: int, nd2: int):
    """Dominance rule for the output split (reference: _operations.py:90-148):
    a distributed operand wins over a replicated one; when both are split the
    first operand's split wins (the reference redistributes the second). Splits
    are mapped through broadcasting's right-alignment."""

    def mapped(split, nd_in):
        if split is None:
            return None
        return split + (nd_out - nd_in)

    m1, m2 = mapped(s1, nd1), mapped(s2, nd2)
    if m1 is not None:
        return m1
    return m2


def _lazy_operand(x, comm):
    """DAG node for one operand of a fused op: lazy handles contribute their
    pending expression, concrete DNDarrays their pinned physical buffer,
    plain jax values a replicated leaf.  Mixed meshes cannot share one jitted
    program — decline and let the eager path handle (or reject) them."""
    if isinstance(x, DNDarray):
        if x.comm is not comm and x.comm.mesh != comm.mesh:
            raise fusion.Unfusable("operands live on different meshes")
        return fusion.leaf_from(x)
    return fusion.leaf(x)


def _lazy_binary(operation, o1, o2, where, fn_kwargs, out_shape, split, device, comm):
    n1 = _lazy_operand(o1, comm)
    n2 = _lazy_operand(o2, comm)
    res = fusion.node(operation, (n1, n2), **fn_kwargs)
    if tuple(res.aval.shape) != tuple(out_shape):
        # an operation with non-broadcast shape semantics: the eager path's
        # actual-result-shape bookkeeping is authoritative
        raise fusion.Unfusable("result shape disagrees with broadcast shape")
    if where is not None:
        wn = (
            _lazy_operand(where, comm)
            if isinstance(where, DNDarray)
            else fusion.leaf(jnp.asarray(where))
        )
        base = fusion.node(
            jnp.zeros, (), shape=tuple(out_shape), dtype=jnp.dtype(res.aval.dtype)
        )
        res = fusion.node(jnp.where, (wn, res, base))
    return fusion.defer(
        res, out_shape, types.canonical_heat_type(res.aval.dtype),
        split, device, comm,
    )


def _binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic distributed binary operation (reference: _operations.py:22).

    With the fusion engine on (and no ``out=``), the op joins the lazy DAG
    instead of dispatching: one leaf per operand, the ``where=`` select and
    its zeros base as in-graph nodes, metadata predicted via eval_shape."""
    fn_kwargs = fn_kwargs or {}

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(f"at least one operand must be a DNDarray, got {type(t1)}, {type(t2)}")

    ref = t1 if isinstance(t1, DNDarray) else t2
    comm, device = ref.comm, ref.device

    o1, s1, _ = _as_operand(t1, None if isinstance(t1, DNDarray) else ref.dtype)
    o2, s2, _ = _as_operand(t2, None if isinstance(t2, DNDarray) else ref.dtype)
    sh1 = o1.shape if isinstance(o1, DNDarray) else np.shape(o1)
    sh2 = o2.shape if isinstance(o2, DNDarray) else np.shape(o2)
    out_shape = broadcast_shape(sh1, sh2)
    split = _result_split(s1, s2, len(out_shape), len(sh1), len(sh2))
    # a broadcast dimension of size 1 at the split cannot stay split
    if split is not None and out_shape and out_shape[split] <= 1:
        split = None

    if fusion.enabled() and out is None:
        try:
            return _lazy_binary(
                operation, o1, o2, where, fn_kwargs, out_shape, split, device, comm
            )
        except fusion.Unfusable:
            fusion.count_fallback()

    a = o1.larray if isinstance(o1, DNDarray) else o1
    b = o2.larray if isinstance(o2, DNDarray) else o2
    result = operation(a, b, **fn_kwargs)

    if where is not None:
        wh = where.larray if isinstance(where, DNDarray) else jnp.asarray(where)
        base = out.larray if out is not None else jnp.zeros(out_shape, result.dtype)
        result = jnp.where(wh, result, base)

    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        split, device, comm,
    )
    wrapped = _ensure_split(wrapped, split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), split, device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped


def _local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Elementwise operation with float-cast policy (reference:
    _operations.py:307): integer inputs are promoted to the default float type
    for transcendental ops unless ``no_cast``.  Under fusion the float-cast
    joins the DAG as a cast node — convert + op lower as one program."""
    sanitation.sanitize_in(x)
    if fusion.enabled() and out is None:
        try:
            nx = _lazy_operand(x, x.comm)
            if not no_cast and not jnp.issubdtype(nx.aval.dtype, jnp.inexact):
                nx = fusion.cast_node(nx, jnp.float32)
            res = fusion.node(operation, (nx,), **kwargs)
            return fusion.defer(
                res, res.aval.shape, types.canonical_heat_type(res.aval.dtype),
                x.split if len(res.aval.shape) == x.ndim else None,
                x.device, x.comm,
            )
        except fusion.Unfusable:
            fusion.count_fallback()
    arr = x.larray
    if not no_cast and not jnp.issubdtype(arr.dtype, jnp.inexact):
        arr = arr.astype(jnp.float32)
    result = operation(arr, **kwargs)
    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        x.split if result.ndim == x.ndim else None, x.device, x.comm,
    )
    wrapped = _ensure_split(wrapped, wrapped.split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), wrapped.split, x.device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped


def _reduce_split(split, axes, keepdims: bool, out_ndim: int):
    """Result split of a reduction (reference: reduced-away split →
    replicated; retained dims keep the index, dropped leading axes shift
    it down)."""
    if split is not None:
        if split in axes:
            split = None
        elif keepdims:
            pass  # dims retained, split index unchanged
        else:
            split -= sum(1 for ax in axes if ax < split)
    if out_ndim == 0:
        split = None
    return split


def _lazy_reduce(operation, x, axes, call_axis, keepdims, dtype, kwargs):
    nx = _lazy_operand(x, x.comm)
    if dtype is not None:
        nx = fusion.cast_node(nx, types.canonical_heat_type(dtype).jax_type())
    # 16-bit float accumulation contract (see the eager path below): probe
    # the op for a dtype kwarg via shape inference and ride the f32
    # accumulator + cast-back inside the same fused program
    half = (
        jnp.issubdtype(nx.aval.dtype, jnp.floating)
        and jnp.dtype(nx.aval.dtype).itemsize < 4
    )
    res = None
    if half and dtype is None:
        try:
            res = fusion.node(
                operation, (nx,),
                axis=call_axis, keepdims=keepdims, dtype=jnp.float32, **kwargs
            )
        except fusion.Unfusable:
            res = None
        if res is not None and jnp.issubdtype(res.aval.dtype, jnp.floating):
            res = fusion.cast_node(res, nx.aval.dtype)
    if res is None:
        res = fusion.node(operation, (nx,), axis=call_axis, keepdims=keepdims, **kwargs)
    split = _reduce_split(x.split, axes, keepdims, len(res.aval.shape))
    return fusion.defer(
        res, res.aval.shape, types.canonical_heat_type(res.aval.dtype),
        split, x.device, x.comm,
    )


def _reduce_op(
    operation: Callable,
    x: DNDarray,
    axis=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    dtype=None,
    initial=None,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference: _operations.py:381). The reference's
    local-reduce + Allreduce + neutral-fill dance is a single jnp call; XLA
    inserts the cross-device reduce when the split axis participates.  Under
    fusion a trailing reduction extends its producer chain's DAG, so e.g.
    ``((x - mu) / sd).sum(axis=1)`` lowers as one executable."""
    sanitation.sanitize_in(x)
    axes, was_none = sanitize_axes_for_reduction(x.shape, axis)
    call_axis = None if was_none else (axes if len(axes) > 1 else axes[0])
    if fusion.enabled() and out is None:
        try:
            return _lazy_reduce(operation, x, axes, call_axis, keepdims, dtype, kwargs)
        except fusion.Unfusable:
            fusion.count_fallback()
    arr = x.larray
    if dtype is not None:
        arr = arr.astype(types.canonical_heat_type(dtype).jax_type())
    # 16-bit float inputs accumulate in f32 and cast back (NumPy's fp16
    # contract): a bf16 accumulator saturates after ~256 terms — the mean
    # of 1e9 standard normals came out at 1e-2 instead of ~3e-5.  The f32
    # accumulator rides the op's own dtype kwarg so convert+reduce stay ONE
    # XLA program even eagerly; an explicit astype would dispatch separately
    # and materialize an array-sized f32 copy (25.6 GB at bf16[1e8, 64]).
    # Ops without a dtype kwarg (min/max/argmax/all) are exact in any float
    # dtype and take the plain path.
    half = jnp.issubdtype(arr.dtype, jnp.floating) and jnp.dtype(arr.dtype).itemsize < 4
    result = None
    if half and dtype is None:
        try:
            result = operation(
                arr, axis=call_axis, keepdims=keepdims, dtype=jnp.float32, **kwargs
            )
        except TypeError:
            result = None
        if result is not None and jnp.issubdtype(result.dtype, jnp.floating):
            result = result.astype(arr.dtype)
    if result is None:
        result = operation(arr, axis=call_axis, keepdims=keepdims, **kwargs)

    split = _reduce_split(x.split, axes, keepdims, np.ndim(result))

    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        split, x.device, x.comm,
    )
    wrapped = _ensure_split(wrapped, split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), split, x.device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped


def _cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative operation (reference: _operations.py:206). The
    local-cumop + Exscan + combine pipeline is one partitioned jnp scan."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative ops require an axis")
    if fusion.enabled() and out is None:
        try:
            nx = _lazy_operand(x, x.comm)
            if dtype is not None:
                nx = fusion.cast_node(nx, types.canonical_heat_type(dtype).jax_type())
            res = fusion.node(operation, (nx,), axis=axis)
            return fusion.defer(
                res, res.aval.shape, types.canonical_heat_type(res.aval.dtype),
                x.split, x.device, x.comm,
            )
        except fusion.Unfusable:
            fusion.count_fallback()
    arr = x.larray
    if dtype is not None:
        arr = arr.astype(types.canonical_heat_type(dtype).jax_type())
    result = operation(arr, axis=axis)
    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        x.split, x.device, x.comm,
    )
    wrapped = _ensure_split(wrapped, x.split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), x.split, x.device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped
