"""Generic operation machinery (reference: heat/core/_operations.py).

The reference's four workhorses map as follows:

* ``__binary_op`` (:22-203) — type-promote, broadcast, align distributions,
  apply. Here alignment is *declarative*: we pick the result split with the
  reference's dominance rule and let XLA re-shard the other operand when the
  computation runs (the hand-written lshape-map surgery at :149-174 has no
  analog).
* ``__reduce_op`` (:381-507) — local partial reduce + MPI Allreduce becomes a
  single jnp reduction; XLA emits the cross-device all-reduce when the split
  axis is reduced. Custom MPI ops (argmax twin-payload :476-482) are ordinary
  jnp reductions.
* ``__cum_op`` (:206-304) — local cumop + Exscan becomes jnp.cumsum/cumprod;
  XLA partitions the scan.
* ``__local_op`` (:307-378) — elementwise with float-cast policy; identical
  role here.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import sanitation, types
from .dndarray import DNDarray, _ensure_split
from .stride_tricks import broadcast_shape, sanitize_axes_for_reduction, sanitize_axis

__all__ = ["_binary_op", "_local_op", "_reduce_op", "_cum_op"]


def _as_operand(x, comm=None, device=None):
    """Lift scalars / array-likes to (jax_value, split, is_scalar)."""
    if isinstance(x, DNDarray):
        return x, x.split
    return x, None


def _result_split(s1: Optional[int], s2: Optional[int], nd_out: int, nd1: int, nd2: int):
    """Dominance rule for the output split (reference: _operations.py:90-148):
    a distributed operand wins over a replicated one; when both are split the
    first operand's split wins (the reference redistributes the second). Splits
    are mapped through broadcasting's right-alignment."""

    def mapped(split, nd_in):
        if split is None:
            return None
        return split + (nd_out - nd_in)

    m1, m2 = mapped(s1, nd1), mapped(s2, nd2)
    if m1 is not None:
        return m1
    return m2


def _binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic distributed binary operation (reference: _operations.py:22)."""
    fn_kwargs = fn_kwargs or {}

    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(f"at least one operand must be a DNDarray, got {type(t1)}, {type(t2)}")

    ref = t1 if isinstance(t1, DNDarray) else t2
    comm, device = ref.comm, ref.device

    if isinstance(t1, DNDarray) and isinstance(t2, DNDarray):
        a, b = t1.larray, t2.larray
        s1, s2, nd1, nd2 = t1.split, t2.split, t1.ndim, t2.ndim
        out_shape = broadcast_shape(t1.shape, t2.shape)
    elif isinstance(t1, DNDarray):
        a = t1.larray
        b = t2.larray if isinstance(t2, DNDarray) else t2
        if isinstance(b, (list, tuple, np.ndarray)):
            b = jnp.asarray(b)
        if np.isscalar(b):
            # scalar-aware promotion (reference: result_type, types.py:868
            # — a python scalar must not widen the array dtype): jax's
            # weak-type rules under x64 would take int32 + 1.5 to f64
            b = jnp.asarray(b, types.result_type(t1.dtype, b).jax_type())
        s1, nd1 = t1.split, t1.ndim
        s2, nd2 = None, (np.ndim(b) if not np.isscalar(b) else 0)
        out_shape = broadcast_shape(t1.shape, np.shape(b))
    else:
        b = t2.larray
        a = t1
        if isinstance(a, (list, tuple, np.ndarray)):
            a = jnp.asarray(a)
        if np.isscalar(a):
            a = jnp.asarray(a, types.result_type(t2.dtype, a).jax_type())
        s2, nd2 = t2.split, t2.ndim
        s1, nd1 = None, (np.ndim(a) if not np.isscalar(a) else 0)
        out_shape = broadcast_shape(np.shape(a), t2.shape)

    result = operation(a, b, **fn_kwargs)
    split = _result_split(s1, s2, len(out_shape), nd1, nd2)
    # a broadcast dimension of size 1 at the split cannot stay split
    if split is not None and out_shape and out_shape[split] <= 1:
        split = None

    if where is not None:
        wh = where.larray if isinstance(where, DNDarray) else jnp.asarray(where)
        base = out.larray if out is not None else jnp.zeros(out_shape, result.dtype)
        result = jnp.where(wh, result, base)

    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        split, device, comm,
    )
    wrapped = _ensure_split(wrapped, split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), split, device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped


def _local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Elementwise operation with float-cast policy (reference:
    _operations.py:307): integer inputs are promoted to the default float type
    for transcendental ops unless ``no_cast``."""
    sanitation.sanitize_in(x)
    arr = x.larray
    if not no_cast and not jnp.issubdtype(arr.dtype, jnp.inexact):
        arr = arr.astype(jnp.float32)
    result = operation(arr, **kwargs)
    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        x.split if result.ndim == x.ndim else None, x.device, x.comm,
    )
    wrapped = _ensure_split(wrapped, wrapped.split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), wrapped.split, x.device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped


def _reduce_op(
    operation: Callable,
    x: DNDarray,
    axis=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    dtype=None,
    initial=None,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference: _operations.py:381). The reference's
    local-reduce + Allreduce + neutral-fill dance is a single jnp call; XLA
    inserts the cross-device reduce when the split axis participates."""
    sanitation.sanitize_in(x)
    axes, was_none = sanitize_axes_for_reduction(x.shape, axis)
    arr = x.larray
    if dtype is not None:
        arr = arr.astype(types.canonical_heat_type(dtype).jax_type())
    call_axis = None if was_none else (axes if len(axes) > 1 else axes[0])
    # 16-bit float inputs accumulate in f32 and cast back (NumPy's fp16
    # contract): a bf16 accumulator saturates after ~256 terms — the mean
    # of 1e9 standard normals came out at 1e-2 instead of ~3e-5.  The f32
    # accumulator rides the op's own dtype kwarg so convert+reduce stay ONE
    # XLA program even eagerly; an explicit astype would dispatch separately
    # and materialize an array-sized f32 copy (25.6 GB at bf16[1e8, 64]).
    # Ops without a dtype kwarg (min/max/argmax/all) are exact in any float
    # dtype and take the plain path.
    half = jnp.issubdtype(arr.dtype, jnp.floating) and jnp.dtype(arr.dtype).itemsize < 4
    result = None
    if half and dtype is None:
        try:
            result = operation(
                arr, axis=call_axis, keepdims=keepdims, dtype=jnp.float32, **kwargs
            )
        except TypeError:
            result = None
        if result is not None and jnp.issubdtype(result.dtype, jnp.floating):
            result = result.astype(arr.dtype)
    if result is None:
        result = operation(arr, axis=call_axis, keepdims=keepdims, **kwargs)

    # result split (reference: reduced-away split → replicated)
    split = x.split
    if split is not None:
        if split in axes:
            split = None
        elif keepdims:
            pass  # dims retained, split index unchanged
        else:
            split -= sum(1 for ax in axes if ax < split)
    if np.ndim(result) == 0:
        split = None

    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        split, x.device, x.comm,
    )
    wrapped = _ensure_split(wrapped, split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), split, x.device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped


def _cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative operation (reference: _operations.py:206). The
    local-cumop + Exscan + combine pipeline is one partitioned jnp scan."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative ops require an axis")
    arr = x.larray
    if dtype is not None:
        arr = arr.astype(types.canonical_heat_type(dtype).jax_type())
    result = operation(arr, axis=axis)
    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype),
        x.split, x.device, x.comm,
    )
    wrapped = _ensure_split(wrapped, x.split)
    if out is not None:
        sanitation.sanitize_out(out, tuple(result.shape), x.split, x.device)
        out.larray = wrapped.parray.astype(out.dtype.jax_type())
        return out
    return wrapped
