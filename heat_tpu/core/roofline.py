"""Roofline attribution: measured program time vs device peaks.

The cost ledger (``telemetry.programs()``) predicts FLOPs and mandatory
HBM bytes per compiled program; the measured-timing extension adds wall
clocks from the live call sites (the fusion cache-hit path, the
transport tile loops, the ring matmul).  This module closes the
predicted→achieved loop: a device-peaks table (detected from jax's
``device_kind``, overridable via ``HEAT_TPU_PEAKS``) turns predicted
work + measured seconds into achieved GFLOP/s and GB/s, percent of the
compute and HBM rooflines, and a compute/memory-bound verdict per
program — the attribution the ROADMAP's Pallas-tier item needs to pick
its targets (the memory-bound tail).

Honesty rule: on CPU, or any device the table doesn't know, the peaks
are UNKNOWN — the report still shows measured time and achieved rates,
but the roofline fractions are ``None`` and the verdict is
``"unknown-peak"``, never a percentage of a made-up peak.
``HEAT_TPU_PEAKS`` supplies explicit numbers either as ``k=v`` pairs::

    HEAT_TPU_PEAKS="bf16_tflops=197,hbm_gbps=819"

or as a JSON object with the same keys (``f32_tflops`` defaults to a
quarter of ``bf16_tflops``, the MXU model ``benchmarks/cb/config.py``
uses).

The verdict is STRUCTURAL: with known peaks, a program whose predicted
HBM traffic takes longer at peak bandwidth than its predicted FLOPs take
at peak compute is memory-bound (arithmetic intensity below the machine
balance), independent of how well the measured time does against either
bound — the achieved fractions then say how far from that bound it runs.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

__all__ = ["attribute", "detect_peaks", "render", "report"]

# Public per-chip peak numbers by TPU generation: dense bf16 MXU TFLOP/s
# and HBM GB/s.  f32 rides the MXU at 1/4 rate (the same peak model the
# cb config uses: PEAK_F32_TFLOPS = PEAK_BF16_TFLOPS / 4).  Matched as
# lowercase substrings of jax's device_kind, most specific first.
_KNOWN = (
    ("v6e", 918.0, 1640.0),
    ("v6", 918.0, 1640.0),
    ("v5p", 459.0, 2765.0),
    ("v5e", 197.0, 819.0),
    ("v5 lite", 197.0, 819.0),  # device_kind spells v5e "TPU v5 lite"
    ("v5lite", 197.0, 819.0),
    ("v4", 275.0, 1228.0),
)

# dtypes that run the MXU at full (half-precision) rate
_HALF_DTYPES = frozenset(("bfloat16", "float16"))


def _parse_env(raw: str) -> Optional[Dict[str, float]]:
    raw = raw.strip()
    if not raw:
        return None
    try:
        if raw.startswith("{"):
            kv = json.loads(raw)
        else:
            kv = {}
            for part in raw.replace(";", ",").split(","):
                if not part.strip():
                    continue
                k, _, v = part.partition("=")
                kv[k.strip()] = v
        return {str(k): float(v) for k, v in kv.items()}
    except (ValueError, TypeError):
        return None


def detect_peaks() -> Dict[str, Any]:
    """The active device's peak table: ``{"device", "known",
    "bf16_tflops", "f32_tflops", "hbm_gbps", "source"}``.  ``source`` is
    ``env`` (``HEAT_TPU_PEAKS`` override), ``detected`` (device_kind
    matched the built-in table), or ``unknown`` (honest CPU fallback —
    ``known`` False, all peaks ``None``)."""
    try:
        import jax

        kind = str(jax.devices()[0].device_kind)
    except Exception:
        kind = "unknown"
    env = _parse_env(os.environ.get("HEAT_TPU_PEAKS", ""))
    if env is not None:
        bf16 = env.get("bf16_tflops")
        f32 = env.get("f32_tflops", bf16 / 4.0 if bf16 else None)
        hbm = env.get("hbm_gbps")
        return {
            "device": kind,
            "known": bool(bf16 or f32 or hbm),
            "bf16_tflops": bf16,
            "f32_tflops": f32,
            "hbm_gbps": hbm,
            "source": "env",
        }
    low = kind.lower()
    for sub, bf16, hbm in _KNOWN:
        if sub in low:
            return {
                "device": kind,
                "known": True,
                "bf16_tflops": bf16,
                "f32_tflops": bf16 / 4.0,
                "hbm_gbps": hbm,
                "source": "detected",
            }
    return {
        "device": kind,
        "known": False,
        "bf16_tflops": None,
        "f32_tflops": None,
        "hbm_gbps": None,
        "source": "unknown",
    }


def _flops_peak(peaks: dict, dtype) -> Optional[float]:
    """Peak FLOP/s for a program's compute dtype (f32 when unrecorded —
    the conservative full-precision rate)."""
    name = str(dtype)
    key = "bf16_tflops" if name in _HALF_DTYPES else "f32_tflops"
    got = peaks.get(key)
    return got * 1e12 if got else None


def attribute(entry: dict, peaks: Optional[dict] = None) -> Optional[dict]:
    """One roofline row for a ledgered program — or ``None`` when the
    program has no measured executions yet (predicted cost alone can't
    place it on the roofline)."""
    if peaks is None:
        peaks = detect_peaks()
    calls = entry.get("calls", 0)
    min_s = entry.get("min_s")
    if not calls or not min_s or min_s <= 0:
        return None
    flops = float(entry.get("flops") or 0.0)
    hbm = float(entry.get("hbm_bytes") or 0.0)
    # best-sustained rates: min over the sampled walls (standard roofline
    # practice — the slower samples carry dispatch/interference noise,
    # and the per-program p50 is reported alongside for honesty)
    gflops = flops / min_s / 1e9
    gbps = hbm / min_s / 1e9
    peak_flops = _flops_peak(peaks, entry.get("dtype", "float32"))
    hbm_gbps = peaks.get("hbm_gbps")
    peak_bw = hbm_gbps * 1e9 if hbm_gbps else None
    frac_c = gflops * 1e9 / peak_flops if peak_flops and flops else None
    frac_h = gbps * 1e9 / peak_bw if peak_bw and hbm else None
    if not peaks.get("known"):
        verdict = "unknown-peak"
    else:
        t_compute = flops / peak_flops if peak_flops else 0.0
        t_hbm = hbm / peak_bw if peak_bw else 0.0
        if t_compute == 0.0 and t_hbm == 0.0:
            verdict = "unknown-peak"  # no predicted work on either axis
        else:
            verdict = "memory-bound" if t_hbm >= t_compute else "compute-bound"
    # host-I/O axis (round 22, core/stream.py): streaming programs carry
    # the MEASURED fraction of pass wall spent blocked on host reads
    # (queue stalls / total host-read seconds).  This overrides the
    # structural verdict because it is an observation, not a model — a
    # stream pass whose consumer waited for the disk most of the time is
    # I/O-bound whatever the FLOP/byte ratio says, and the verdict stays
    # honest even on unknown-peak CPU where the structural axes are mute.
    io_stall = entry.get("io_stall_frac")
    if io_stall is not None and io_stall >= 0.5:
        verdict = "io-bound"
    # the memory axis (memtrack watermarks folded in by timed_call):
    # measured peak residency vs the cost model's predicted mandatory
    # traffic — the honest sequel to predicted-vs-measured time.  An
    # amplification >> 1 means the program's working set dwarfs its
    # operands (staging copies, retained intermediates, mirror buffers).
    peak_bytes = entry.get("peak_bytes")
    amp = round(peak_bytes / hbm, 3) if peak_bytes and hbm else None
    # wire axis (round 17, core/wire.py): programs that ship a quantized
    # collective carry the byte model of what the f32 wire WOULD have
    # moved (logical) vs what the quantized format moved (wire).  The
    # flip marker re-runs the structural verdict with the wire volume
    # folded into the movement bound, compressed vs uncompressed — True
    # means the compression is what moved this row off (or onto) the
    # memory-bound tail, so the row must not be read as a compute win.
    wire = entry.get("wire")
    w_logical = float(entry.get("logical_bytes") or 0.0)
    w_wire = float(entry.get("wire_bytes") or 0.0)
    wire_ratio = round(w_logical / w_wire, 2) if wire and w_wire else None
    wire_flip = None
    if wire and peaks.get("known") and verdict != "unknown-peak" and peak_bw:
        t_compute_ = flops / peak_flops if peak_flops else 0.0
        v_c = "memory-bound" if (hbm + w_wire) / peak_bw >= t_compute_ else "compute-bound"
        v_u = "memory-bound" if (hbm + w_logical) / peak_bw >= t_compute_ else "compute-bound"
        wire_flip = v_c != v_u
    return {
        "fingerprint": entry["fingerprint"],
        "kind": entry.get("kind"),
        "calls": calls,
        "total_s": entry.get("total_s"),
        "p50_s": entry.get("p50_s"),
        "min_s": min_s,
        "flops": flops,
        "hbm_bytes": hbm,
        "achieved_gflops": round(gflops, 3),
        "achieved_gbps": round(gbps, 3),
        "frac_compute_roofline": round(frac_c, 4) if frac_c is not None else None,
        "frac_hbm_roofline": round(frac_h, 4) if frac_h is not None else None,
        "peak_bytes": peak_bytes,
        "mem_amplification": amp,
        "mem_source": entry.get("mem_source"),
        "verdict": verdict,
        "io_stall_frac": io_stall,
        "io_bytes": entry.get("io_bytes"),
        "mesh": entry.get("mesh"),
        "wire": wire,
        "wire_logical_bytes": w_logical if wire else None,
        "wire_bytes": w_wire if wire else None,
        "wire_ratio": wire_ratio,
        "wire_verdict_flip": wire_flip,
    }


def report(
    programs: List[dict],
    *,
    top: Optional[int] = None,
    peaks: Optional[dict] = None,
) -> dict:
    """The roofline document: ``{"device", "peaks", "rows",
    "memory_bound_tail"}``.  Rows cover every program with measured time,
    sorted by total measured seconds (the cost ranking a tuning pass
    reads top-down); ``memory_bound_tail`` lists the fingerprints the
    compute roofline can't help — the Pallas ROADMAP item's feed."""
    if peaks is None:
        peaks = detect_peaks()
    rows = [r for e in programs for r in (attribute(e, peaks),) if r is not None]
    rows.sort(key=lambda r: -(r["total_s"] or 0.0))
    if top is not None:
        rows = rows[: max(int(top), 0)]
    return {
        "device": peaks["device"],
        "peaks": peaks,
        "rows": rows,
        "memory_bound_tail": [
            r["fingerprint"] for r in rows if r["verdict"] == "memory-bound"
        ],
    }


def render(doc: Optional[dict] = None, top: Optional[int] = None) -> str:
    """Human-readable report table (REPL / docs walkthrough aid).  With
    no document, pulls ``telemetry.roofline_report(top=top)``."""
    if doc is None:
        from . import telemetry

        doc = telemetry.roofline_report(top=top)
    p = doc["peaks"]
    lines = [
        f"device={doc['device']} source={p['source']} "
        f"peaks: bf16={p['bf16_tflops']} TFLOP/s f32={p['f32_tflops']} "
        f"TFLOP/s hbm={p['hbm_gbps']} GB/s"
    ]
    lines.append(
        f"{'fingerprint':<14}{'kind':<20}{'calls':>6}{'total_s':>10}"
        f"{'p50_s':>10}{'GFLOP/s':>10}{'GB/s':>9}{'%comp':>7}{'%hbm':>7}"
        f"{'peakMB':>8}{'amp':>6}{'lgclMB':>9}{'wireMB':>8}{'wire_x':>7}"
        "  verdict"
    )
    for r in doc["rows"]:
        pc = f"{100 * r['frac_compute_roofline']:.1f}" if r["frac_compute_roofline"] is not None else "-"
        ph = f"{100 * r['frac_hbm_roofline']:.1f}" if r["frac_hbm_roofline"] is not None else "-"
        pk = f"{r['peak_bytes'] / 1e6:.1f}" if r.get("peak_bytes") else "-"
        am = f"{r['mem_amplification']:.2f}" if r.get("mem_amplification") else "-"
        if r.get("wire"):
            lg = f"{r['wire_logical_bytes'] / 1e6:.2f}"
            wi = f"{r['wire_bytes'] / 1e6:.2f}"
            wx = f"{r['wire_ratio']:.1f}" if r.get("wire_ratio") else "-"
        else:
            lg = wi = wx = "-"
        flip = " [wire-flip]" if r.get("wire_verdict_flip") else ""
        lines.append(
            f"{r['fingerprint']:<14}{(r['kind'] or ''):<20}{r['calls']:>6}"
            f"{r['total_s']:>10.4f}{r['p50_s']:>10.6f}"
            f"{r['achieved_gflops']:>10.2f}{r['achieved_gbps']:>9.2f}"
            f"{pc:>7}{ph:>7}{pk:>8}{am:>6}{lg:>9}{wi:>8}{wx:>7}"
            f"  {r['verdict']}{flip}"
        )
    if doc["memory_bound_tail"]:
        lines.append(
            "memory-bound tail (Pallas-tier candidates): "
            + ", ".join(doc["memory_bound_tail"])
        )
    return "\n".join(lines)
