"""Out-of-core streaming engine: double-buffered host→device slabs under a
measured HBM residency budget (round 22; ROADMAP frontier assumption 3,
reference: heat/utils/data/partial_dataset.py's prefetch-thread model).

The transport engine already bounds *staging* at O(tile); this module
applies the same discipline to *residency*, so an array larger than device
memory becomes a measured, overlapped streaming schedule instead of a
crash.  Three layers:

**Chunk sources.**  :func:`open_source` wraps HDF5 datasets, NetCDF
variables, ``.npy`` memory maps, and in-memory arrays behind one tiny
handle (``shape`` / ``np_dtype`` / ``read(lo, hi)`` / ``close``).  All
rank-local slab math funnels through :func:`read_rows` — the ONE chunk
reader previously copied three times (``core/io.py:load_hdf5``,
``cluster/packing.py:load_hdf5_packed``, ``utils/data/partial_dataset``) —
and every read still routes through ``io._read_region``, so the existing
test spies see streaming reads too.

**Residency plan.**  :func:`plan_pass` sizes the slab and the host
prefetch depth from the budget resolution chain: explicit argument >
``HEAT_TPU_STREAM_BUDGET`` > measured headroom
(``memtrack.suggest_budget``, ledgered via ``autotune.note_budget_seed``)
> a static default.  Three device slabs are transiently live under double
buffering (computing, prefetched, and the consumer's just-released loop
reference), so a slab is at most ``budget // 3`` bytes; the slab-size
*fraction* is an
autotune arm (:data:`autotune.STREAM_ARMS`) per (source-geometry
fingerprint, device kind) — the tuner, not a constant, picks the slab
that maximizes overlap, and every arm is numerically identical so tuning
state can never change results.

**The pass.**  :class:`StreamPass` runs a daemon reader thread (host
reads into a bounded queue, poison-pill shutdown, exceptions propagated
to the consumer) while the consumer generator wraps each host slab into a
``split=0`` DNDarray — ``jax.device_put`` dispatches asynchronously, and
the next slab is fetched *before* the current one is yielded, so slab
``k+1``'s read + transfer hides behind slab ``k``'s compute.  Slabs are a
fixed row count (a multiple of the mesh size, tail zero-padded) so one
compiled program serves every slab — the no-retrace law holds across the
pass.  Consumed slabs are simply dropped by the consumer; their ledger
entries die with the buffers, and the ``staging`` tag's high-water mark
(``memtrack.summary()["peak_bytes_by_tag"]``) is the budget proof.

Telemetry: ``heat_tpu_stream_*`` gauges, ``stream_slab`` /
``stream_pass`` flight-recorder events, and a measured prefetch-overlap
fraction — ``1 - stall/io``, where *stall* is consumer time blocked on
the queue (the first fetch, the unavoidable cold pipeline fill, is
excluded and reported separately) and *io* is reader time on disk.  An
injected or real ``RESOURCE_EXHAUSTED`` during a slab transfer shrinks
the slab (halved, floored at one row per device) and re-chunks the
in-flight host rows instead of dying — the streaming face of the
informed-OOM-retry contract.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
from typing import Any, Callable, NamedTuple, Optional, Tuple

import numpy as np

from . import autotune, factories, guard, memtrack, telemetry
from ..parallel.mesh import sanitize_comm

__all__ = [
    "ChunkSource",
    "DEFAULT_BUDGET",
    "Slab",
    "StreamPass",
    "StreamPlan",
    "finish_pass",
    "open_source",
    "plan_pass",
    "read_rows",
    "residency_budget",
    "stats",
]

# static residency default when nothing measured and no env override: two
# 128 MiB slabs — small enough to be safe on every supported device,
# large enough that host read syscall overhead amortizes
DEFAULT_BUDGET = 256 << 20

_STATS = telemetry.register_group(
    "stream",
    {
        "sources": 0,        # chunk sources opened
        "passes": 0,         # completed streaming passes
        "slabs": 0,          # device slabs produced
        "bytes_read": 0,     # host bytes read off disk/memory
        "oom_retries": 0,    # slab transfers retried after OOM
        "slab_shrinks": 0,   # slab-row halvings (OOM backoff)
        "io_s": 0.0,         # reader-thread seconds on host reads
        "stall_s": 0.0,      # consumer seconds blocked on the queue
        #                      (cold pipeline fill excluded; see below)
        "fill_s": 0.0,       # the excluded first-fetch pipeline fill
    },
)


def stats() -> dict:
    """Snapshot of the ``stream`` counter group (exported to Prometheus
    as ``heat_tpu_stream_*`` gauges)."""
    return telemetry.snapshot_group("stream")


# ------------------------------------------------------------ chunk reading


def read_rows(
    source,
    lo: int,
    hi: int,
    *,
    split_axis: int = 0,
    base: Optional[tuple] = None,
    copy: bool = False,
) -> np.ndarray:
    """THE rank-local slab read: rows ``[lo, hi)`` of ``split_axis``,
    full extent elsewhere, as a host ndarray.  Every h5py/NetCDF/npy/
    in-memory slab read in the repo funnels through here (satellite:
    previously three independent copies of this arithmetic), and through
    ``io._read_region`` below it, so the loaders' never-more-than-a-slab
    test spies cover streaming too.

    ``base`` is an optional tuple of already-normalized slices (one per
    dim, as ``io._normalize_slices`` produces): ``lo``/``hi`` then index
    *logical* rows within ``base[split_axis]``, honoring its step — the
    contract ``load_hdf5`` needs for user-sliced loads.  ``copy=True``
    forces a materialized copy (mmap-backed NetCDF/npy sources, where the
    view must not outlive the handle); memory maps are always copied.
    """
    from . import io as ht_io  # lazy: io imports this module at top level

    if base is None:
        shape = source.shape
        sel = tuple(
            slice(lo, hi) if d == split_axis else slice(0, n)
            for d, n in enumerate(shape)
        )
    else:
        bs = base[split_axis]
        step = bs.step if bs.step is not None else 1
        start = bs.start if bs.start is not None else 0
        sel = list(base)
        sel[split_axis] = slice(start + lo * step, start + hi * step, step)
        sel = tuple(sel)
    out = ht_io._read_region(source, sel)
    if copy or isinstance(out, np.memmap):
        out = np.array(out)
    return np.asarray(out)


class ChunkSource:
    """A row-sliceable host source: ``shape``, ``np_dtype``,
    ``read(lo, hi)`` → host ndarray of rows ``[lo, hi)``, ``close()``.
    Context manager; ``close`` is idempotent."""

    shape: Tuple[int, ...] = ()
    np_dtype: np.dtype = np.dtype(np.float32)

    def read(self, lo: int, hi: int) -> np.ndarray:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self) -> "ChunkSource":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _cast(self, arr: np.ndarray) -> np.ndarray:
        if arr.dtype != self.np_dtype:
            arr = arr.astype(self.np_dtype)
        return arr


class _ArraySource(ChunkSource):
    """In-memory ndarray / live h5py dataset / memory map — anything with
    ``shape`` and basic slicing."""

    def __init__(self, obj, np_dtype=None):
        self._obj = obj
        self.shape = tuple(obj.shape)
        own = np.dtype(getattr(obj, "dtype", np.float32))
        self.np_dtype = np.dtype(np_dtype) if np_dtype is not None else own

    def read(self, lo: int, hi: int) -> np.ndarray:
        return self._cast(read_rows(self._obj, lo, hi))


class _H5Source(ChunkSource):
    def __init__(self, path: str, dataset: str, np_dtype=None):
        import h5py

        self._handle = h5py.File(path, "r")
        try:
            self._dset = self._handle[dataset]
        except Exception:
            self._handle.close()
            raise
        self.shape = tuple(self._dset.shape)
        self.np_dtype = (
            np.dtype(np_dtype) if np_dtype is not None
            else np.dtype(self._dset.dtype)
        )

    def read(self, lo: int, hi: int) -> np.ndarray:
        return self._cast(read_rows(self._dset, lo, hi))

    def close(self) -> None:
        if self._handle is not None:
            self._handle.close()
            self._handle = None


class _NetCDFSource(ChunkSource):
    def __init__(self, path: str, variable: str, np_dtype=None):
        try:
            import netCDF4

            self._handle = netCDF4.Dataset(path, "r")
            self._scipy = False
        except ImportError:
            from scipy.io import netcdf_file

            self._handle = netcdf_file(path, "r", mmap=True)
            self._scipy = True
        self._var = self._handle.variables[variable]
        self.shape = tuple(self._var.shape)
        self.np_dtype = (
            np.dtype(np_dtype) if np_dtype is not None
            else np.dtype(self._var.dtype)
        )

    def read(self, lo: int, hi: int) -> np.ndarray:
        # copy=True: classic-format reads are views into the file mmap
        return self._cast(read_rows(self._var, lo, hi, copy=True))

    def close(self) -> None:
        if self._handle is None:
            return
        import warnings

        self._var = None
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            self._handle.close()
        self._handle = None


def open_source(source, dataset: Optional[str] = None, *, np_dtype=None) -> ChunkSource:
    """Open a streamable row source.  Accepts a path (``.h5``/``.hdf5``
    and ``.nc``/``.nc4``/``.netcdf`` need ``dataset``; ``.npy`` memory-
    maps), an in-memory ndarray / h5py dataset / any ``shape`` +
    ``__getitem__`` object, or an already-open :class:`ChunkSource`
    (returned as-is — caller keeps ownership)."""
    if isinstance(source, ChunkSource):
        return source
    _STATS["sources"] += 1
    if isinstance(source, str):
        ext = os.path.splitext(source)[-1].lower().strip()
        if ext in (".h5", ".hdf5"):
            if dataset is None:
                raise ValueError("HDF5 sources need a dataset name")
            return _H5Source(source, dataset, np_dtype)
        if ext in (".nc", ".nc4", ".netcdf"):
            if dataset is None:
                raise ValueError("NetCDF sources need a variable name")
            return _NetCDFSource(source, dataset, np_dtype)
        if ext == ".npy":
            return _ArraySource(np.load(source, mmap_mode="r"), np_dtype)
        raise ValueError(f"unsupported streaming source extension {ext!r}")
    if hasattr(source, "shape") and hasattr(source, "__getitem__"):
        return _ArraySource(source, np_dtype)
    raise TypeError(f"cannot stream from {type(source)}")


# -------------------------------------------------------------- the budget


def residency_budget(budget: Optional[int] = None) -> int:
    """Resolve the streaming residency budget in bytes: explicit argument
    > ``HEAT_TPU_STREAM_BUDGET`` (strict parse, lint HT001) > measured
    headroom via :func:`memtrack.suggest_budget` (half the free HBM —
    ledgered through ``autotune.note_budget_seed`` when it shrinks the
    default) > :data:`DEFAULT_BUDGET` on statsless backends."""
    if budget is not None:
        return int(budget)
    if os.environ.get("HEAT_TPU_STREAM_BUDGET", "").strip():
        return autotune.env_bytes("HEAT_TPU_STREAM_BUDGET", DEFAULT_BUDGET)
    granted = memtrack.suggest_budget(DEFAULT_BUDGET, fraction=0.5)
    if granted is None or granted <= 0:
        return DEFAULT_BUDGET
    if granted < DEFAULT_BUDGET:
        autotune.note_budget_seed("stream.slab", granted, DEFAULT_BUDGET)
    return granted


class StreamPlan(NamedTuple):
    site: str            # consumer dispatch site ("kmeans_fit", ...)
    rows: int            # total logical rows in the source
    row_bytes: int       # bytes per logical row at the streaming dtype
    slab_rows: int       # device slab rows (multiple of the mesh size)
    depth: int           # host prefetch queue capacity, in slabs
    budget: int          # resolved residency budget, bytes
    arm: str             # STREAM_ARMS member that sized slab_rows
    key: Optional[Tuple[str, str]]  # tuning-table key (None: tuner off)


_ARM_DIV = {"slab_full": 1, "slab_half": 2, "slab_quarter": 4}


def _round_down(x: int, m: int) -> int:
    return (x // m) * m


def _pick_arm(key: Tuple[str, str]) -> str:
    """Least-sampled arm first while exploring: all arms are numerically
    identical, so each pass runs ONE arm and rotation — not the repeated
    prior ``decide`` would return — is what fills every arm's samples."""
    e = autotune.table().get(key)
    counts = {
        a: len(e["arms"].get(a, [])) if e else 0
        for a in autotune.STREAM_ARMS
    }
    return min(autotune.STREAM_ARMS, key=lambda a: counts[a])


def plan_pass(
    src: ChunkSource,
    *,
    comm=None,
    site: str = "stream",
    budget: Optional[int] = None,
) -> StreamPlan:
    """Size one streaming pass over ``src``: resolve the budget, consult
    the tuner for the slab fraction, derive slab rows (multiple of the
    mesh size, two slabs resident under double buffering) and the host
    prefetch depth (what's left of the budget, clamped to [1, 4])."""
    comm = sanitize_comm(comm)
    shape = src.shape
    if not shape:
        raise ValueError("streaming sources must have at least one dim")
    rows = int(shape[0])
    row_bytes = int(src.np_dtype.itemsize)
    for n in shape[1:]:
        row_bytes *= int(n)
    b = residency_budget(budget)
    n_dev = comm.size
    # THREE slabs are transiently live (measured, not assumed): the slab
    # being computed on, the prefetched next one, and the consumer's
    # just-finished loop reference, which Python rebinds only after the
    # generator has already dispatched the next transfer → budget/3 each.
    # The floor is one row per device; below it streaming cannot shard.
    max_rows = max(n_dev, _round_down((b // 3) // max(row_bytes, 1), n_dev))
    arm, key = "slab_full", None
    if autotune.enabled():
        # geometry: rows bucket coarse (streaming length doesn't change
        # the right slab), features/dtype/mesh exact, budget bucketed to
        # a power of two so headroom jitter can't fragment the table
        key = autotune.stream_key(
            site, rows.bit_length(), shape[1:], str(src.np_dtype),
            n_dev, int(b).bit_length(),
        )
        d = autotune.decide(
            key, _pick_arm(key), desc=f"stream {site} {shape}",
            arms=autotune.STREAM_ARMS,
        )
        arm = d.arm
    slab_rows = max(n_dev, _round_down(max_rows // _ARM_DIV[arm], n_dev))
    slab_bytes = slab_rows * row_bytes
    depth = max(1, min(4, b // max(slab_bytes, 1) - 1))
    return StreamPlan(site, rows, row_bytes, slab_rows, depth, b, arm, key)


# ---------------------------------------------------------------- the pass


class Slab(NamedTuple):
    index: int      # 0-based slab number within the pass
    x: Any          # DNDarray, shape (slab_rows, *features), split=0
    valid: int      # rows [0, valid) are real; the rest are zero padding
    base: int       # global row offset of this slab's row 0


class _Reader(threading.Thread):
    """Daemon host-read loop: slabs into a bounded queue, ``None`` poison
    pill on exhaustion OR failure (the error rides ``self.error`` to the
    consumer — satellite: the old partial_dataset thread had neither a
    shutdown path nor error propagation)."""

    def __init__(self, src: ChunkSource, q: "queue_mod.Queue",
                 slab_rows: int, rows: int, stop: threading.Event):
        super().__init__(daemon=True, name="heat-tpu-stream-reader")
        self._src = src
        self._q = q
        self._slab_rows = slab_rows
        self._rows = rows
        # NOT named _stop: threading.Thread owns a private _stop method
        self._halt = stop
        self.error: Optional[BaseException] = None
        self.io_s = 0.0
        self.bytes_read = 0

    def _put(self, item) -> None:
        while not self._halt.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return
            except queue_mod.Full:
                continue

    def run(self) -> None:
        try:
            lo = 0
            while lo < self._rows and not self._halt.is_set():
                hi = min(lo + self._slab_rows, self._rows)
                t0 = time.perf_counter()
                host = self._src.read(lo, hi)
                self.io_s += time.perf_counter() - t0
                self.bytes_read += host.nbytes
                self._put((lo, host))
                lo = hi
        except BaseException as e:
            self.error = e
        finally:
            self._put(None)


class StreamPass:
    """One single-use streaming pass: iterate to get :class:`Slab`\\ s.

    The iterator prefetches — slab ``k+1`` is dequeued, transferred
    (async ``device_put`` inside ``factories.array``) and tagged
    ``staging`` *before* slab ``k`` is yielded, so its host read and
    wire time hide behind the consumer's device compute on ``k``.  Slab
    shape is constant across the pass (tail zero-padded), so the
    consumer's jitted step compiles once.  On ``RESOURCE_EXHAUSTED``
    during a transfer the slab halves (floored at one row per device)
    and the in-flight host rows re-chunk at the new size — later slabs
    run in a new compiled bucket, the documented cost of surviving.

    Use as an iterator or context manager; ``close()`` (idempotent,
    called automatically at exhaustion / generator close) stops and
    joins the reader thread."""

    def __init__(self, src: ChunkSource, *, comm=None,
                 plan: Optional[StreamPlan] = None, site: str = "stream",
                 budget: Optional[int] = None):
        self._src = open_source(src)
        self.comm = sanitize_comm(comm)
        self.plan = plan if plan is not None else plan_pass(
            self._src, comm=self.comm, site=site, budget=budget,
        )
        self.slab_rows = self.plan.slab_rows
        self.stall_s = 0.0
        self.fill_s = 0.0
        self.slabs = 0
        self.oom_retries = 0
        self._host: Optional[np.ndarray] = None
        self._off = 0
        self._hbase = 0
        self._got_first = False
        self._t0 = time.perf_counter()
        self._t1: Optional[float] = None
        self._stop = threading.Event()
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=self.plan.depth)
        self._reader = _Reader(
            self._src, self._q, self.plan.slab_rows, self.plan.rows,
            self._stop,
        )
        self._reader.start()

    # -- lifecycle

    def close(self) -> None:
        """Stop and join the reader (poison-pill + stop event); safe to
        call repeatedly and from ``__del__`` — abandoning a pass mid-way
        leaks neither a thread nor an open source handle it started."""
        if self._t1 is None:
            self._t1 = time.perf_counter()
        if self._stop.is_set():
            return
        self._stop.set()
        # drain so a reader blocked on a full queue sees the stop event
        try:
            while True:
                self._q.get_nowait()
        except queue_mod.Empty:
            pass
        self._reader.join(timeout=5.0)
        _STATS["io_s"] += self._reader.io_s
        _STATS["stall_s"] += self.stall_s
        _STATS["fill_s"] += self.fill_s
        _STATS["bytes_read"] += self._reader.bytes_read

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "StreamPass":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- measured report

    @property
    def wall_s(self) -> float:
        end = self._t1 if self._t1 is not None else time.perf_counter()
        return end - self._t0

    def overlap_frac(self) -> float:
        """Fraction of the reader's host-read time hidden behind device
        compute: ``1 - stall/io``.  The first fetch (cold pipeline fill —
        nothing to overlap with yet) is excluded from the stall and
        reported separately as ``fill_s``."""
        io = self._reader.io_s
        if io <= 0.0:
            return 1.0
        return max(0.0, 1.0 - self.stall_s / io)

    def report(self) -> dict:
        return {
            "slabs": self.slabs,
            "slab_rows": self.slab_rows,
            "bytes_read": self._reader.bytes_read,
            "io_s": round(self._reader.io_s, 6),
            "stall_s": round(self.stall_s, 6),
            "fill_s": round(self.fill_s, 6),
            "wall_s": round(self.wall_s, 6),
            "overlap_frac": round(self.overlap_frac(), 4),
            "oom_retries": self.oom_retries,
        }

    # -- slab production

    def _wrap(self, rows_np: np.ndarray):
        guard.fire("stream.slab")
        x = factories.array(rows_np, split=0, comm=self.comm)
        memtrack.tag_buffer(x.larray, "staging")
        return x

    def _shrink(self, exc: BaseException) -> None:
        n_dev = self.comm.size
        if self.slab_rows <= n_dev:
            raise exc
        new = max(n_dev, _round_down(self.slab_rows // 2, n_dev))
        _STATS["oom_retries"] += 1
        _STATS["slab_shrinks"] += 1
        self.oom_retries += 1
        telemetry.record_event(
            "stream_oom_retry", site=self.plan.site,
            slab_rows=self.slab_rows, retry_rows=new,
            error=str(exc)[:160],
        )
        self.slab_rows = new

    def _fetch(self) -> Optional[Slab]:
        while True:
            if self._host is None or self._off >= self._host.shape[0]:
                t0 = time.perf_counter()
                item = self._q.get()
                dt = time.perf_counter() - t0
                if self._got_first:
                    self.stall_s += dt
                else:
                    self._got_first = True
                    self.fill_s += dt
                if item is None:
                    if self._reader.error is not None:
                        raise RuntimeError(
                            "stream reader failed for "
                            f"{self.plan.site!r}"
                        ) from self._reader.error
                    return None
                self._hbase, self._host = item
                self._off = 0
            take = min(self.slab_rows, self._host.shape[0] - self._off)
            rows_np = self._host[self._off : self._off + take]
            base = self._hbase + self._off
            if take < self.slab_rows:
                pad = np.zeros(
                    (self.slab_rows - take,) + rows_np.shape[1:],
                    rows_np.dtype,
                )
                rows_np = np.concatenate([rows_np, pad])
            try:
                x = self._wrap(rows_np)
            except Exception as e:
                if not _is_oom(e):
                    raise
                # halve and re-cut THIS slab's rows at the new size —
                # the outer loop re-enters with _off unchanged
                self._shrink(e)
                continue
            self._off += take
            slab = Slab(self.slabs, x, take, base)
            self.slabs += 1
            _STATS["slabs"] += 1
            telemetry.record_event(
                "stream_slab", site=self.plan.site, index=slab.index,
                rows=self.slab_rows, valid=take, base=base,
                arm=self.plan.arm,
            )
            return slab

    def __iter__(self):
        try:
            nxt = self._fetch()
            while nxt is not None:
                cur = nxt
                # prefetch before yielding: slab k+1's dequeue + async
                # device_put dispatch while the caller computes on k
                nxt = self._fetch()
                yield cur
        finally:
            self.close()


def _is_oom(e: BaseException) -> bool:
    if "RESOURCE_EXHAUSTED" in str(e):
        return True
    try:
        from ..utils.fault import InjectedOOM

        return isinstance(e, InjectedOOM)
    except Exception:
        return False


def finish_pass(sp: StreamPass) -> dict:
    """Close out one completed pass: fold its wall into the tuner (the
    arm's measured sample), count it, flight-record the summary, and
    return the measured report (the consumer attaches ``overlap_frac`` /
    ``io_bytes`` to its program row via ``telemetry.annotate_program``)."""
    sp.close()
    rep = sp.report()
    _STATS["passes"] += 1
    pl = sp.plan
    if pl.key is not None and autotune.enabled():
        autotune.observe(pl.key, pl.arm, sp.wall_s)
    telemetry.record_event(
        "stream_pass", site=pl.site, arm=pl.arm, budget=pl.budget,
        **rep,
    )
    return rep
