"""Fused op-chain execution: lazy expressions + a sharding-aware compile cache.

The reference executes one torch call (plus one optional MPI collective) per
operator; the eager port kept that shape, so a chain like ``(x - mu) / sd``
dispatches N separate XLA programs with no cross-op fusion, and re-traces
whenever the same chain recurs through a new Python call path.  This module
makes the `_operations.py` workhorses *lazy*: elementwise ops, dtype casts,
``where=`` masks and trailing reductions accumulate into a small op-DAG (an
:class:`Expr` per node), and the whole DAG lowers as ONE jitted XLA
computation at a materialization boundary — ``.larray`` access, a
split-changing op, I/O, or a comparison used in Python control flow (all of
which read the mangled ``_DNDarray__array`` slot and therefore funnel through
:class:`LazyDNDarray.__getattr__`).

Compiled executables are cached under
``(op-graph fingerprint, leaf avals + NamedShardings, target layout)`` with
hit/miss counters exposed via :func:`cache_stats`, so steady-state serving
traffic pays zero retrace.  Scalars enter the graph as 0-d array *inputs*
(never baked constants): the fingerprint is value-independent and a chain
re-run with a different scalar is a cache hit.

Donation-awareness: inside a fused program the intermediates of the chain
never materialize (XLA reuses their buffers), the pad-to-physical +
``with_sharding_constraint`` finalization happens in-program instead of as a
separate dispatch, and the compile layer honors ``donate`` indices
(``jax.jit(donate_argnums=...)``) for callers that hand over a dead input
buffer.  The engine also cooperates with the PR-1 transport engine's
donating ``resplit_``: leaf buffers captured by still-pending expressions
are *pinned* (:func:`safe_to_donate`) so a donating in-place resplit cannot
invalidate a lazy chain built before it.

``HEAT_TPU_FUSE=off`` (or ``0``/``false``) restores fully eager execution
for debugging; :func:`fuse` is the scoped equivalent.

Guardrails (round 8, ISSUE 3): fused execution degrades instead of dying.
A compile or execution failure of the fused program (an XLA error, a
lowering bug) no longer propagates — :func:`_run` falls back to per-op
eager evaluation of the same linearized DAG, and :func:`cache_stats`
breaks the ``fallbacks`` total down by reason (``unfusable``,
``compile_error``, ``exec_error``, ``guard_replay``).  With the
non-finite guard on (``HEAT_TPU_GUARD``, :mod:`heat_tpu.core.guard`),
every op node records the user source line that built it, and a
materialized chain whose finite inputs produced NaN/Inf is replayed
eagerly op-by-op to raise :class:`~heat_tpu.core.guard.NonFiniteError`
naming the first offending op and its originating line.  Provenance is
excluded from the compile-cache key, so guarding adds zero retraces.
"""

from __future__ import annotations

import os
import time
import warnings
import weakref
from collections import OrderedDict
from contextlib import contextmanager
from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import envparse, guard, memtrack, telemetry, types
from ..analysis import program_audit, sanitize
from .dndarray import DNDarray, _physical_dim
from .guard import NonFiniteError

__all__ = [
    "LazyDNDarray",
    "NonFiniteError",
    "Unfusable",
    "cache_stats",
    "defer",
    "describe",
    "enabled",
    "fuse",
    "last_hlo",
    "leaf",
    "leaf_from",
    "materialize",
    "materialize_all",
    "materialize_resplit",
    "node",
    "op_name",
    "register_op",
    "register_split_terminator",
    "register_terminator",
    "reset_cache",
    "safe_to_donate",
    "set_enabled",
]


# --------------------------------------------------------------- env switch

def _env_enabled() -> bool:
    return os.environ.get("HEAT_TPU_FUSE", "on").strip().lower() not in (
        "off", "0", "false", "no",
    )


_ENABLED = _env_enabled()


def enabled() -> bool:
    """Whether the lazy fusion engine is active (``HEAT_TPU_FUSE``)."""
    return _ENABLED


def set_enabled(flag: bool) -> bool:
    """Switch the engine on/off; returns the previous state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


@contextmanager
def fuse(flag: bool = True):
    """Scoped :func:`set_enabled` (``with fusion.fuse(False): ...``)."""
    prev = set_enabled(flag)
    try:
        yield
    finally:
        set_enabled(prev)


class Unfusable(Exception):
    """Raised while building a lazy node when the op cannot enter the DAG
    (unhashable static kwargs, shape inference failure, mixed meshes).
    Callers fall back to the eager path — which either succeeds or raises
    the proper user-facing error."""


# ----------------------------------------------------------------- op table
# Registered metadata for the fns that flow through the engine: a stable
# display name (fingerprints key on the function OBJECT — qualnames are
# unsafe, closures with different static state share them) and a kind tag.
# Registration is optional: unregistered callables fuse too, they just
# print as their __name__ in describe()/debug output.

_OP_TABLE: "dict[Callable, Tuple[str, str]]" = {}


def register_op(fn: Callable, name: str, kind: str = "elementwise") -> Callable:
    """Record display metadata for ``fn`` (see arithmetics/relational/logical
    module bottoms for the standard tables)."""
    _OP_TABLE[fn] = (name, kind)
    return fn


def op_name(fn: Callable) -> str:
    meta = _OP_TABLE.get(fn)
    if meta is not None:
        return meta[0]
    return getattr(fn, "__name__", repr(fn))


# -------------------------------------------------------------- buffer pins
# id(array) -> live-pin count.  A pin means: some still-pending Expr leaf
# holds this exact jax.Array strongly (so the id cannot be recycled while
# the entry exists).  resplit_ consults safe_to_donate() before handing the
# buffer to the transport engine's donating all-to-all.

_PINNED: "dict[int, int]" = {}
# buf_id -> weakrefs to the pinning Exprs; diagnostic shadow of _PINNED
# that lets memtrack's leak detector tell "pin whose owner is gone but the
# finalize never fired" from a legitimately live pin
_PIN_OWNERS: "dict[int, list]" = {}


def _unpin(buf_id: int) -> None:
    n = _PINNED.get(buf_id, 0) - 1
    owners = _PIN_OWNERS.get(buf_id)
    if owners:
        # drop a dead owner ref if one exists (this finalize just killed
        # its Expr), else the newest — the count is what's authoritative
        for i, r in enumerate(owners):
            if r() is None:
                del owners[i]
                break
        else:
            owners.pop()
        if not owners:
            _PIN_OWNERS.pop(buf_id, None)
    if n > 0:
        _PINNED[buf_id] = n
    else:
        _PINNED.pop(buf_id, None)


def _pin(expr: "Expr", value) -> None:
    buf_id = id(value)
    _PINNED[buf_id] = _PINNED.get(buf_id, 0) + 1
    _PIN_OWNERS.setdefault(buf_id, []).append(weakref.ref(expr))
    weakref.finalize(expr, _unpin, buf_id)
    memtrack.tag_buffer(value, "pinned")


def pin_leaks() -> "list[dict]":
    """Pins whose owning Exprs are (partly) gone: for each pinned buffer,
    compare the live-owner count against the pin count — a shortfall means
    an Expr died without its finalize releasing the pin (the leak class
    ``telemetry.leaks()`` exists to catch).  Empty in a healthy process."""
    out = []
    for buf_id, count in _PINNED.items():
        live = sum(1 for r in _PIN_OWNERS.get(buf_id, ()) if r() is not None)
        if live < count:
            out.append({"buf_id": buf_id, "pins": count, "live_owners": live})
    return out


def safe_to_donate(value) -> bool:
    """False iff a pending lazy expression still references ``value`` as a
    leaf — donating it would turn later materialization into a
    use-after-free (``Array has been deleted``)."""
    return id(value) not in _PINNED


# ------------------------------------------------------------------ op-DAG

class Expr:
    """One node of the lazy DAG.

    Leaf: ``value`` is a concrete jax.Array (physical — possibly padded — or
    logical) and ``lshape`` its logical shape.  Op node: ``fn`` applied to
    ``args`` with static ``kwargs``; ``aval`` is the eval_shape-predicted
    result.  Materialization *leafifies* the node in place (sets ``value``,
    drops ``fn``/``args``) so diamond DAGs never recompute a subchain.

    ``site`` is the user source line that built the node (guard.py
    provenance, ``None`` with the guard off or for internal builders).  It
    is diagnostic-only: never part of the compile-cache key."""

    __slots__ = ("fn", "args", "kwargs", "aval", "value", "lshape", "site", "__weakref__")

    def __init__(self, fn, args, kwargs, aval, value=None, lshape=None, site=None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.aval = aval
        self.value = value
        self.lshape = lshape
        self.site = site

    def leafify(self, value, lshape) -> None:
        self.value = value
        self.lshape = tuple(lshape)
        self.fn = None
        self.args = ()
        self.kwargs = None


def leaf(value, lshape=None, pin: bool = False) -> Expr:
    """Wrap a concrete jax array as a DAG leaf.  ``lshape`` is the logical
    shape when ``value`` carries even-chunk physical padding."""
    lshape = tuple(value.shape) if lshape is None else tuple(lshape)
    aval = jax.ShapeDtypeStruct(lshape, value.dtype)
    e = Expr(None, (), None, aval, value=value, lshape=lshape)
    if pin:
        _pin(e, value)
    return e


def leaf_from(x: DNDarray) -> Expr:
    """Leaf (or pending sub-DAG) for a DNDarray operand.  Lazy handles
    contribute their expression — consumer chains extend the producer's DAG
    instead of forcing it.  Concrete handles contribute their *physical*
    array (the program slices the pad off), pinned against donation."""
    if isinstance(x, LazyDNDarray) and "_DNDarray__array" not in x.__dict__:
        e = x._expr
        if e is not None:
            return e
    return leaf(x.parray, x.gshape, pin=True)


def _kwargs_key(kwargs) -> tuple:
    if not kwargs:
        return ()
    try:
        items = tuple(sorted(kwargs.items(), key=lambda kv: kv[0]))
        hash(items)
    except TypeError as err:
        raise Unfusable(f"unhashable static kwargs: {kwargs!r}") from err
    return items


# eval_shape is O(1) per op but not free; memoize on (fn, child avals,
# static kwargs).  LRU-capped: stray per-call closures must not grow it
# unboundedly over a long serving process.
_AVAL_MEMO: "OrderedDict[tuple, jax.ShapeDtypeStruct]" = OrderedDict()
_AVAL_MEMO_MAX = 4096


def _infer_aval(fn, child_avals, kw_key):
    key = (fn, tuple((a.shape, str(a.dtype)) for a in child_avals), kw_key)
    try:
        out = _AVAL_MEMO[key]
        _AVAL_MEMO.move_to_end(key)
        return out
    except KeyError:
        pass
    except TypeError as err:  # unhashable fn
        raise Unfusable(f"unhashable op {fn!r}") from err
    kwargs = dict(kw_key)
    try:
        out = jax.eval_shape(lambda *xs: fn(*xs, **kwargs), *child_avals)
    except Unfusable:
        raise
    except Exception as err:
        raise Unfusable(f"shape inference failed for {op_name(fn)}: {err}") from err
    if not isinstance(out, jax.ShapeDtypeStruct):
        raise Unfusable(f"{op_name(fn)} does not return a single array")
    _AVAL_MEMO[key] = out
    if len(_AVAL_MEMO) > _AVAL_MEMO_MAX:
        _AVAL_MEMO.popitem(last=False)
    return out


def node(fn: Callable, args: Tuple[Expr, ...], **kwargs) -> Expr:
    """Apply ``fn`` lazily to child nodes with static ``kwargs``.  Metadata
    (shape/dtype) is predicted via ``jax.eval_shape`` — no execution.  With
    the guard on, the user source line that built the op rides along for
    non-finite provenance."""
    kw_key = _kwargs_key(kwargs)
    aval = _infer_aval(fn, tuple(a.aval for a in args), kw_key)
    site = guard.capture_site(2) if guard.enabled() else None
    return Expr(fn, tuple(args), kw_key, aval, site=site)


def _astype(t, dtype):
    return t.astype(dtype)


register_op(_astype, "astype", kind="cast")


def cast_node(child: Expr, dtype) -> Expr:
    """Lazy dtype cast (fuses into the chain; no array-sized copy)."""
    if str(child.aval.dtype) == str(jnp.dtype(dtype)):
        return child
    return node(_astype, (child,), dtype=jnp.dtype(dtype))


def _render_instrs(instrs, leaves, out_slots, upto=None, mark=None) -> str:
    """Shared renderer behind :func:`describe` and the guard's offending-
    subtree report.  ``upto`` truncates after that slot; ``mark`` annotates
    one slot (the first non-finite producer).

    A slot consumed more than once — by several op nodes, several program
    outputs, or both — is a shared subexpression: it renders ONCE, tagged
    ``<<shared xN>>`` with its consumer count, instead of being re-printed
    per consumer (the instruction list is already in deduplicated form, so
    re-printing would misreport the program as executing it N times)."""
    if isinstance(out_slots, int):
        out_slots = (out_slots,)
    refs: "dict[int, int]" = {}
    for ins in instrs:
        if ins[0] == "O":
            for c in ins[3]:
                refs[c] = refs.get(c, 0) + 1
    for s in out_slots:
        refs[s] = refs.get(s, 0) + 1
    last = len(instrs) - 1 if upto is None else int(upto)
    lines = []
    for i, ins in enumerate(instrs[: last + 1]):
        if ins[0] == "L":
            lf = leaves[ins[1]]
            line = f"%{i} = leaf{tuple(lf.lshape)}:{lf.value.dtype}"
        else:
            _, fn, kw, ch = ins
            kws = f" {dict(kw)}" if kw else ""
            line = f"%{i} = {op_name(fn)}({', '.join('%%%d' % c for c in ch)}){kws}"
        if refs.get(i, 0) > 1:
            line += f"   <<shared x{refs[i]}>>"
        if mark is not None and i == mark:
            line += "   <-- first non-finite"
        lines.append(line)
    if upto is None:
        lines.append("return " + ", ".join(f"%{s}" for s in out_slots))
    else:
        lines.append(f"return %{last}")
    return "\n".join(lines)


def describe(*exprs) -> str:
    """Human-readable postorder rendering of one or more DAG roots
    (debugging aid).  Accepts :class:`Expr` roots or (lazy) DNDarrays;
    several roots render as ONE deduplicated instruction list with a
    multi-value ``return`` — exactly the program :func:`materialize_all`
    would compile — and subtrees consumed more than once carry a
    ``<<shared xN>>`` ref-mark instead of being printed per consumer."""
    roots = []
    for e in exprs:
        if isinstance(e, Expr):
            roots.append(e)
        elif isinstance(e, DNDarray):
            roots.append(leaf_from(e))
        else:
            raise TypeError(f"describe() takes Expr or DNDarray, got {type(e)}")
    instrs, _, leaves, out_slots = _linearize(*roots)
    return _render_instrs(instrs, leaves, out_slots)


# -------------------------------------------------- fingerprint + lowering

def _linearize(*roots: Expr):
    """Postorder-linearize one or more DAG roots into
    ``(instrs, sites, leaves, out_slots)``.

    ``instrs`` is the canonical serialization the compile cache keys on:
    leaves become ``("L", leaf_index)`` numbered by first encounter, op
    nodes ``("O", fn, kwargs_key, child_slots)``.  All roots share ONE
    instruction list — ``out_slots`` names each root's result slot — so a
    subtree reachable from several roots is scheduled exactly once.

    Deduplication is two-level.  Node identity: a diamond (the same
    ``Expr`` object reached twice) serializes once.  Structural CSE: two
    *distinct* op nodes with the same fingerprint — op object, kwargs key,
    child slots, the same scheme the cache key uses — collapse to one
    slot, so independently built copies of a subexpression (``mean`` and
    ``var`` each re-deriving ``(x - mu)``) execute once inside the fused
    program.  Every op-node reuse from either level counts as a
    ``cse_hits`` event in :func:`cache_stats`.

    ``sites`` is the parallel per-slot provenance (guard.py user lines) —
    kept OUT of ``instrs`` so the same chain built from two source
    locations shares one cache entry; a structurally merged node keeps the
    site of its first builder."""
    instrs = []
    sites = []
    leaves = []
    slot: "dict[int, int]" = {}
    leaf_slot: "dict[tuple, int]" = {}
    struct_slot: "dict[tuple, int]" = {}
    keepalive = []  # id()-keyed dict needs the nodes alive for the walk

    def visit(n: Expr) -> int:
        nid = id(n)
        hit = slot.get(nid)
        if hit is not None:
            if instrs[hit][0] == "O":
                _STATS["cse_hits"] += 1
            return hit
        keepalive.append(n)
        if n.value is not None:
            # two leaf nodes wrapping the same buffer collapse to one
            # program input (x appearing twice in a chain is one arg)
            lk = (id(n.value), tuple(n.lshape))
            if lk in leaf_slot:
                slot[nid] = leaf_slot[lk]
                return slot[nid]
            leaves.append(n)
            instrs.append(("L", len(leaves) - 1))
            sites.append(n.site)
            leaf_slot[lk] = len(instrs) - 1
        else:
            ch = tuple(visit(c) for c in n.args)
            sk = (n.fn, n.kwargs, ch)
            hit = struct_slot.get(sk)
            if hit is not None:
                _STATS["cse_hits"] += 1
                slot[nid] = hit
                return hit
            instrs.append(("O", n.fn, n.kwargs, ch))
            sites.append(n.site)
            struct_slot[sk] = len(instrs) - 1
        slot[nid] = len(instrs) - 1
        return slot[nid]

    out_slots = tuple(visit(r) for r in roots)
    return tuple(instrs), tuple(sites), leaves, out_slots


def _build_program(
    instrs, out_slots, lshapes, gshapes, splits, nshards, targets, with_guard=False
):
    """The single fused computation for one cache entry: slice leaf pads to
    logical, evaluate the DAG once, and — for EVERY output slot — pad the
    result to its physical shape and pin its canonical NamedSharding; the
    whole `_ensure_split` finalization happens *inside* the program instead
    of as a separate dispatch.  Returns a flat tuple, one array per root.
    A subtree feeding several roots executes once (the instruction list is
    already in deduplicated form).

    ``with_guard=True`` folds the non-finite guard's reduction into the
    SAME executable: the program appends one joint ``allfinite`` scalar
    (AND over all outputs) to the tuple, so the guard costs zero extra
    dispatches on the hot path (a separate jitted isfinite program
    measured ~10x the acceptable tax on the CPU CI mesh).  Guard-off
    programs are byte-identical to the unguarded build."""

    def program(*vals):
        env = []
        for ins in instrs:
            if ins[0] == "L":
                v = vals[ins[1]]
                ls = lshapes[ins[1]]
                if tuple(v.shape) != ls:
                    v = v[tuple(slice(0, n) for n in ls)]
                env.append(v)
            else:
                _, fn, kw, ch = ins
                env.append(fn(*[env[c] for c in ch], **dict(kw or ())))
        outs = []
        flag = jnp.asarray(True) if with_guard else None
        for out_slot, gshape, split, target in zip(out_slots, gshapes, splits, targets):
            out = env[out_slot]
            if with_guard and jnp.issubdtype(jnp.result_type(out), jnp.inexact):
                # on the logical (pre-pad) output: pad zeros are always finite
                flag = jnp.logical_and(flag, jnp.all(jnp.isfinite(out)))
            if split is not None and gshape:
                n = gshape[split]
                pn = _physical_dim(n, nshards)
                if pn != n:
                    pad = [(0, 0)] * len(gshape)
                    pad[split] = (0, pn - n)
                    out = jnp.pad(out, pad)
            out = jax.lax.with_sharding_constraint(out, target)
            outs.append(out)
        return tuple(outs) + ((flag,) if with_guard else ())

    return program


# --------------------------------------------------------- chain terminators
# Schedule-controlled engines (parallel/overlap.py's collective matmul)
# register a *lowerer* consulted at compile-cache misses, before the generic
# GSPMD program is built.  A lowerer that recognizes the chain returns a
# replacement program with the same contract as _build_program
# (``program(*leaf_vals) -> out`` or ``(out, allfinite)`` under the folded
# guard); returning None declines.  The replacement enters the SAME cache
# entry — hits/misses/retrace accounting in cache_stats() cover terminated
# chains identically.  ``salt`` contributes the engine's dispatch state
# (mode/threshold) to the cache key, so flipping HEAT_TPU_MATMUL builds a
# distinct entry instead of reusing the other mode's executable.
# Correctness never depends on a lowerer: a declined or failing lowering
# falls back to the generic fused program (and a replacement program that
# fails to compile falls back eager like any other entry).

_TERMINATORS: "list[Tuple[Callable, Optional[Callable]]]" = []


def register_terminator(lowerer: Callable, salt: Optional[Callable] = None) -> Callable:
    """Register ``lowerer(instrs, leaves, out_slot, lshapes, gshape, split,
    comm, target, with_guard) -> program | None`` (see block comment)."""
    _TERMINATORS.append((lowerer, salt))
    return lowerer


def _terminator_salt() -> tuple:
    return tuple(s() for _, s in _TERMINATORS if s is not None)


# zero-arg callables whose results join EVERY compile-cache key (a
# terminator salt rides only alongside its lowerer's registration).
# Process-wide dispatch state that changes which program a chain should
# build — the autotune plane's (enabled, generation) — registers here,
# so a tuned-winner flip builds a distinct cache entry instead of
# reusing the executable lowered under the old decision.
_CACHE_SALTS: "list[Callable]" = []


def register_cache_salt(fn: Callable) -> Callable:
    """Register a zero-arg callable contributing to every compile-cache
    key (idempotent per callable)."""
    if fn not in _CACHE_SALTS:
        _CACHE_SALTS.append(fn)
    return fn


def _cache_salt() -> tuple:
    return tuple(s() for s in _CACHE_SALTS)


def _lower_terminated(instrs, leaves, out_slot, lshapes, gshape, split, comm,
                      target, with_guard):
    for lowerer, _ in _TERMINATORS:
        try:
            program = lowerer(
                instrs, leaves, out_slot, lshapes, gshape, split, comm,
                target, with_guard,
            )
        except Exception:
            program = None  # a broken matcher must not break the chain
        if program is not None:
            return program
    return None


# ------------------------------------------------------------ compile cache

class _Entry:
    __slots__ = ("jitted", "avals", "hits", "fp")

    def __init__(self, jitted, avals, fp=None):
        self.jitted = jitted
        self.avals = avals
        self.hits = 0
        self.fp = fp  # telemetry ledger fingerprint (None below counters)


_CACHE: "OrderedDict[tuple, _Entry]" = OrderedDict()
_CACHE_MAX = envparse.env_int("HEAT_TPU_FUSE_CACHE_SIZE", 4096)
# All counters live in ONE telemetry group; the registry owns the reset
# contract (a counter added to the defaults below resets/exports/snapshots
# with no second bookkeeping site).  Notable members:
#   roots_per_program — output-arity histogram of compiled programs
#                       ({n_roots: misses at that arity}).  A serving
#                       steady state shows this frozen; a growing
#                       multi-root bucket on repeated materialize_all()
#                       calls is a retrace regression.
#   fallback_reasons  — per-reason breakdown of the `fallbacks` total:
#     unfusable     — op declined to enter the DAG (built eagerly instead)
#     compile_error — fused program failed to trace/compile/first-run;
#                     re-executed per-op eagerly with identical semantics
#     exec_error    — cached executable failed at run time; same recovery
#     guard_replay  — non-finite guard replayed the chain op-by-op to
#                     attribute the first NaN/Inf producer
_STATS = telemetry.register_group(
    "fusion",
    {
        "hits": 0, "misses": 0, "evictions": 0, "fallbacks": 0,
        "cse_hits": 0,
        "fallback_reasons": {
            "unfusable": 0, "compile_error": 0, "exec_error": 0,
            "guard_replay": 0,
        },
        "roots_per_program": {},
    },
    extra=lambda: {"size": len(_CACHE)},
)
# hot-path aliases into the group (reset_group restores nested dicts in
# place, so these never dangle)
_FALLBACK_REASONS = _STATS["fallback_reasons"]
_ROOTS_PER_PROGRAM = _STATS["roots_per_program"]


def cache_stats() -> dict:
    """Counters for the executable cache: ``hits``/``misses`` (lookups),
    ``size`` (live entries), ``evictions`` (LRU drops past
    ``HEAT_TPU_FUSE_CACHE_SIZE``), ``fallbacks`` (total degraded-to-eager
    events) with a per-reason breakdown under ``fallback_reasons``
    (``unfusable`` / ``compile_error`` / ``exec_error`` /
    ``guard_replay``).  A serving steady state shows misses flat and hits
    climbing — a miss on a repeated chain is a retrace regression; a
    climbing ``compile_error``/``exec_error`` bucket means fused programs
    are failing and silently running degraded.

    DAG-scheduler counters: ``cse_hits`` counts op-subtree reuse events
    during linearization — every time a root (or another consumer) resolves
    to an already-scheduled op slot instead of re-emitting its subtree,
    whether by node identity (a diamond / several roots over one producer)
    or by structural fingerprint (independently built copies of the same
    subexpression).  ``roots_per_program`` is the output-arity histogram of
    compiled programs (``{1: single-root misses, 2: two-output misses,
    ...}``): `materialize_all` traffic shows up as multi-root buckets, and
    a bucket that keeps growing on repeated same-shape calls is a
    multi-output retrace regression.

    Thin shim over ``telemetry.snapshot_group("fusion")`` — the same
    counters appear in ``ht.telemetry.snapshot()`` and the Prometheus
    export."""
    return telemetry.snapshot_group("fusion")


def reset_cache() -> None:
    """Drop all executables and zero the counters (tests/benchmarks).
    Counter reset is registry-managed (``telemetry.reset_group``)."""
    _CACHE.clear()
    telemetry.reset_group("fusion")


def count_fallback(reason: str = "unfusable") -> None:
    _STATS["fallbacks"] += 1
    _FALLBACK_REASONS[reason] = _FALLBACK_REASONS.get(reason, 0) + 1
    telemetry.record_event("fallback", reason=reason)
    if reason == "exec_error":
        # a cached executable dying at run time is the flight recorder's
        # flagship postmortem case: dump the trail before degrading
        telemetry.postmortem("exec_error_fallback")


def last_hlo() -> Optional[str]:
    """Compiled HLO text of the most recently used cache entry (census
    tests count modules/ops in it).  None when the cache is empty."""
    if not _CACHE:
        return None
    entry = next(reversed(_CACHE.values()))
    return entry.jitted.lower(*entry.avals).compile().as_text()


def _sliced_leaf(vals, lshapes, idx):
    v = vals[idx]
    ls = lshapes[idx]
    if tuple(v.shape) != ls:
        v = v[tuple(slice(0, n) for n in ls)]
    return v


def _eager_eval(instrs, vals, lshapes):
    """Per-op eager evaluation of the linearized DAG: the degraded-mode
    twin of :func:`_build_program`'s in-jit loop.  Each op dispatches as
    its own XLA program (exactly the pre-fusion execution shape), so a
    chain that breaks the fused compiler still computes — slower, never
    wrong."""
    env = []
    for ins in instrs:
        if ins[0] == "L":
            env.append(_sliced_leaf(vals, lshapes, ins[1]))
        else:
            _, fn, kw, ch = ins
            env.append(fn(*[env[c] for c in ch], **dict(kw or ())))
    return env


def _finalize_eager(out, gshape, split, nshards, target):
    """The `_build_program` finalization (pad to physical + canonical
    sharding) for eagerly-computed results."""
    if split is not None and gshape:
        n = gshape[split]
        pn = _physical_dim(n, nshards)
        if pn != n:
            pad = [(0, 0)] * len(gshape)
            pad[split] = (0, pn - n)
            out = jnp.pad(out, pad)
    return jax.device_put(out, target)


def _eager_fallback(instrs, vals, lshapes, out_slots, gshapes, splits, comm, targets):
    env = _eager_eval(instrs, vals, lshapes)
    return tuple(
        _finalize_eager(env[s], tuple(g), sp, comm.size, tg)
        for s, g, sp, tg in zip(out_slots, gshapes, splits, targets)
    )


@jax.jit
def _allfinite(a):
    return jnp.all(jnp.isfinite(a))


def _finite(v) -> bool:
    """Host-synced finiteness of one array (True for non-float dtypes)."""
    if not jnp.issubdtype(v.dtype, jnp.inexact):
        return True
    return bool(_allfinite(v))


# Outputs at or below this many elements are guard-checked on the host (one
# small device_get + a numpy pass); above it the allfinite reduction is
# folded into the fused executable instead, so no output-sized host
# transfer ever happens.  64K elements = 256 KiB of f32 — well under a
# tile, and the host pass is cheaper than an extra XLA dispatch there.
_GUARD_FOLD_MIN_ELEMS = 1 << 16


def _host_finite(out) -> bool:
    arr = np.asarray(out)
    if not np.issubdtype(arr.dtype, np.inexact):
        return True
    return bool(np.isfinite(arr).all())


def _reaches(instrs, root_slot, target_slot) -> bool:
    """Whether ``target_slot`` is in the subtree of ``root_slot`` (used to
    attribute a shared offending node to every consuming output)."""
    memo: "dict[int, bool]" = {}

    def walk(s):
        if s == target_slot:
            return True
        got = memo.get(s)
        if got is not None:
            return got
        ins = instrs[s]
        memo[s] = r = ins[0] == "O" and any(walk(c) for c in ins[3])
        return r

    return walk(root_slot)


def _guard_check(outs, instrs, sites, leaves, lshapes, out_slots, fast_flag=None):
    """Raise :class:`NonFiniteError` when the chain *introduced* NaN/Inf.

    ``outs``/``out_slots`` cover every root of the (possibly multi-output)
    program.  Fast path: the joint ``allfinite`` scalar the fused program
    already computed (``fast_flag``, large outputs), or a host-side numpy
    pass over the fetched outputs (small outputs / eager-fallback
    results).  Only when that trips: if any input leaf already carried
    non-finite values the chain merely propagated them (nansum-style
    workflows are legal) and nothing is raised; otherwise the linearized
    DAG replays eagerly op-by-op — ONCE, over the deduplicated instruction
    list, so a shared node is evaluated and blamed once — to name the
    first op whose finite inputs went non-finite, plus every program
    output its subtree feeds."""
    if (
        bool(fast_flag)
        if fast_flag is not None
        else all(_host_finite(o) for o in outs)
    ):
        return
    vals = [lf.value for lf in leaves]
    if not all(_finite(v) for v in vals):
        return  # propagation, not production
    count_fallback("guard_replay")
    err = None
    env = []
    for i, ins in enumerate(instrs):
        if ins[0] == "L":
            env.append(_sliced_leaf(vals, lshapes, ins[1]))
            continue
        _, fn, kw, ch = ins
        val = fn(*[env[c] for c in ch], **dict(kw or ()))
        env.append(val)
        if not _finite(val):
            name = op_name(fn)
            site = sites[i]
            subtree = _render_instrs(instrs, leaves, out_slots, upto=i, mark=i)
            consumers = ""
            if len(out_slots) > 1:
                fed = [
                    k for k, s in enumerate(out_slots) if _reaches(instrs, s, i)
                ]
                consumers = (
                    f"; feeds output(s) {', '.join('%%%d' % out_slots[k] for k in fed)}"
                    f" (root index {', '.join(str(k) for k in fed)})"
                    f" of the {len(out_slots)}-output program"
                )
            err = NonFiniteError(
                f"non-finite values first produced by op '{name}' "
                f"(built at {guard.format_site(site)}){consumers}; "
                f"offending subtree:\n{subtree}",
                op=name, site=site, subtree=subtree,
            )
            break
    if err is None:
        # the eager replay stayed finite: the non-finites exist only in
        # the fused program's output (an XLA numeric divergence — or an
        # injected corruption).  Still a guard trip: degraded numerics
        # must not pass silently just because they resist op-level
        # attribution.
        subtree = _render_instrs(instrs, leaves, out_slots)
        err = NonFiniteError(
            "non-finite values in the fused output, but an eager op-by-op "
            "replay of the same chain is finite — fused-program numeric "
            "divergence (rerun with HEAT_TPU_FUSE=off to confirm); chain:\n"
            f"{subtree}",
            op=None, site=None, subtree=subtree,
        )
    eid = telemetry.record_event(
        "guard_blame",
        op=err.op,
        site=guard.format_site(err.site) if err.site else None,
        n_roots=len(out_slots),
        strict=guard.strict(),
    )
    err.event_id = eid
    if guard.strict():
        telemetry.postmortem("guard_raise")
        raise err
    # default warn mode: NumPy's own contract for sqrt(-1)/log(0)-class
    # results is a RuntimeWarning, not an exception — keep parity, but
    # with chain-aware attribution attached.  Warning is constructed as an
    # INSTANCE so the blame event id survives onto it (warning → event
    # correlation for tests and postmortems).
    w = guard.NonFiniteWarning(str(err))
    w.event_id = eid
    warnings.warn(w, stacklevel=3)


def _tuplize(program, with_guard):
    """Adapt a single-root terminator program (contract: returns ``out`` or
    ``(out, allfinite)``) to the scheduler's flat-tuple convention
    (``(out,)`` or ``(out, allfinite)`` flattened)."""

    def wrapped(*vals):
        out = program(*vals)
        if with_guard:
            out, flag = out
            return (out, flag)
        return (out,)

    return wrapped


def _program_fingerprint(instrs, out_slots) -> str:
    """Stable short digest of the program TOPOLOGY for the telemetry
    ledger: registered display names (not function reprs, which carry
    object addresses), static kwargs, child slots, and the root set.
    Distinct from the compile-cache key on purpose — the ledger
    identifies a program shape across meshes and dtypes."""
    parts = []
    for ins in instrs:
        if ins[0] == "L":
            parts.append(f"L{ins[1]}")
        else:
            parts.append(f"{op_name(ins[1])}{ins[2] or ()}>{ins[3]}")
    parts.append(f"->{out_slots}")
    return telemetry.fingerprint(parts)


def _estimate_cost(instrs, leaves, lshapes, out_slots):
    """Walk the linearized DAG once and estimate ``(ops, flops,
    hbm_bytes)`` for the telemetry cost ledger.

    FLOPs per op by registered kind: elementwise/cast/comparison/
    predicate count one per OUTPUT element; reduction/composite/scan one
    per INPUT element; matmul counts ``2·m·k·n`` from its 2-D operand
    avals — the same operand accounting the overlap dispatcher's
    bytes-per-step cost model keys on.  HBM bytes are the mandatory
    traffic floor: each unique leaf read once plus each root written once
    (fused intermediates never round-trip — that is the point of the
    engine).  Avals re-derive through the memoized :func:`_infer_aval`,
    so a repeat walk of a known topology is dict lookups."""

    def _nelems(shape):
        n = 1
        for d in shape:
            n *= int(d)
        return n

    avals = []
    n_ops = 0
    flops = 0.0
    for ins in instrs:
        if ins[0] == "L":
            lf = leaves[ins[1]]
            avals.append(
                jax.ShapeDtypeStruct(tuple(lshapes[ins[1]]), lf.value.dtype)
            )
            continue
        _, fn, kw, ch = ins
        child = tuple(avals[c] for c in ch)
        out = _infer_aval(fn, child, kw)
        avals.append(out)
        n_ops += 1
        kind = _OP_TABLE.get(fn, (None, "elementwise"))[1]
        if (
            kind == "matmul"
            and len(child) >= 2
            and len(child[0].shape) == 2
            and len(child[1].shape) == 2
        ):
            (m, k), n = child[0].shape, child[1].shape[-1]
            flops += 2.0 * int(m) * int(k) * int(n)
        elif kind in ("reduction", "composite", "scan"):
            flops += float(sum(_nelems(a.shape) for a in child))
        else:  # elementwise / cast / comparison / predicate / unregistered
            flops += float(_nelems(out.shape))
    hbm = sum(
        _nelems(lshapes[i]) * np.dtype(lf.value.dtype).itemsize
        for i, lf in enumerate(leaves)
    )
    hbm += sum(
        _nelems(avals[s].shape) * np.dtype(avals[s].dtype).itemsize
        for s in out_slots
    )
    return n_ops, flops, float(hbm)


def _run_many(exprs, gshapes, splits, comm, donate: Tuple[int, ...] = ()):
    """Telemetry-spanned wrapper: every multi-root lowering runs inside a
    ``fusion.materialize`` span (nested under any caller span; at trace
    level it lands in Perfetto via ``jax.profiler.TraceAnnotation``)."""
    with telemetry.span("fusion.materialize", roots=len(exprs)):
        return _run_many_impl(exprs, gshapes, splits, comm, donate)


def _run_many_impl(exprs, gshapes, splits, comm, donate: Tuple[int, ...] = ()):
    """Lower several DAG roots as ONE multi-output program (or fetch the
    cached executable) and run it, returning one physical array per root.

    The roots linearize into a single deduplicated instruction list
    (:func:`_linearize`), so subtrees shared between roots — by node
    identity or by structural fingerprint — compile and execute exactly
    once.  The cache key carries the full ``out_slots`` tuple: output
    arity and the root-set fingerprint are part of the entry, so a
    two-output program never aliases its single-output prefix.

    Failure containment: a fused program that fails to compile or execute
    falls back to per-op eager evaluation of the same DAG (counted under
    ``compile_error``/``exec_error`` in :func:`cache_stats`); with the
    guard on, a materialized chain whose finite inputs produced NaN/Inf
    raises :class:`NonFiniteError` via an attributing eager replay — the
    folded fast-finite flag joins the program's output tuple instead of
    forcing a second dispatch."""
    instrs, sites, leaves, out_slots = _linearize(*exprs)
    vals = [lf.value for lf in leaves]
    if sanitize.enabled():
        # every DAG leaf funnels through here — the use-after-donate
        # choke point for fused programs
        for v in vals:
            sanitize.check_use(v, "fusion.materialize")
    lshapes = tuple(tuple(lf.lshape) for lf in leaves)
    gshapes = tuple(tuple(g) for g in gshapes)
    splits = tuple(splits)
    targets = tuple(comm.sharding(s, len(g)) for s, g in zip(splits, gshapes))
    sig = tuple(
        (tuple(v.shape), str(v.dtype), getattr(v, "sharding", None))
        for v in vals
    )
    # For large outputs the guard folds its allfinite reduction into the
    # executable (no output-sized host transfer, no extra dispatch), so
    # the guard state is part of the program — guard-off entries stay
    # byte-identical to the unguarded build.  Small outputs keep the
    # unmodified program and are checked host-side after the fetch.
    guard_on = guard.enabled()
    fold = False
    if guard_on:
        n_max = 0
        for g in gshapes:
            n = 1
            for d in g:
                n *= int(d)
            n_max = max(n_max, n)
        fold = n_max > _GUARD_FOLD_MIN_ELEMS
    key = (
        instrs, out_slots, lshapes, sig, gshapes, splits, targets, donate,
        guard_on, _terminator_salt(), _cache_salt(),
    )
    flag = None
    donated_ran = False
    entry = _CACHE.get(key)
    if entry is None:
        _STATS["misses"] += 1
        n_roots = len(out_slots)
        _ROOTS_PER_PROGRAM[n_roots] = _ROOTS_PER_PROGRAM.get(n_roots, 0) + 1
        # ledger + flight-recorder bookkeeping happens only on the miss
        # path — by definition not the steady state, so the DAG cost walk
        # and fingerprint hash add nothing to cached traffic
        fp = None
        ops = 0
        flops = hbm = 0.0
        mesh_info = {"devices": comm.size}
        if telemetry.ledger_enabled():
            try:
                fp = _program_fingerprint(instrs, out_slots)
                ops, flops, hbm = _estimate_cost(
                    instrs, leaves, lshapes, out_slots
                )
            except Exception:  # an estimator bug must never block lowering
                pass
        telemetry.record_event(
            "cache_miss", fingerprint=fp, n_roots=n_roots,
        )
        telemetry.record_event(
            "compile_begin", fingerprint=fp, n_roots=n_roots, ops=ops,
            mesh=mesh_info, flops=flops, hbm_bytes=hbm,
        )
        t0 = time.monotonic()
        try:
            guard.fire("fusion.compile")
            program = None
            if n_roots == 1:
                # schedule-controlled engines (overlap.py's ring matmul)
                # keep their single-root contract; multi-root programs
                # always take the generic GSPMD build
                single = _lower_terminated(
                    instrs, leaves, out_slots[0], lshapes, gshapes[0],
                    splits[0], comm, targets[0], fold,
                )
                if single is not None:
                    program = _tuplize(single, fold)
            if program is None:
                program = _build_program(
                    instrs, out_slots, lshapes, gshapes, splits, comm.size,
                    targets, with_guard=fold,
                )
            jitted = jax.jit(program, donate_argnums=donate or ())
            if program_audit.enabled():
                fp_a = fp
                if fp_a is None:
                    try:
                        fp_a = _program_fingerprint(instrs, out_slots)
                    except Exception:
                        fp_a = None
                program_audit.audit_program(
                    "fused", fp_a, jitted, vals,
                    donate=tuple(donate or ()), expect="reduce",
                )
            # only mesh shardings are recorded for AOT re-lowering (last_hlo):
            # a SingleDeviceSharding on an uncommitted scalar leaf would pin it
            # to device 0 and clash with the mesh-committed array leaves
            avals = tuple(
                jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=s if isinstance(s, jax.sharding.NamedSharding) else None,
                )
                for v in vals
                for s in (getattr(v, "sharding", None),)
            )
            entry = _Entry(jitted, avals)
            outs = entry.jitted(*vals)
            donated_ran = True
            if fold:
                outs, flag = outs[:-1], outs[-1]
        except Exception:
            # trace/lowering/compile/first-run failure: the executable is
            # unusable — do NOT cache it; recompute per-op eagerly
            telemetry.record_event(
                "compile_end", fingerprint=fp, ok=False,
                dur_s=round(time.monotonic() - t0, 6),
            )
            count_fallback("compile_error")
            flag = None
            outs = _eager_fallback(
                instrs, vals, lshapes, out_slots, gshapes, splits, comm, targets
            )
        else:
            telemetry.record_event(
                "compile_end", fingerprint=fp, ok=True,
                dur_s=round(time.monotonic() - t0, 6),
                n_roots=n_roots, ops=ops, flops=flops, hbm_bytes=hbm,
                mesh=mesh_info,
            )
            if fp is not None:
                telemetry.record_program(
                    fp, kind="fused", n_roots=n_roots, ops=ops,
                    flops=flops, hbm_bytes=hbm, mesh=mesh_info,
                )
            entry.fp = fp
            _CACHE[key] = entry
            while len(_CACHE) > _CACHE_MAX:
                _, evicted = _CACHE.popitem(last=False)
                _STATS["evictions"] += 1
                telemetry.record_event("cache_evict", fingerprint=evicted.fp)
    else:
        _STATS["hits"] += 1
        entry.hits += 1
        _CACHE.move_to_end(key)
        telemetry.program_hit(entry.fp)
        telemetry.record_event("cache_hit", fingerprint=entry.fp)
        try:
            guard.fire("fusion.exec")
            # steady-state executions get the (sampled) measured wall
            # clock; the miss path's first run is excluded — its wall is
            # trace+compile time, already on the compile_end event
            outs = telemetry.timed_call(entry.fp, entry.jitted, *vals)
            donated_ran = True
            if fold:
                outs, flag = outs[:-1], outs[-1]
        except Exception:
            count_fallback("exec_error")
            flag = None
            outs = _eager_fallback(
                instrs, vals, lshapes, out_slots, gshapes, splits, comm, targets
            )
    if donate and donated_ran:
        # the executed program consumed these leaves via donate_argnums —
        # poison the stale handles (the eager fallback never donates)
        for i in donate:
            if i < len(vals):
                sanitize.poison(
                    vals[i], donated_site="fusion._run_many(donate_argnums)"
                )
    outs = tuple(outs)
    fused_outs = outs
    outs = guard.corrupt("fusion.exec", outs)
    if guard_on:
        # an injected corruption replaced the output object: the folded
        # flag describes the pre-corruption values, so re-check explicitly
        _guard_check(
            outs, instrs, sites, leaves, lshapes, out_slots,
            fast_flag=flag if outs is fused_outs else None,
        )
    return outs


def _run(expr: Expr, gshape, split, comm, donate: Tuple[int, ...] = ()):
    """Single-root :func:`_run_many` (the ``.larray`` boundary)."""
    return _run_many((expr,), (gshape,), (split,), comm, donate)[0]


# ----------------------------------------------------------- lazy DNDarray

class LazyDNDarray(DNDarray):
    """A DNDarray whose payload is a pending :class:`Expr`.

    All metadata (shape, dtype, split, device, comm) is exact and available
    immediately — only the array value is deferred.  Every base-class code
    path that reads the mangled ``_DNDarray__array`` slot (``.larray``,
    ``.parray``, ``__bool__``, ``resplit_``, printing, ``numpy()``, ...)
    triggers ``__getattr__`` on the missing slot, which materializes the
    DAG through the compile cache and caches the physical result — the
    materialization boundaries of the ISSUE fall out of attribute access,
    with zero changes to the call sites."""

    def __init__(self, expr, gshape, dtype, split, device, comm):
        super().__init__(None, gshape, dtype, split, device, comm)
        object.__setattr__(self, "_expr", expr)
        del self._DNDarray__array

    def __getattr__(self, name):
        if name == "_DNDarray__array":
            expr = self._expr
            value = _run(expr, self.gshape, self.split, self.comm)
            # leafify in place: later chains referencing this node reuse
            # the computed buffer instead of recompiling the subchain.
            # The buffer is pinned for the node's remaining lifetime (it
            # may now be a leaf of other pending DAGs) and the handle
            # drops its expression reference, so the pin dies with the
            # last consumer rather than with this handle.
            expr.leafify(value, self.gshape)
            memtrack.register_buffer(value, tag="output", split=self.split)
            _pin(expr, value)
            object.__setattr__(self, "_DNDarray__array", value)
            object.__setattr__(self, "_expr", None)
            return value
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        # still pending + copy=True: the cast joins the DAG (a fused
        # convert_element_type, never an array-sized dispatch)
        if copy and "_DNDarray__array" not in self.__dict__:
            ht_dtype = types.canonical_heat_type(dtype)
            try:
                casted = cast_node(self._expr, ht_dtype.jax_type())
            except Unfusable:
                return super().astype(dtype, copy)
            return LazyDNDarray(
                casted, self.gshape, ht_dtype, self.split, self.device, self.comm
            )
        return super().astype(dtype, copy)


def defer(expr: Expr, gshape, dtype, split, device, comm) -> LazyDNDarray:
    """Wrap a DAG root as a lazy DNDarray with the given result metadata."""
    return LazyDNDarray(
        expr, tuple(gshape), dtype, split, device, comm
    )


def materialize_all(*arrays):
    """Materialize several (possibly lazy) DNDarrays as ONE fused program.

    All still-pending roots that share a mesh lower together through
    :func:`_run_many`: subtrees shared between the roots (by node identity
    or structural fingerprint) compile and execute exactly once, and the
    whole batch is a single compile-cache entry / single XLA dispatch.
    Already-materialized (or eager) arrays pass through untouched; roots
    on different meshes are grouped per mesh.  Returns ``arrays`` as a
    tuple, every element now physical.
    """
    # DNDarray.__eq__ is elementwise — membership tests must use id()
    pending = []
    seen = set()
    for x in arrays:
        if (
            isinstance(x, LazyDNDarray)
            and "_DNDarray__array" not in x.__dict__
            and id(x) not in seen
        ):
            seen.add(id(x))
            pending.append(x)
    while pending:
        head = pending[0]
        group = [
            x for x in pending
            if x.comm is head.comm or x.comm.mesh == head.comm.mesh
        ]
        gids = {id(x) for x in group}
        pending = [x for x in pending if id(x) not in gids]
        if len(group) == 1:
            group[0].parray  # single root: the ordinary __getattr__ path
            continue
        exprs = tuple(x._expr for x in group)
        outs = _run_many(
            exprs,
            tuple(x.gshape for x in group),
            tuple(x.split for x in group),
            head.comm,
        )
        for x, value in zip(group, outs):
            expr = x._expr
            expr.leafify(value, x.gshape)
            memtrack.register_buffer(value, tag="output", split=x.split)
            _pin(expr, value)
            object.__setattr__(x, "_DNDarray__array", value)
            object.__setattr__(x, "_expr", None)
    for x in arrays:
        x.parray  # eager handles are no-ops; duplicates already leafified
    return tuple(arrays)


def materialize(*arrays):
    """Force one or more (possibly lazy) DNDarrays to physical payloads.

    ``materialize(x)`` keeps the original single-array contract and
    returns ``x`` itself.  ``materialize(a, b, ...)`` batches all pending
    roots into ONE multi-output fused executable (shared subtrees
    deduplicated — see :func:`materialize_all`) and returns the arrays as
    a tuple.  Exported as ``heat_tpu.materialize``.
    """
    if not arrays:
        raise TypeError("materialize() requires at least one array")
    if len(arrays) == 1:
        arrays[0].parray  # property read funnels through __getattr__
        return arrays[0]
    return materialize_all(*arrays)


# ------------------------------------------- split-boundary terminators

# Lowerers consulted when a lazy chain terminates at a split CHANGE (a
# resplit / split-crossing reshape boundary) rather than at a plain read.
# Contract: lowerer(instrs, leaves, out_slot, lshapes, gshape, old_split,
# new_split, comm, tile_bytes) -> physical array in the NEW split, or
# None to decline.  Registered lazily by parallel/transport.py so core
# keeps zero imports from parallel at module load.
_SPLIT_TERMINATORS: "list[Callable]" = []


def register_split_terminator(lowerer: Callable) -> Callable:
    """Register a split-boundary lowerer (see ``_SPLIT_TERMINATORS``)."""
    _SPLIT_TERMINATORS.append(lowerer)
    return lowerer


_SPLIT_LOWERERS_READY = False


def _ensure_split_lowerers() -> None:
    global _SPLIT_LOWERERS_READY
    if _SPLIT_LOWERERS_READY:
        return
    from ..parallel import transport

    transport.ensure_fused_tail_registered()
    _SPLIT_LOWERERS_READY = True


def materialize_resplit(x, new_split, tile_bytes=None):
    """Lower a pending chain DIRECTLY into the new split's transport loop.

    When ``x`` is a still-pending :class:`LazyDNDarray` whose elementwise
    tail a registered split terminator can fuse into the per-tile
    all-to-all (compute on tile *k* overlapping the collective for tile
    *k+1*), returns the physical array already in ``new_split`` — no
    separate pre-pass materialization.  Returns None when the chain is
    not pending, the boundary is not a real split change, or every
    lowerer declines; callers then fall back to materialize-then-resplit.

    ``x`` itself stays pending: the fused output is in the NEW layout,
    while other consumers of the chain still need the old-split value.
    """
    if not _ENABLED:
        return None
    if not (
        isinstance(x, LazyDNDarray) and "_DNDarray__array" not in x.__dict__
    ):
        return None
    if new_split is None or x.split is None or new_split == x.split:
        return None
    _ensure_split_lowerers()
    expr = x._expr
    if expr is None:
        return None
    instrs, sites, leaves, out_slots = _linearize(expr)
    lshapes = tuple(tuple(lf.lshape) for lf in leaves)
    for lowerer in _SPLIT_TERMINATORS:
        try:
            out = lowerer(
                instrs, leaves, out_slots[0], lshapes, tuple(x.gshape),
                x.split, int(new_split), x.comm, tile_bytes,
            )
        except Exception:
            out = None
        if out is not None:
            if guard.enabled():
                _guard_check(
                    (out,), instrs, sites, leaves, lshapes, out_slots,
                    fast_flag=None,
                )
            return out
    return None
