"""Logical operations (reference: heat/core/logical.py, 549 LoC)."""

from __future__ import annotations

import jax.numpy as jnp

from . import _operations, types
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """True where all elements along axis are truthy (reference: MPI.LAND
    reduce, logical.py:~30)."""
    return _operations._reduce_op(jnp.all, x, axis=axis, out=out, keepdims=keepdims)


def allclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Global closeness verdict (reference: logical.py:~100)."""
    a = x.larray if isinstance(x, DNDarray) else jnp.asarray(x)
    b = y.larray if isinstance(y, DNDarray) else jnp.asarray(y)
    return bool(jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan))  # ht: HT002 ok — allclose returns a Python bool by NumPy-parity contract


def any(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """True where any element along axis is truthy (reference: MPI.LOR)."""
    return _operations._reduce_op(jnp.any, x, axis=axis, out=out, keepdims=keepdims)


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    return _operations._binary_op(
        jnp.isclose, x, y, fn_kwargs={"rtol": rtol, "atol": atol, "equal_nan": equal_nan}
    )


def isfinite(x) -> DNDarray:
    return _operations._local_op(jnp.isfinite, x, no_cast=True)


def isinf(x) -> DNDarray:
    return _operations._local_op(jnp.isinf, x, no_cast=True)


def isnan(x) -> DNDarray:
    return _operations._local_op(jnp.isnan, x, no_cast=True)


def isneginf(x, out=None) -> DNDarray:
    return _operations._local_op(jnp.isneginf, x, out=out, no_cast=True)


def isposinf(x, out=None) -> DNDarray:
    return _operations._local_op(jnp.isposinf, x, out=out, no_cast=True)


def logical_and(x, y) -> DNDarray:
    return _operations._binary_op(jnp.logical_and, x, y)


def logical_not(x, out=None) -> DNDarray:
    return _operations._local_op(jnp.logical_not, x, out=out, no_cast=True)


def logical_or(x, y) -> DNDarray:
    return _operations._binary_op(jnp.logical_or, x, y)


def logical_xor(x, y) -> DNDarray:
    return _operations._binary_op(jnp.logical_xor, x, y)


def signbit(x, out=None) -> DNDarray:
    return _operations._local_op(jnp.signbit, x, out=out, no_cast=True)


DNDarray.all = lambda self, axis=None, out=None, keepdims=False: all(self, axis, out, keepdims)
DNDarray.any = lambda self, axis=None, out=None, keepdims=False: any(self, axis, out, keepdims)

# fusion op table (see arithmetics.py)
from . import fusion as _fusion  # noqa: E402

for _fn, _name in [
    (jnp.logical_and, "logical_and"), (jnp.logical_or, "logical_or"),
    (jnp.logical_xor, "logical_xor"), (jnp.logical_not, "logical_not"),
    (jnp.isclose, "isclose"), (jnp.isfinite, "isfinite"),
    (jnp.isinf, "isinf"), (jnp.isnan, "isnan"),
    (jnp.isneginf, "isneginf"), (jnp.isposinf, "isposinf"),
    (jnp.signbit, "signbit"),
]:
    _fusion.register_op(_fn, _name, kind="predicate")
for _fn, _name in [(jnp.all, "all"), (jnp.any, "any")]:
    _fusion.register_op(_fn, _name, kind="reduction")

