"""Version information for heat_tpu.

Mirrors the reference's version layout (heat/core/version.py:1-16) with a
major/minor/micro/extension split.
"""

major: int = 0
"""Major version number."""
minor: int = 1
"""Minor version number."""
micro: int = 0
"""Micro version number."""
extension: str = "dev"
"""Extension tag."""

if not extension:
    __version__ = f"{major}.{minor}.{micro}"
else:
    __version__ = f"{major}.{minor}.{micro}-{extension}"
