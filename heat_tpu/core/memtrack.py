"""HBM residency ledger: per-buffer attribution, watermarks, OOM forensics.

Heat's scarce resource is device memory per worker (PAPER.md §0: one shard
per process, split along one axis), and the ROADMAP's out-of-core item —
"stream what doesn't fit in HBM" — cannot be built or debugged without
measuring what fits.  PRs 6–7 gave the *time* axis a full observability
plane (flight recorder, spans, cost ledger, measured roofline); this module
is the *memory* counterpart, wired through the same telemetry levels:

**Live-buffer ledger.**  Every DNDarray construction (and the factory/
transport/fusion output sites) registers its device buffer here via a
``weakref.finalize`` — nbytes, dtype, split, sharding, a creation site
(the user ``file:line``, reusing the guard's caller-attribution walk), and
a tag (``leaf|pinned|staging|donated|output``).  Entries die with their
buffers; :func:`live_buffers` answers "who holds HBM right now" top-K by
bytes, :func:`census` packages the same answer for OOM postmortems, and
the ``memtrack`` group in ``telemetry.snapshot()`` carries the summary.

**Unified device readers.**  :func:`device_bytes_in_use` /
:func:`min_free_bytes` are the ONE ``device.memory_stats()`` reader
(previously three hand-rolled copies: ``utils/monitor.py``,
``cluster/kmeans.py``, and per-call max loops), tolerant of backends that
return ``None`` (CPU, remote TPU tunnels).  :func:`stats_override` lets
tests — and :meth:`FaultInjector.low_hbm` — simulate a memory-starved
device on backends with no stats, so the informed OOM backoff is testable
on the CI mesh.

**Watermark sampling.**  :func:`sample_bytes` reads the max per-device
``bytes_in_use`` (falling back to the ledger's tracked live bytes where
the backend is silent — the source rides the sample, so a ledger-derived
number is never mistaken for a device-measured one).
``telemetry.timed_call`` samples it around the three timed execution
sites (fusion cache-hit path, transport tile loops, ring matmul), giving
``telemetry.programs()`` / ``roofline_report()`` a measured
``peak_bytes`` + memory-amplification column and ``export_trace()`` a
Perfetto counter track.

**Retention detection.**  :func:`memwatch` scopes a region whose
registrations are expected to die by exit; survivors — plus fusion pins
whose owning Expr is gone (``fusion.pin_leaks``) — surface through
:func:`leaks`.

Gating: the ledger registers at ``events`` level and above (``off`` and
``counters`` pay one integer compare per would-be registration, matching
telemetry's documented idle cost); watermark sampling rides
``timed_call``'s existing gate (every call at ``events``, every Nth at
``counters``).
"""

from __future__ import annotations

import gc
import os
import time
import weakref
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import jax

from . import guard, telemetry

__all__ = [
    "census",
    "device_bytes_in_use",
    "device_peaks",
    "enabled",
    "leaks",
    "live_buffers",
    "memwatch",
    "min_free_bytes",
    "register_buffer",
    "reset",
    "sample_bytes",
    "set_enabled",
    "set_stats_override",
    "stats_override",
    "suggest_budget",
    "summary",
    "tag_buffer",
    "would_fit",
]

# the tag vocabulary: why a buffer is (still) resident
TAGS = ("leaf", "pinned", "staging", "donated", "output")

# kill-switch UNDER the telemetry level: HEAT_TPU_MEMTRACK=0 keeps the
# flight recorder / spans / timings at events level but silences the
# residency ledger and watermark sampler — the control the cb
# memtrack_overhead row toggles to price the ledger alone, and an
# operator's out if ledger cost ever matters on a hot serving path
_ENABLED = [os.environ.get("HEAT_TPU_MEMTRACK", "1").lower()
            not in ("0", "off", "false")]


def set_enabled(on: bool) -> bool:
    """Enable/disable the ledger + sampler (returns the previous state).
    Orthogonal to the telemetry level: disabling keeps every other
    events-level facility live."""
    prev = _ENABLED[0]
    _ENABLED[0] = bool(on)
    return prev


def enabled() -> bool:
    return _ENABLED[0]

# id(buffer) -> record; the finalize on the buffer removes the entry, so
# the ledger holds no strong reference and can never extend a lifetime
_LEDGER: Dict[int, dict] = {}
_LIVE_BYTES = [0]       # sum of nbytes over _LEDGER (mutable module slot)
_PEAK_LIVE = [0]        # high-water mark of _LIVE_BYTES
_REG_SEQ = [0]          # registration counter (memwatch scope marker)
_DEVICE_PEAKS: Dict[str, int] = {}   # device str -> max sampled bytes_in_use
_WATCH_RETAINED: List[dict] = []     # survivors of the last memwatch() scope
# per-tag live bytes + their high-water marks, maintained incrementally
# (register/retag/drop) rather than derived from _LEDGER: a derived scan
# only sees the tag at snapshot time, but the streaming engine's budget
# proof needs the PEAK "staging" residency — the most slab bytes ever
# simultaneously live — which only an incremental counter can record
_LIVE_BY_TAG: Dict[str, int] = {}
_PEAK_BY_TAG: Dict[str, int] = {}


def _reset_state() -> None:
    _LEDGER.clear()
    _LIVE_BYTES[0] = 0
    _PEAK_LIVE[0] = 0
    _REG_SEQ[0] = 0
    _DEVICE_PEAKS.clear()
    _WATCH_RETAINED.clear()
    _LIVE_BY_TAG.clear()
    _PEAK_BY_TAG.clear()


def _tag_add(tag: str, nbytes: int) -> None:
    live = _LIVE_BY_TAG.get(tag, 0) + nbytes
    _LIVE_BY_TAG[tag] = live
    if live > _PEAK_BY_TAG.get(tag, 0):
        _PEAK_BY_TAG[tag] = live


def summary() -> dict:
    """The ``memtrack`` group's derived fields: live count/bytes, the
    ledger high-water mark, a per-tag bytes breakdown, and the sampled
    per-device peaks."""
    by_tag: Dict[str, int] = {}
    by_dtype: Dict[str, int] = {}
    for rec in _LEDGER.values():
        by_tag[rec["tag"]] = by_tag.get(rec["tag"], 0) + rec["nbytes"]
        dt = str(rec["dtype"])
        by_dtype[dt] = by_dtype.get(dt, 0) + rec["nbytes"]
    return {
        "live_buffers": len(_LEDGER),
        "live_bytes": _LIVE_BYTES[0],
        "peak_live_bytes": _PEAK_LIVE[0],
        "bytes_by_tag": by_tag,
        # per-dtype residency: the one-snapshot answer to "what did
        # quantizing the weights actually buy" (int8 vs f32/bf16 bytes)
        "bytes_by_dtype": by_dtype,
        # high-water marks per tag: "staging" is the streaming engine's
        # proof that double-buffered slabs never exceeded their budget
        "peak_bytes_by_tag": dict(_PEAK_BY_TAG),
        "device_peak_bytes": dict(_DEVICE_PEAKS),
    }


_COUNTERS = telemetry.register_group(
    "memtrack",
    {
        # buffers ever registered / released by their finalizer
        "registered": 0,
        "released": 0,
        # re-registrations of an already-ledgered live buffer (an alias
        # wrapped again — e.g. a no-pad _to_physical pass-through)
        "rebinds": 0,
        # watermark reads taken by telemetry.timed_call
        "mem_samples": 0,
    },
    extra=summary,
    on_reset=_reset_state,
)


def reset() -> None:
    """Zero the counters AND drop the ledger/peaks/watch state
    (registry-managed: ``telemetry.reset_group("memtrack")``)."""
    telemetry.reset_group("memtrack")


# ------------------------------------------------------------------ ledger

def _drop(buf_id: int) -> None:
    rec = _LEDGER.pop(buf_id, None)
    if rec is None:
        return
    _LIVE_BYTES[0] -= rec["nbytes"]
    _LIVE_BY_TAG[rec["tag"]] = _LIVE_BY_TAG.get(rec["tag"], 0) - rec["nbytes"]
    _COUNTERS["released"] += 1


def _format_sharding(s) -> Optional[str]:
    if s is None:
        return None
    spec = getattr(s, "spec", None)
    name = type(s).__name__
    return f"{name}({spec})" if spec is not None else name


def register_buffer(value, *, tag: str = "leaf", split=None) -> Optional[int]:
    """Ledger one device buffer (gated: ``events`` level and above; the
    idle cost is the one integer compare below).  The creation site is
    the nearest user frame (guard's caller-attribution walk); lifetime is
    tracked by ``weakref.finalize`` on the buffer itself, so the entry
    disappears exactly when XLA can reclaim the memory.  Re-registering a
    live buffer (an alias wrapped into a second DNDarray) keeps the first
    entry — the true creation site — and counts a rebind.  Returns the
    ledger key (``id(value)``) or ``None`` when not ledgered."""
    if telemetry._LEVEL < telemetry._EVENTS or not _ENABLED[0]:
        return None
    try:
        # itemsize * prod(shape), not value.nbytes: jax rederives the
        # nbytes property per read (~5x the cost of this loop) and the
        # ledger sits on every materialization
        nbytes = int(value.dtype.itemsize)
        for dim in value.shape:
            nbytes *= int(dim)
    except Exception:
        return None  # not an array-like payload (tracers, tuples, None)
    buf_id = id(value)
    if buf_id in _LEDGER:
        _COUNTERS["rebinds"] += 1
        return buf_id
    try:
        # a plain ref with a death callback, not weakref.finalize: finalize
        # pays registry + atexit bookkeeping we don't need (~3x the cost),
        # and this sits on every materialization.  The ref rides the
        # record, so dropping the record (reset) also disarms the callback.
        ref = weakref.ref(value, lambda _r, _b=buf_id: _drop(_b))
    except TypeError:
        return None  # backend array type without weakref support
    _REG_SEQ[0] += 1
    # dtype/sharding stay RAW here (both are tiny interned/shared objects,
    # holding them extends no buffer lifetime); _render formats them
    # lazily so the per-materialization hot path pays no string work
    _LEDGER[buf_id] = {
        "id": buf_id,
        "seq": _REG_SEQ[0],
        "nbytes": nbytes,
        "dtype": getattr(value, "dtype", None),
        "shape": tuple(getattr(value, "shape", ())),
        "split": split,
        "sharding": getattr(value, "sharding", None),
        "tag": tag if tag in TAGS else "leaf",
        "site": guard.format_site(guard.capture_site(2)),
        "ts": time.monotonic(),
        "wr": ref,
    }
    _COUNTERS["registered"] += 1
    _LIVE_BYTES[0] += nbytes
    if _LIVE_BYTES[0] > _PEAK_LIVE[0]:
        _PEAK_LIVE[0] = _LIVE_BYTES[0]
    _tag_add(_LEDGER[buf_id]["tag"], nbytes)
    return buf_id


def tag_buffer(value, tag: str) -> None:
    """Retag a live ledger entry (e.g. a leaf about to be DONATED to a
    destructive resplit, or one newly PINNED by a pending lazy DAG).
    No-op below ``events`` level or for unledgered buffers."""
    if telemetry._LEVEL < telemetry._EVENTS or not _ENABLED[0]:
        return
    rec = _LEDGER.get(id(value))
    if rec is not None and tag in TAGS and tag != rec["tag"]:
        _LIVE_BY_TAG[rec["tag"]] = (
            _LIVE_BY_TAG.get(rec["tag"], 0) - rec["nbytes"]
        )
        rec["tag"] = tag
        _tag_add(tag, rec["nbytes"])


def _pinned_ids() -> set:
    try:
        from . import fusion

        return set(fusion._PINNED)
    except Exception:
        return set()


def _render(rec: dict, pinned: set, now: float) -> dict:
    return {
        "id": rec["id"],
        "nbytes": rec["nbytes"],
        "dtype": str(rec["dtype"]) if rec["dtype"] is not None else None,
        "shape": rec["shape"],
        "split": rec["split"],
        "sharding": _format_sharding(rec["sharding"]),
        "tag": rec["tag"],
        "pinned": rec["id"] in pinned,
        "site": rec["site"],
        "age_s": round(now - rec["ts"], 3),
    }


def live_buffers(top: Optional[int] = 10) -> List[dict]:
    """The live ledger, largest first: one dict per buffer with nbytes,
    dtype, shape, split, sharding, tag, live pin state, the creation site
    (``file:line in func``), and age.  ``top`` bounds the list (``None``
    = all)."""
    rows = sorted(_LEDGER.values(), key=lambda r: -r["nbytes"])
    if top is not None:
        rows = rows[: max(int(top), 0)]
    pinned = _pinned_ids()
    now = time.monotonic()
    return [_render(r, pinned, now) for r in rows]


def census(top: int = 8) -> dict:
    """The buffer census an OOM postmortem attaches: total live
    count/bytes plus the top-K buffers with creation sites and pin
    state — "what was resident when the allocation failed"."""
    return {
        "live_buffers": len(_LEDGER),
        "live_bytes": _LIVE_BYTES[0],
        "bytes_by_dtype": summary()["bytes_by_dtype"],
        "top": live_buffers(top),
    }


# --------------------------------------------------- unified device readers

# test/injection hook: a list of fake per-device memory_stats() dicts
# (each with bytes_in_use/bytes_limit) standing in for jax's readers —
# installed by stats_override() / FaultInjector.low_hbm(), so the
# informed backoff and watermark paths are drivable on stats-less CPU
_STATS_OVERRIDE: Optional[List[dict]] = None


def set_stats_override(devices: Optional[List[dict]]) -> Optional[List[dict]]:
    """Install (or clear, with ``None``) simulated per-device
    ``memory_stats()`` readings; returns the previous override."""
    global _STATS_OVERRIDE
    prev = _STATS_OVERRIDE
    _STATS_OVERRIDE = list(devices) if devices is not None else None
    return prev


@contextmanager
def stats_override(devices: List[dict]):
    """Scoped :func:`set_stats_override`::

    >>> with memtrack.stats_override(
    ...     [{"bytes_in_use": 900, "bytes_limit": 1000}]
    ... ):
    ...     assert memtrack.min_free_bytes() == 100
    """
    prev = set_stats_override(devices)
    try:
        yield
    finally:
        set_stats_override(prev)


# (name, device) pairs cached at first use: jax.local_devices() and
# str(device) are rebuilt per call otherwise, and the watermark sampler
# reads stats twice per timed program — the cache keeps a sample in the
# low-microsecond range.  The local device set is fixed per process.
_DEVICE_READERS: Optional[List[tuple]] = None


def _device_readers() -> List[tuple]:
    global _DEVICE_READERS
    if _DEVICE_READERS is None:
        try:
            _DEVICE_READERS = [(str(d), d) for d in jax.local_devices()]
        except Exception:
            return []  # backend not up yet: retry next call, cache nothing
    return _DEVICE_READERS


def _raw_device_stats() -> List[Tuple[str, Optional[dict]]]:
    """``(device, memory_stats() or None)`` per local device — ``None``
    where the backend has no reader (CPU) or the read fails (remote
    tunnels)."""
    if _STATS_OVERRIDE is not None:
        return [
            (str(d.get("device", f"injected:{i}")), d)
            for i, d in enumerate(_STATS_OVERRIDE)
        ]
    out: List[Tuple[str, Optional[dict]]] = []
    for name, dev in _device_readers():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        out.append((name, stats))
    return out


def device_bytes_in_use() -> Tuple[List[Tuple[str, Optional[int]]], Optional[int]]:
    """``(per_device, worst)``: per-device ``bytes_in_use`` readings and
    their max.  The max — not device 0 — is the number that matters on a
    multi-device mesh: uneven splits and replicated operands peak on
    whichever device holds the remainder.  Devices without stats report
    ``None`` and are ignored by the max (``worst`` is ``None`` when no
    device reports).  The ONE reader behind ``utils/monitor``,
    ``cluster/kmeans`` and the watermark sampler."""
    per: List[Tuple[str, Optional[int]]] = []
    worst = None
    for name, stats in _raw_device_stats():
        used = stats.get("bytes_in_use") if stats else None
        used = int(used) if used is not None else None
        per.append((name, used))
        if used is not None and (worst is None or used > worst):
            worst = used
    return per, worst


def min_free_bytes() -> Optional[int]:
    """Tightest per-device headroom: ``min(bytes_limit - bytes_in_use)``
    over devices exposing both — the budget the informed OOM backoff
    sizes its first-retry tile from.  ``None`` when no device reports."""
    tightest = None
    for _name, stats in _raw_device_stats():
        if not stats:
            continue
        limit = stats.get("bytes_limit")
        used = stats.get("bytes_in_use")
        if limit is None or used is None:
            continue
        free = int(limit) - int(used)
        if tightest is None or free < tightest:
            tightest = free
    return tightest


def suggest_budget(
    request: int,
    *,
    fraction: float = 0.25,
    floor: int = 0,
    headroom: int = 0,
    free: Optional[int] = None,
) -> Optional[int]:
    """THE free-HBM budget formula:
    ``max(floor, min(request, (free - headroom) * fraction))``.

    One helper behind every HBM-informed sizing decision — transport's
    informed OOM retry, kmeans' lane-pack residency check, and the
    autotune plane's plan-time tile/staging seeding — so the clamp
    semantics can never drift between sites.  ``request`` is what the
    caller would spend absent memory pressure; ``fraction`` reserves
    slack for everything that isn't this buffer; ``headroom`` is an
    absolute reservation subtracted before the fraction.  Returns
    ``None`` when no device reports memory stats (statsless backends
    keep their static defaults — never a fake budget).  Pass ``free``
    to reuse a reading already taken this call."""
    if free is None:
        # cheap no-op on statsless backends: reuse sample_bytes' latch
        # (set after one full silent device read; overrides beat it)
        if _STATS_OVERRIDE is None and _STATSLESS[0]:
            return None
        free = min_free_bytes()
        if free is None:
            return None
    granted = int((int(free) - int(headroom)) * float(fraction))
    return max(int(floor), min(int(request), granted))


def would_fit(
    nbytes: int,
    *,
    fraction: float = 0.5,
    headroom: int = 0,
) -> Optional[bool]:
    """Admission-control face of :func:`suggest_budget`: does an
    ``nbytes`` staging allocation fit inside the suggested budget?

    Returns ``None`` on statsless backends (CPU) — the caller should
    admit, never shed on fake numbers.  The serving front door's
    ``hbm_pressure`` shed decision routes through here so its clamp
    semantics stay identical to transport's OOM retry and the autotune
    seeding sites."""
    nbytes = int(nbytes)
    granted = suggest_budget(nbytes, fraction=fraction, headroom=headroom)
    if granted is None:
        return None
    return granted >= nbytes


def device_peaks() -> Dict[str, int]:
    """Max sampled ``bytes_in_use`` per device (fed by
    :func:`sample_bytes` via ``telemetry.timed_call``)."""
    return dict(_DEVICE_PEAKS)


# latched after a full device read where NO device reported stats: a
# backend that is silent once (CPU) is silent for the process, and the
# sampler runs twice per timed program — skip the 8-device probe loop.
# Overrides are checked before the latch, so low_hbm() still lands.
_STATSLESS = [False]


def sample_bytes() -> Tuple[Optional[int], Optional[str]]:
    """One watermark reading: ``(bytes, source)``.  Prefers the measured
    device max (``source="device"``, folding per-device peaks as a side
    effect); where the backend is silent, falls back to the ledger's
    tracked live bytes (``source="ledger"`` — only meaningful while the
    ledger records, i.e. ``events`` level).  ``(None, None)`` when
    neither axis has data — an honest unknown, never a fake zero."""
    if not _ENABLED[0]:
        return None, None
    if _STATS_OVERRIDE is None and _STATSLESS[0]:
        per, worst = [], None
    else:
        per, worst = device_bytes_in_use()
        if worst is None and _STATS_OVERRIDE is None and per:
            _STATSLESS[0] = True
    if worst is not None:
        _COUNTERS["mem_samples"] += 1
        for name, used in per:
            if used is not None and used > _DEVICE_PEAKS.get(name, -1):
                _DEVICE_PEAKS[name] = used
        return worst, "device"
    if telemetry._LEVEL >= telemetry._EVENTS:
        _COUNTERS["mem_samples"] += 1
        return _LIVE_BYTES[0], "ledger"
    return None, None


# ------------------------------------------------------ retention detection

class _MemWatch:
    """Handle yielded by :func:`memwatch`; ``retained`` fills at exit."""

    __slots__ = ("retained", "_mark")

    def __init__(self, mark: int):
        self.retained: List[dict] = []
        self._mark = mark


@contextmanager
def memwatch():
    """Retention scope: every buffer registered inside and still alive at
    exit is a suspect.  Exit runs one ``gc.collect()`` (a diagnostic
    scope may hold cycles that would free momentarily anyway), then
    records the survivors on the handle's ``retained`` and module-wide
    for :func:`leaks`::

    >>> with telemetry.memwatch() as w:
    ...     scratch = ht.zeros((4096,), split=0)
    ...     keep = ht.ones((8,), split=0)
    ...     del scratch
    >>> [r["site"] for r in w.retained]   # names keep's creation line
    """
    w = _MemWatch(_REG_SEQ[0])
    try:
        yield w
    finally:
        gc.collect()
        pinned = _pinned_ids()
        now = time.monotonic()
        w.retained = [
            _render(rec, pinned, now)
            for rec in sorted(_LEDGER.values(), key=lambda r: -r["nbytes"])
            if rec["seq"] > w._mark
        ]
        _WATCH_RETAINED[:] = w.retained


def leaks() -> List[dict]:
    """Suspected retention, two classes: ``kind="pin"`` — entries in
    fusion's ``_PINNED`` registry whose owning Expr is gone (the
    ``weakref.finalize`` unpin never fired — exactly the class the pin
    lifecycle tests guard); ``kind="retained"`` — buffers registered
    inside the last :func:`memwatch` scope and STILL alive now.  Empty
    means no evidence of leaked residency."""
    out: List[dict] = []
    try:
        from . import fusion

        for rec in fusion.pin_leaks():
            out.append(dict(rec, kind="pin"))
    except Exception:
        pass
    for row in _WATCH_RETAINED:
        if row["id"] in _LEDGER:
            out.append(dict(row, kind="retained"))
    return out
